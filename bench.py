"""Benchmark: Llama training throughput, tokens/sec/chip (BASELINE metric).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

BASELINE.json ships no published numbers ("published": {}), so the
comparison point is the roofline: value / (tokens/sec/chip at 40% MFU on
this chip's peak) — i.e. vs_baseline >= 1.0 means we meet a 40%-MFU bar,
the regime well-tuned TPU LLM stacks land in.  On CPU (no TPU available)
the roofline is undefined and vs_baseline is reported against a fixed
CPU reference constant so the number is still comparable run-to-run.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from __graft_entry__ import _bench_model
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.train import data as datalib
from kubeflow_tpu.train import trainer as trainlib

WARMUP_STEPS = 3
MEASURED_STEPS = 10
WINDOWS = 3
TARGET_MFU = 0.40
CPU_REFERENCE_TPS = 2000.0  # fixed constant for CPU-only comparability


def main() -> None:
    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    if on_tpu:
        model = _bench_model()
        batch, seq = 14, 1024
    else:
        model = llamalib.tiny()
        batch, seq = 8, 128

    cfg = trainlib.TrainConfig(
        model=model,
        mesh_axes={"data": len(devices)} if len(devices) > 1 else {},
        global_batch=batch,
        seq_len=seq,
        steps=WARMUP_STEPS + WINDOWS * MEASURED_STEPS,
        warmup_steps=2,
        log_every=10_000,  # quiet
    )
    t = trainlib.Trainer(cfg, devices=devices)
    source = datalib.SyntheticLm(
        batch, seq, model.vocab_size, process_index=0, process_count=1)
    state = t.init_state()
    step_fn = t.compiled_step()

    from kubeflow_tpu.parallel import sharding as shardlib

    def put(step: int):
        return {
            k: jax.device_put(v, t.batch_sharding)
            for k, v in source.local_batch(step).items()
        }

    # Steady-state protocol: steps are enqueued asynchronously and the host
    # blocks once per measured window (matching Trainer.train's metering).
    # Synchronizing on the loss every step would serialize a full host
    # round-trip into each step — on a remote-dispatch PJRT backend that is
    # ~100ms/step of pure dispatch latency, not training throughput.
    window_times = []
    step = 0
    with shardlib.shard_context(t.mesh):
        for _ in range(WARMUP_STEPS):
            state, out = step_fn(state, put(step))
            step += 1
        # device_get, not block_until_ready: some PJRT backends (axon
        # tunnel) report ready before remote execution completes
        float(jax.device_get(out["loss"]))
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            for _ in range(MEASURED_STEPS):
                state, out = step_fn(state, put(step))
                step += 1
            float(jax.device_get(out["loss"]))
            window_times.append((time.perf_counter() - t0) / MEASURED_STEPS)

    window_times.sort()
    median = window_times[len(window_times) // 2]
    n_chips = len(devices)
    tps_chip = batch * seq / median / n_chips

    flops_tok = llamalib.flops_per_token(model, seq)
    kind = getattr(devices[0], "device_kind", "cpu").lower()
    peak = trainlib.PEAK_TFLOPS.get(kind, 0.0)
    if peak:
        target_tps = TARGET_MFU * peak * 1e12 / flops_tok
        vs_baseline = tps_chip / target_tps
    else:
        vs_baseline = tps_chip / CPU_REFERENCE_TPS

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tps_chip, 2),
        "unit": f"tokens/s/chip (model={llamalib.num_params(model)/1e6:.0f}M, "
                f"seq={seq}, {kind})",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
