"""Benchmark: Llama training throughput, tokens/sec/chip (BASELINE metric).

Line 1 (the driver's row, schema frozen): the 271M flagship at seq 1024 —
{"metric", "value", "unit", "vs_baseline"}.

Additional TPU-only rows (same schema, one JSON line each) keep the
long-context and billion-scale claims under the driver's eye every round
(round-2 verdict weak #7):
  line 2 — the same flagship at seq 4096 (flash attention's regime;
           full-recompute remat to fit HBM);
  line 3 — the 1.19B single-chip config (largest that fits 16 GiB:
           Adafactor + grad accumulation + full recompute; PERF.md).

BASELINE.json ships no published numbers ("published": {}), so the
comparison point is the roofline: value / (tokens/sec/chip at 40% MFU on
this chip's peak) — i.e. vs_baseline >= 1.0 means we meet a 40%-MFU bar,
the regime well-tuned TPU LLM stacks land in.  On CPU (no TPU available)
the roofline is undefined and vs_baseline is reported against a fixed
CPU reference constant so the number is still comparable run-to-run.
"""

from __future__ import annotations

import dataclasses
import json
import statistics
import time

import jax
import jax.numpy as jnp

from __graft_entry__ import _bench_model
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.train import data as datalib
from kubeflow_tpu.train import trainer as trainlib

WARMUP_STEPS = 3
MEASURED_STEPS = 10
WINDOWS = 3
TARGET_MFU = 0.40
CPU_REFERENCE_TPS = 2000.0  # fixed constant for CPU-only comparability


def measure(model, batch, seq, *, windows=WINDOWS, steps=MEASURED_STEPS,
            **train_kw) -> float:
    """Median-window tokens/sec/chip for one config (async dispatch, one
    host sync per window — per-step syncs are ~100ms each on the
    remote-dispatch PJRT backend and measure the tunnel, not the chip)."""
    devices = jax.devices()
    cfg = trainlib.TrainConfig(
        model=model,
        mesh_axes={"data": len(devices)} if len(devices) > 1 else {},
        global_batch=batch,
        seq_len=seq,
        steps=WARMUP_STEPS + windows * steps,
        warmup_steps=2,
        log_every=10_000,  # quiet
        **train_kw,
    )
    t = trainlib.Trainer(cfg, devices=devices)
    source = datalib.SyntheticLm(
        batch, seq, model.vocab_size, process_index=0, process_count=1)
    state = t.init_state()
    step_fn = t.compiled_step()

    from kubeflow_tpu.parallel import sharding as shardlib

    def put(step: int):
        return {
            k: jax.device_put(v, t.batch_sharding)
            for k, v in source.local_batch(step).items()
        }

    window_times = []
    step = 0
    with shardlib.shard_context(t.mesh):
        for _ in range(WARMUP_STEPS):
            state, out = step_fn(state, put(step))
            step += 1
        # device_get, not block_until_ready: some PJRT backends (axon
        # tunnel) report ready before remote execution completes
        float(jax.device_get(out["loss"]))
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(steps):
                state, out = step_fn(state, put(step))
                step += 1
            float(jax.device_get(out["loss"]))
            window_times.append((time.perf_counter() - t0) / steps)

    # true median (even window counts average the middle two — picking
    # index len//2 would report the worse window, a different statistic
    # than the odd-window rows)
    return batch * seq / statistics.median(window_times) / len(jax.devices())


def report(metric: str, model, batch, seq, tps_chip: float) -> None:
    flops_tok = llamalib.flops_per_token(model, seq)
    kind = getattr(jax.devices()[0], "device_kind", "cpu").lower()
    peak = trainlib.PEAK_TFLOPS.get(kind, 0.0)
    if peak:
        target_tps = TARGET_MFU * peak * 1e12 / flops_tok
        vs_baseline = tps_chip / target_tps
    else:
        vs_baseline = tps_chip / CPU_REFERENCE_TPS
    print(json.dumps({
        "metric": metric,
        "value": round(tps_chip, 2),
        "unit": f"tokens/s/chip (model={llamalib.num_params(model)/1e6:.0f}M, "
                f"seq={seq}, {kind})",
        "vs_baseline": round(vs_baseline, 4),
    }), flush=True)


PROBE_TIMEOUT_S = 120.0  # generous for a healthy chip; bounds a dead one


def _devices_or_skip():
    """jax.devices() with graceful degradation (BENCH_r05 regression: a
    registered-but-unreachable TPU/axon plugin crashed the whole bench
    with rc=1 and an unparseable traceback — and its init can BLOCK for
    minutes before failing).  Order: probe the default backend in a
    short-lived subprocess so a dead plugin costs a bounded timeout, not
    a hang; fall back to CPU (the config update restricts platform
    discovery, so the retry cannot re-trip the dead plugin); and if even
    CPU is unusable, ONE parseable "skipped" row in the driver's schema
    and exit 0 — a bench that cannot run must record that fact, not a
    stack trace."""
    import os
    import subprocess
    import sys

    err = "default backend probe failed"
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        # probe for ANY non-cpu platform selection (pinned or default):
        # the subprocess inherits the env, so a pinned-but-dead plugin
        # still fails inside the bounded probe, never in-process.  On a
        # healthy accelerator this double-inits the backend (~seconds) —
        # accepted: the bench itself runs for minutes, and the hang this
        # guards against cost a whole BENCH round (r05)
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, timeout=PROBE_TIMEOUT_S, text=True)
            ok = probe.returncode == 0
            err = (probe.stderr or "").strip().splitlines()[-1:] or [err]
            err = err[0]
        except subprocess.TimeoutExpired:
            ok = False
            err = f"backend init exceeded {PROBE_TIMEOUT_S:.0f}s"
        if not ok:
            jax.config.update("jax_platforms", "cpu")
    try:
        return jax.devices()
    except RuntimeError as e:
        err = str(e)
    try:
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()
    except Exception:  # noqa: BLE001 — no backend at all
        print(json.dumps({
            "metric": "llama_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": f"skipped: no usable jax backend ({err})"[:200],
            "vs_baseline": 0.0,
            "skipped": True,
        }), flush=True)
        raise SystemExit(0)


def main() -> None:
    on_tpu = _devices_or_skip()[0].platform == "tpu"

    # -- line 1: the frozen driver row ----------------------------------
    if on_tpu:
        model, batch, seq = _bench_model(), 14, 1024
    else:
        model, batch, seq = llamalib.tiny(), 8, 128
    tps = measure(model, batch, seq)
    report("llama_train_tokens_per_sec_per_chip", model, batch, seq, tps)

    if not on_tpu:
        return

    # -- line 2: long-context row (seq 4096, flash + full recompute) ----
    model4k = dataclasses.replace(
        _bench_model(), max_seq_len=4096, remat_policy="nothing")
    tps = measure(model4k, 12, 4096, windows=2, steps=5)
    report("llama_train_tokens_per_sec_per_chip_seq4096",
           model4k, 12, 4096, tps)

    # -- line 3: billion-scale single-chip row --------------------------
    model1b = llamalib.llama_1b()
    tps = measure(model1b, 16, 2048, windows=2, steps=5,
                  accum_steps=8, optimizer="adafactor")
    report("llama1b_train_tokens_per_sec_per_chip", model1b, 16, 2048, tps)

    # -- line 4: SERVING row (r5) — continuous-batching decode under the
    # driver's eye.  Guarded: a serving failure must never take down the
    # training headline rows above.
    try:
        bench_serving()
    except Exception as e:  # noqa: BLE001 — report, don't crash the bench
        print(json.dumps({
            "metric": "llama_continuous_serving_tokens_per_sec",
            "value": 0.0, "unit": f"SERVING ROW FAILED: {e}",
            "vs_baseline": 0.0}), flush=True)


def bench_serving() -> None:
    """Continuous-engine decode throughput, 271M, 8 slots, chunk 16 —
    the steady-state burst from scripts/serving_bench.py distilled to a
    driver row.  vs_baseline compares against the per-token HBM
    roofline at full pool occupancy (weights + attended KV per decoded
    token over 819 GB/s) — the tunnel's dispatch floor keeps the
    measured value well under it; a directly-attached chip closes in."""
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    cfg = _bench_model()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    import numpy as np

    rng = np.random.default_rng(0)
    prompts = rng.integers(
        1, cfg.vocab_size, size=(8, 128)).tolist()
    eng = ContinuousEngine(cfg, params, num_slots=8, decode_chunk=16,
                           pipeline_depth=3, prefix_cache=False)
    try:
        eng.warmup([(8, 128), (1, 128)])
        prime = [eng.submit(p, max_new_tokens=16) for p in prompts]
        for r in prime:
            r.wait(300)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=64) for p in prompts]
        for r in reqs:
            r.wait(300)
        dt = time.perf_counter() - t0
    finally:
        eng.stop()
    tps = 8 * 64 / dt
    # decode roofline: every token streams the weight bytes (batched
    # over live slots) + its attended KV window (~192 positions here)
    wbytes = llamalib.num_params(cfg) * 4  # f32 params as initialized
    kvbytes = (2 * cfg.num_layers * 256 * cfg.num_kv_heads
               * cfg.head_dim * 4)
    roofline = 8 / ((wbytes + 8 * kvbytes) / 819e9)
    print(json.dumps({
        "metric": "llama_continuous_serving_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s (271M, 8 slots, 64 new tokens, chunk 16, "
                "continuous batching; roofline-limited by the tunnel "
                "dispatch floor)",
        "vs_baseline": round(tps / roofline, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
