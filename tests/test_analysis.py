"""Platform analyzer (kubeflow_tpu/analysis): lint rules + ratchet + auditors.

Three layers, matching the package:

- per-rule FIXTURE tests: one true positive and one near-miss false
  positive per rule, linted as tmp files placed under the path prefixes
  the rules scope to;
- the RATCHET: the whole repo lints with zero findings above
  ``analysis/baseline.json`` — this is the tier-1 gate every future PR
  inherits (a new host sync / lock inversion / silent swallow fails
  here, not in production);
- the RUNTIME auditors: RecompileGuard counting real jit cache misses
  and LockAudit catching real acquisition-order inversions.

Pure-stdlib imports only at module level (plus jax inside the guard
test) so this file stays cheap — it runs first alphabetically.
"""

import os
import threading

import pytest

from kubeflow_tpu.analysis import astlint
from kubeflow_tpu.analysis.runtime import (
    BlockLedger,
    LockAudit,
    RecompileCounter,
    recompile_guard,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code: str, rules,
                 rel="kubeflow_tpu/serving/_fixture.py"):
    """Lint one synthetic module placed at ``rel`` under a tmp root (the
    path matters: lock-order scopes to platform dirs)."""
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(code)
    report = astlint.run_lint(str(tmp_path), paths=[str(target)],
                              rules=list(rules))
    return report.findings


class TestHostSyncRule:
    TP = """
import jax
import numpy as np

class FooEngine:
    def _loop(self):
        self._step()

    def _step(self):
        x = self._fetch()
        return x.item()

    def _fetch(self):
        return jax.device_get(self.buf)
"""

    def test_true_positive_via_reachability(self, tmp_path):
        found = lint_snippet(tmp_path, self.TP, ["host-sync-in-dispatch"])
        kinds = {f.message for f in found}
        assert any(".item()" in m for m in kinds)
        assert any("device_get" in m for m in kinds)
        # reachability names the offending scopes
        assert {f.scope for f in found} == {"FooEngine._step",
                                            "FooEngine._fetch"}

    def test_near_miss_unreachable_helper(self, tmp_path):
        code = """
import jax

class FooEngine:
    def _loop(self):
        return 1

    def debug_dump(self):
        # host sync, but NOT reachable from the dispatch loop
        return jax.device_get(self.buf)

class LoopHelper:
    def _loop(self):
        return jax.device_get(self.buf)
"""
        # LoopHelper's class name doesn't end in Engine -> no roots
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []

    def test_pragma_silences(self, tmp_path):
        code = """
import jax

class FooEngine:
    def _process(self):
        # analysis: ok host-sync-in-dispatch — the fetch boundary
        return jax.device_get(self.buf)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []

    def test_allocator_methods_are_roots(self, tmp_path):
        """ISSUE 6 satellite: the paged-KV allocator runs ON the
        scheduler's dispatch path, so EVERY ``*Allocator`` method is a
        root — a ``.item()`` on the free list is flagged even though no
        ``_loop``/``_admit`` exists in the file."""
        code = """
import numpy as np

class BlockAllocator:
    def alloc(self, n):
        return int(self._refs.sum().item())

    def tables(self, bt):
        return np.asarray(bt)
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_palloc.py")
        scopes = {f.scope for f in found}
        assert "BlockAllocator.alloc" in scopes
        assert "BlockAllocator.tables" in scopes

    def test_allocator_near_miss_other_class(self, tmp_path):
        code = """
import numpy as np

class BlockTableHelper:
    def tables(self, bt):
        return np.asarray(bt)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_palloc.py") == []

    def test_traffic_plane_methods_are_roots(self, tmp_path):
        """ISSUE 9 satellite: token-bucket/queue accounting runs on
        router/HTTP threads and the engine's admission hook — a device
        fetch or blocking socket in a ``*TrafficPlane``/``*Admission``
        method stalls every live request, so EVERY method is a root."""
        code = """
import numpy as np

class QosTrafficPlane:
    def acquire(self, tenant):
        return self._charge(tenant)

    def _charge(self, tenant):
        return float(self._tokens.sum())

class PolicyAdmission:
    def admit(self, req, sock):
        sock.sendall(b"ping")
        return np.asarray(self._live)

class EnginePreemptor:
    def _step(self):
        return self._victim.tokens.tolist()
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_traffic.py")
        scopes = {f.scope for f in found}
        assert "QosTrafficPlane._charge" in scopes
        assert "PolicyAdmission.admit" in scopes
        assert "EnginePreemptor._step" in scopes
        assert any("socket" in f.message for f in found)

    def test_traffic_near_miss_other_class(self, tmp_path):
        code = """
import numpy as np

class TrafficReport:
    def render(self):
        return np.asarray(self._rows)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_traffic.py") == []

    def test_resizer_and_reshard_classes_rooted(self, tmp_path):
        """ISSUE 10 satellite (the PR 8 ``*Preemptor`` lesson): every
        method of a ``*Resizer``/``*Reshard`` class is a lint root —
        elastic-resize orchestration touches scheduler state, so an
        undeclared device fetch or blocking socket there must surface,
        pragma'd with a reason or moved off-thread."""
        code = """
import jax

class GangResizer:
    def _copy_weights(self):
        return jax.device_get(self._params)

class WeightReshard:
    def _stream(self):
        self._sock.sendall(self._frame)
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_resize.py")
        scopes = {f.scope for f in found}
        assert "GangResizer._copy_weights" in scopes
        assert "WeightReshard._stream" in scopes
        assert any("socket" in f.message for f in found)

    def test_resizer_near_miss_other_class(self, tmp_path):
        """Prefix lookalikes (``Reshard*``/``Resize*`` without the
        suffix) are helper/plan classes, not the orchestrator — clean."""
        code = """
import numpy as np

class ReshardPlanner:
    def table(self):
        return np.asarray(self._rows)

class ResizeReport:
    def render(self):
        return self._latency.tolist()
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_resize.py") == []

    def test_tier_spill_hibernate_classes_rooted(self, tmp_path):
        """ISSUE 12 satellite: the KV-tier classes join the walk —
        ``*BlockPool`` by suffix (its match/take run ON the scheduler
        thread at admission), anything named *Tier*/*Spill*/*Hibernat*
        by substring (spill stores, hibernation orchestrators).  An
        UNdeclared device fetch or blocking socket in tier bookkeeping
        must surface: spill I/O never runs on the scheduler — the
        mailbox seam is the only crossing."""
        code = """
import jax
import numpy as np

class HostBlockPool:
    def match(self, arr):
        return int(self._depths.max())

class KvSpillStore:
    def write(self, snap):
        return [np.asarray(x) for x in snap]

class SessionHibernator:
    def pump(self):
        return jax.device_get(self._leaves)
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_tier.py")
        scopes = {f.scope for f in found}
        assert "HostBlockPool.match" in scopes
        assert "KvSpillStore.write" in scopes
        assert "SessionHibernator.pump" in scopes

    def test_tier_near_miss_other_class(self, tmp_path):
        """Lookalikes without the tier vocabulary (or the BlockPool
        suffix) stay unrooted — and a pragma'd tier site is a declared
        boundary, not a finding."""
        code = """
import numpy as np

class PoolBlocks:
    def render(self):
        return np.asarray(self._rows)

class HostBlockPoolStats:
    def rows(self):
        return self._counts.tolist()

class WarmSpillStore:
    def write(self, snap):
        # analysis: ok host-sync-in-dispatch — host wire bytes, worker thread
        return [np.asarray(x) for x in snap]
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_tier.py") == []

    def test_storage_tier_faults_paired(self):
        """The ISSUE 12 chaos faults (spill_torn / spill_kill_mid_write
        / tier_io_stall) must be seen PAIRED by the fault-pairing
        analyzer: declared FaultKind members with both a builder and a
        ``due_*`` consumer in chaos/plan.py."""
        import kubeflow_tpu.chaos.plan as plan_mod

        report = astlint.run_lint(REPO_ROOT, paths=[plan_mod.__file__],
                                  rules=["fault-pairing"])
        bad = [f for f in report.findings
               if "SPILL" in f.message.upper()
               or "TIER_IO" in f.message.upper()]
        assert bad == [], bad

    def test_blocking_socket_send_in_scheduler_flagged(self, tmp_path):
        """ISSUE 8 satellite: a blocking socket send reachable from an
        engine's scheduler roots stalls every live request for a
        network round trip — the migrate path must run off-thread."""
        code = """
class FooEngine:
    def _loop(self):
        while True:
            self._stream_block()

    def _stream_block(self):
        self.sock.sendall(self._next_frame())
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"])
        assert len(found) == 1
        assert "socket" in found[0].message
        assert found[0].scope == "FooEngine._stream_block"

    def test_blocking_socket_near_miss_worker_thread(self, tmp_path):
        """sendall in a method NOT reachable from scheduler roots (the
        migration worker pattern) — and in a non-Engine server class —
        is clean."""
        code = """
import socket

class FooEngine:
    def _loop(self):
        self._mailbox.get_nowait()

    def _migration_worker(self):
        # runs on its own thread; never called from _loop
        self.sock.sendall(b"frame")

class KvMigrationServer:
    def _serve_one(self, c):
        c.sendall(b"ack")
        return socket.create_connection(("h", 1))
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []


class TestJitInLoopRule:
    def test_true_positive(self, tmp_path):
        code = """
import jax

def bad(fns):
    progs = []
    for f in fns:
        progs.append(jax.jit(f))
    return progs

def also_bad(buckets):
    while buckets:
        p = make_decode_program(buckets.pop())
"""
        found = lint_snippet(tmp_path, code, ["jit-in-loop"])
        assert len(found) == 2
        assert {f.scope for f in found} == {"bad", "also_bad"}

    def test_near_miss_cached_getter(self, tmp_path):
        code = """
import jax

def good(fns):
    cache = {}
    def getter(k):
        # construction inside a def inside nothing-loopy: fine
        if k not in cache:
            cache[k] = jax.jit(fns[k])
        return cache[k]
    out = []
    for k in range(8):
        out.append(getter(k)(k))  # CALLING a cached program is fine
    return out
"""
        assert lint_snippet(tmp_path, code, ["jit-in-loop"]) == []


class TestLockOrderRule:
    def test_cycle_true_positive(self, tmp_path):
        code = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with b_lock:
        with a_lock:
            pass
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "lock-order cycle" in found[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        code = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with a_lock:
        with b_lock:
            pass
"""
        assert lint_snippet(tmp_path, code, ["lock-order"]) == []

    def test_blocking_under_lock(self, tmp_path):
        code = """
import threading
import time

class Pump:
    def run(self):
        with self._lock:
            time.sleep(1.0)
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "time.sleep" in found[0].message
        assert "Pump._lock" in found[0].message

    def test_near_miss_sleep_in_nested_def(self, tmp_path):
        code = """
import threading
import time

class Pump:
    def run(self):
        with self._lock:
            def later():
                time.sleep(1.0)  # runs on another thread, NOT under lock
            self._spawn(later)
"""
        assert lint_snippet(tmp_path, code, ["lock-order"]) == []

    def test_interprocedural_cycle_one_level(self, tmp_path):
        code = """
import threading

class Gang:
    def pub(self):
        with self._lock:
            self._flush()

    def _flush(self):
        with self._sendgate:
            pass

    def other(self):
        with self._sendgate:
            with self._lock:
                pass
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "cycle" in found[0].message

    def test_outside_scoped_dirs_ignored(self, tmp_path):
        code = """
import threading, time
class P:
    def run(self):
        with self._lock:
            time.sleep(1)
"""
        assert lint_snippet(tmp_path, code, ["lock-order"],
                            rel="kubeflow_tpu/models/_fixture.py") == []


class TestSwallowedExceptionRule:
    def test_true_positive(self, tmp_path):
        code = """
def f():
    try:
        risky()
    except Exception:  # noqa: BLE001
        pass
"""
        found = lint_snippet(tmp_path, code, ["swallowed-exception"])
        assert len(found) == 1
        # a bare noqa without a reason is NOT a justification
        assert found[0].scope == "f"

    def test_near_misses(self, tmp_path):
        code = """
import logging
log = logging.getLogger(__name__)

def logs():
    try:
        risky()
    except Exception:  # noqa: BLE001
        log.debug("risky failed", exc_info=True)

def reraises():
    try:
        risky()
    except Exception as e:
        raise RuntimeError("wrapped") from e

def justified():
    try:
        risky()
    except Exception:  # noqa: BLE001 — db unavailable: retry next pass
        pass

def pragma_ok():
    try:
        risky()
    # analysis: ok swallowed-exception — probing an optional backend
    except Exception:
        pass

def narrow():
    try:
        risky()
    except ValueError:
        pass
"""
        assert lint_snippet(tmp_path, code, ["swallowed-exception"]) == []


class TestUnsafePickleRule:
    def test_true_positive(self, tmp_path):
        code = """
import pickle

def recv(sock):
    return pickle.loads(sock.recv(4096))
"""
        found = lint_snippet(tmp_path, code, ["unsafe-pickle"])
        assert len(found) == 1
        assert "arbitrary code execution" in found[0].message

    def test_near_miss_dumps_and_allowlist(self, tmp_path):
        code = """
import pickle

def send(obj):
    return pickle.dumps(obj)
"""
        assert lint_snippet(tmp_path, code, ["unsafe-pickle"]) == []
        # the real allowlisted ingestion point stays clean
        gang = os.path.join(REPO_ROOT, "kubeflow_tpu", "serving", "gang.py")
        report = astlint.run_lint(REPO_ROOT, paths=[gang],
                                  rules=["unsafe-pickle"])
        assert report.findings == []


class TestNondaemonThreadRule:
    def test_true_positive(self, tmp_path):
        code = """
import threading

def start():
    t = threading.Thread(target=work)
    t.start()
"""
        found = lint_snippet(tmp_path, code, ["nondaemon-thread"])
        assert len(found) == 1

    def test_near_misses(self, tmp_path):
        code = """
import threading

def kwarg():
    threading.Thread(target=work, daemon=True).start()

def attr():
    t = threading.Thread(target=work)
    t.daemon = True
    t.start()

def pragma():
    # analysis: ok nondaemon-thread — must survive main for drain
    t = threading.Thread(target=work)
    t.start()
"""
        assert lint_snippet(tmp_path, code, ["nondaemon-thread"]) == []


class TestThreadAffinityRule:
    """ISSUE 11 tentpole: scheduler-owned state mutates only on the
    scheduler thread (or through the mailbox seam)."""

    def test_public_api_write_flagged(self, tmp_path):
        code = """
class FooEngine:
    def _loop(self):
        self._admit()

    def _admit(self):
        self._waiting.sort()

    def submit(self, req):
        self._waiting.append(req)
"""
        found = lint_snippet(tmp_path, code, ["thread-affinity"])
        assert len(found) == 1
        assert found[0].scope == "FooEngine.submit"
        assert "_waiting" in found[0].message
        assert "mailbox" in found[0].message

    def test_spawned_thread_write_flagged(self, tmp_path):
        code = """
import threading

class FooEngine:
    def _loop(self):
        pass

    def _start_worker(self):
        threading.Thread(target=self._worker, daemon=True).start()

    def _worker(self):
        self._slots[0] = None
"""
        found = lint_snippet(tmp_path, code, ["thread-affinity"])
        assert {f.scope for f in found} == {"FooEngine._worker"}
        assert "_slots" in found[0].message

    def test_mailbox_post_is_clean(self, tmp_path):
        """The blessed seam: external entries that only POST to the
        queue never touch owned state — the scheduler-side servicer
        (reachable from _loop) does, and that classifies as scheduler."""
        code = """
class FooEngine:
    def _loop(self):
        self._service()

    def _service(self):
        kind, a = self._migrate_q.get_nowait()
        self._waiting.append(a)

    def submit(self, req):
        self._migrate_q.put(("admit", req))
"""
        assert lint_snippet(tmp_path, code, ["thread-affinity"]) == []

    def test_public_entry_also_called_by_scheduler_flagged(self, tmp_path):
        """Scheduler reachability does not EXEMPT a public entry: a
        method the scheduler calls internally that is also invokable
        cross-thread writes on two threads."""
        code = """
class FooEngine:
    def _loop(self):
        self.flush()

    def flush(self):
        self._waiting.clear()
"""
        found = lint_snippet(tmp_path, code, ["thread-affinity"])
        assert len(found) == 1
        assert found[0].scope == "FooEngine.flush"
        assert "ALSO scheduler-reachable" in found[0].message

    def test_shared_reachability_flagged(self, tmp_path):
        """A helper reachable from BOTH the scheduler and a public
        entry runs on two threads — the write is the race."""
        code = """
class FooEngine:
    def _loop(self):
        self._retire(0)

    def _retire(self, slot):
        self._slots[slot] = None

    def evict(self, slot):
        self._retire(slot)
"""
        found = lint_snippet(tmp_path, code, ["thread-affinity"])
        assert len(found) == 1
        assert found[0].scope == "FooEngine._retire"
        assert "ALSO scheduler-reachable" in found[0].message

    def test_lifecycle_and_reads_are_clean(self, tmp_path):
        code = """
class FooEngine:
    def __init__(self):
        self._waiting = []
        self._slots = [None] * 4

    def stop(self):
        self._waiting.clear()

    def stats(self):
        return {"queue_depth": len(self._waiting)}
"""
        assert lint_snippet(tmp_path, code, ["thread-affinity"]) == []

    def test_foreign_write_flagged_and_follow_carved_out(self, tmp_path):
        code = """
class Orchestrator:
    def cutover(self, engine):
        engine._slots[0] = None

def follow(engine, channel):
    engine._pool_cache = channel.next()
"""
        found = lint_snippet(tmp_path, code, ["thread-affinity"])
        assert len(found) == 1
        assert found[0].scope == "Orchestrator.cutover"
        assert "foreign write" in found[0].message

    def test_pragma_silences(self, tmp_path):
        code = """
class FooEngine:
    def _loop(self):
        pass

    def drain(self):
        # analysis: ok thread-affinity — runs post-join in shutdown
        self._waiting.clear()
"""
        assert lint_snippet(tmp_path, code, ["thread-affinity"]) == []

    def test_non_engine_class_out_of_scope(self, tmp_path):
        code = """
class Router:
    def submit(self, req):
        self._waiting.append(req)
"""
        assert lint_snippet(tmp_path, code, ["thread-affinity"]) == []

    def test_outside_serving_ignored(self, tmp_path):
        code = """
class FooEngine:
    def _loop(self):
        pass

    def submit(self, req):
        self._waiting.append(req)
"""
        assert lint_snippet(tmp_path, code, ["thread-affinity"],
                            rel="kubeflow_tpu/hpo/_fixture.py") == []


class TestOpTableRule:
    """ISSUE 11 tentpole: leader-publish / follower-replay completeness."""

    DRIFTED = """
def leader(ch, toks):
    ch.publish(("alpha", toks))
    ch.publish(("beta", toks))

def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "alpha":
            continue
        raise RuntimeError(f"unknown gang op {op!r}")
"""

    def test_seeded_drift_published_without_arm(self, tmp_path):
        """The acceptance fixture: a published op whose follow() arm
        was deleted MUST be caught."""
        found = lint_snippet(tmp_path, self.DRIFTED, ["op-table"])
        assert len(found) == 1
        assert "`beta`" in found[0].message
        assert "no follower replay arm" in found[0].message

    def test_dead_arm_flagged(self, tmp_path):
        code = """
def leader(ch, toks):
    ch.publish(("alpha", toks))

def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "alpha":
            continue
        elif op == "ghost":
            continue
"""
        found = lint_snippet(tmp_path, code, ["op-table"])
        assert len(found) == 1
        assert "dead replay arm" in found[0].message
        assert "`ghost`" in found[0].message

    def test_cross_file_pairing(self, tmp_path):
        """resize.py publishes, gang.py replays — the table is the
        UNION across the serving layer."""
        pub = """
def orchestrate(channel):
    channel.publish(("resize", {}))
"""
        arm = """
def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "resize":
            continue
"""
        root = tmp_path
        a = root / "kubeflow_tpu/serving/_rz.py"
        b = root / "kubeflow_tpu/serving/_gg.py"
        a.parent.mkdir(parents=True, exist_ok=True)
        a.write_text(pub)
        b.write_text(arm)
        report = astlint.run_lint(str(root), paths=[str(a), str(b)],
                                  rules=["op-table"])
        assert report.findings == []

    def test_unrelated_op_local_ignored(self, tmp_path):
        """A local named ``op`` outside a replay loop (no ``op =
        msg[0]`` binding) contributes no arms."""
        code = """
def eval_condition(condition):
    for op in ("==", "!="):
        if op == "==":
            return True
"""
        assert lint_snippet(tmp_path, code, ["op-table"]) == []

    def test_pragma_silences_leader_only_op(self, tmp_path):
        code = """
def leader(ch, blob):
    ch.publish(("debug_dump", blob))  # analysis: ok op-table — leader-only

def follow(channel):
    msg = channel.next()
    op = msg[0]
    if op == "stop":
        return
    ch2 = None

def leader2(ch):
    ch.publish(("stop",))
"""
        assert lint_snippet(tmp_path, code, ["op-table"]) == []

    def test_pragma_on_any_site_silences_the_op(self, tmp_path):
        """The table ENTRY is the unit of intent: two files publish the
        same leader-only op and the pragma sits on the site sorted
        LAST — the entry must still be silenced (the old anchor-first
        bookkeeping ignored every pragma but the first site's)."""
        pub_a = """
def leader(ch, blob):
    ch.publish(("debug_dump", blob))
"""
        pub_b = """
def mirror(ch, blob):
    ch.publish(("debug_dump", blob))  # analysis: ok op-table — leader-only

def follow(channel):
    msg = channel.next()
    op = msg[0]
    if op == "keep":
        return

def leader2(ch):
    ch.publish(("keep",))
"""
        root = tmp_path
        a = root / "kubeflow_tpu/serving/_aa.py"  # sorts BEFORE _zz
        b = root / "kubeflow_tpu/serving/_zz.py"
        a.parent.mkdir(parents=True, exist_ok=True)
        a.write_text(pub_a)
        b.write_text(pub_b)
        report = astlint.run_lint(str(root), paths=[str(a), str(b)],
                                  rules=["op-table"])
        assert report.findings == []

    def test_path_scoped_lint_sees_whole_table(self):
        """The pre-commit fast path — linting ONE changed file — must
        not report cross-file pairings as drift: resize.py alone
        publishes resize/resize_abort/resize_commit whose arms live in
        gang.py, and the table is built from the whole scope."""
        rz = os.path.join(REPO_ROOT, "kubeflow_tpu", "serving",
                          "resize.py")
        report = astlint.run_lint(REPO_ROOT, paths=[rz],
                                  rules=["op-table"])
        assert report.findings == []
        # same shape for the chaos pairing: net.py alone consumes
        # nothing plan.py doesn't produce
        net = os.path.join(REPO_ROOT, "kubeflow_tpu", "chaos", "net.py")
        report = astlint.run_lint(REPO_ROOT, paths=[net],
                                  rules=["fault-pairing"])
        assert report.findings == []

    def test_real_gang_protocol_is_complete(self):
        """The live contract: every op gang.py/resize.py publishes has
        a follow() arm and vice versa (the rule sees 24 real ops)."""
        paths = [os.path.join(REPO_ROOT, "kubeflow_tpu", "serving", f)
                 for f in ("gang.py", "resize.py")]
        report = astlint.run_lint(REPO_ROOT, paths=paths,
                                  rules=["op-table"])
        assert report.findings == []
        from kubeflow_tpu.analysis import rules_protocol as rp

        ctx = astlint.parse_paths(REPO_ROOT, paths)
        pub = {op for pf in ctx.files.values()
               for op, _ in rp._published_ops(pf)}
        assert len(pub) >= 20  # the table is genuinely populated


class TestFaultPairingRule:
    COMPLETE = """
class FaultKind:
    CRASH = "crash"

class Fault:
    def __init__(self, kind, at=0.0):
        self.kind = kind

class Plan:
    def crash(self):
        self.faults.append(Fault(FaultKind.CRASH))

    def due(self):
        return [f for f in self.faults if f.kind == FaultKind.CRASH]
"""

    def test_unconsumed_kind_flagged(self, tmp_path):
        code = self.COMPLETE.replace(
            'CRASH = "crash"', 'CRASH = "crash"\n    GHOST = "ghost"'
        ).replace(
            "def due(self):",
            "def ghost(self):\n"
            "        self.faults.append(Fault(FaultKind.GHOST))\n\n"
            "    def due(self):")
        found = lint_snippet(tmp_path, code, ["fault-pairing"],
                             rel="kubeflow_tpu/chaos/_fixture.py")
        assert len(found) == 1
        assert "GHOST" in found[0].message
        assert "never fire" in found[0].message

    def test_dead_actuator_arm_flagged(self, tmp_path):
        code = self.COMPLETE.replace(
            "if f.kind == FaultKind.CRASH",
            "if f.kind in (FaultKind.CRASH, FaultKind.PHANTOM)")
        found = lint_snippet(tmp_path, code, ["fault-pairing"],
                             rel="kubeflow_tpu/chaos/_fixture.py")
        assert len(found) == 1
        assert "PHANTOM" in found[0].message

    def test_paired_is_clean_and_scope_is_chaos_only(self, tmp_path):
        assert lint_snippet(tmp_path, self.COMPLETE, ["fault-pairing"],
                            rel="kubeflow_tpu/chaos/_fixture.py") == []
        # the same drifted code OUTSIDE chaos/ is not this rule's business
        drifted = self.COMPLETE.replace(
            'CRASH = "crash"', 'CRASH = "crash"\n    GHOST = "ghost"')
        assert lint_snippet(tmp_path, drifted, ["fault-pairing"],
                            rel="kubeflow_tpu/serving/_fixture.py") == []

    def test_real_fault_plan_is_paired(self):
        plan = os.path.join(REPO_ROOT, "kubeflow_tpu", "chaos", "plan.py")
        report = astlint.run_lint(REPO_ROOT, paths=[plan],
                                  rules=["fault-pairing"])
        assert report.findings == []


class TestMetricsContractRule:
    """metrics-contract (ISSUE 13 satellite): serving stats() keys
    must render to valid Prometheus names (the exporter splices them
    into kft_engine_<key>); the monotonic-counter half is runtime
    (audit_stats_pair, pinned in test_observability.py)."""

    def test_bad_key_in_dict_literal_flagged(self, tmp_path):
        code = """
class FooEngine:
    def stats(self):
        return {"tokens_emitted": 1, "kv-blocks.free": 2}
"""
        found = lint_snippet(tmp_path, code, ["metrics-contract"])
        assert len(found) == 1
        assert "kv-blocks.free" in found[0].message

    def test_bad_key_via_subscript_and_setdefault(self, tmp_path):
        code = """
class FooEngine:
    def stats(self):
        out = {}
        out["queue depth"] = 1
        out.setdefault("spec.rate", 0)
        return out
"""
        found = lint_snippet(tmp_path, code, ["metrics-contract"])
        assert {"queue depth" in f.message or "spec.rate" in f.message
                for f in found} == {True}
        assert len(found) == 2

    def test_clean_stats_and_scope(self, tmp_path):
        code = """
class FooEngine:
    def stats(self):
        out = {"tokens_emitted": 1, "kv_blocks_free": 2}
        out["queue_depth"] = 0
        return out

    def not_stats(self):
        return {"kv-blocks.free": 2}
"""
        assert lint_snippet(tmp_path, code, ["metrics-contract"]) == []
        # outside serving/ is not this rule's business
        bad = 'class E:\n    def stats(self):\n        return {"a-b": 1}\n'
        assert lint_snippet(tmp_path, bad, ["metrics-contract"],
                            rel="kubeflow_tpu/hpo/_fixture.py") == []

    def test_pragma_silences_with_reason(self, tmp_path):
        code = """
class FooEngine:
    def stats(self):
        # analysis: ok metrics-contract — legacy dashboard key
        return {"kv-blocks.free": 2}
"""
        assert lint_snippet(tmp_path, code, ["metrics-contract"]) == []

    def test_real_serving_stats_are_clean(self):
        paths = [os.path.join(REPO_ROOT, "kubeflow_tpu", "serving", f)
                 for f in ("continuous.py", "traffic.py", "trace.py",
                           "paged.py", "gang.py")]
        report = astlint.run_lint(REPO_ROOT, paths=paths,
                                  rules=["metrics-contract"])
        assert report.findings == []


class TestLockGraphCoverage:
    """ISSUE 11 satellite: resize.py/traffic.py's PR 8/9 locks and
    Conditions are IN the nesting graph, and it stays acyclic."""

    def test_cv_suffix_is_lockish(self, tmp_path):
        """``_ack_cv`` (resize.py's reshard Condition) now matches the
        lexical lock matcher — a blocking call under it is seen."""
        code = """
import threading
import time

class ReshardServer:
    def run(self):
        with self._ack_cv:
            time.sleep(1.0)
"""
        found = lint_snippet(tmp_path, code, ["lock-order"],
                             rel="kubeflow_tpu/serving/_rz.py")
        assert len(found) == 1
        assert "ReshardServer._ack_cv" in found[0].message

    def test_repo_lock_graph_acyclic_and_covers_new_modules(self):
        from kubeflow_tpu.analysis.rules_locks import (
            _iter_with_locks,
            collect_lock_graph,
            find_cycles,
        )

        ctx = astlint.parse_paths(REPO_ROOT, astlint.discover(REPO_ROOT))
        edges, _blocking = collect_lock_graph(ctx)
        assert find_cycles(edges) == []
        # the scan actually SEES the PR 8/9 synchronization: resize.py's
        # _ack_cv Condition and traffic.py's plane lock register as
        # with-acquisitions
        rz = ctx.files["kubeflow_tpu/serving/resize.py"]
        tf = ctx.files["kubeflow_tpu/serving/traffic.py"]
        rz_locks = {name for name, _ in _iter_with_locks(rz)}
        tf_locks = {name for name, _ in _iter_with_locks(tf)}
        assert any("_ack_cv" in n for n in rz_locks), rz_locks
        assert any("_lock" in n or "cond" in n for n in tf_locks), tf_locks


class TestRatchet:
    """The tier-1 gate: the repo must lint clean against its baseline."""

    def test_repo_has_no_new_findings(self):
        report = astlint.run_lint(REPO_ROOT)
        baseline = astlint.load_baseline(astlint.baseline_path(REPO_ROOT))
        new = astlint.compare_to_baseline(report, baseline)
        assert new == [], (
            "NEW platform-lint findings above analysis/baseline.json:\n"
            + "\n".join(f"  {f}" for f in new)
            + "\nFix them, pragma them with a reason (# analysis: ok "
            "<rule> — why), or for reviewed debt re-freeze with "
            "`python -m kubeflow_tpu.analysis --update-baseline`.")

    def test_baseline_shrank_from_prefix_count(self):
        """The rules landed with the debt burned down, not frozen: 33
        findings pre-fix at PR 3 (18 swallowed-exception, 11 host-sync,
        4 lock-order blocking-under-lock), <= 8 frozen after; ISSUE 11
        justified the last 4 sweep-recorder sites (`# noqa: BLE001 —
        <reason>`), so the whole platform now lints CLEAN under all
        seven rules — the ratchet floor is zero and must stay there."""
        baseline = astlint.load_baseline(astlint.baseline_path(REPO_ROOT))
        assert sum(baseline.values()) == 0

    def test_key_is_line_number_free(self):
        f1 = astlint.Finding("r", "p.py", 10, "S.f", "msg")
        f2 = astlint.Finding("r", "p.py", 99, "S.f", "msg")
        assert f1.key == f2.key

    def test_compare_counts_per_key(self):
        f = astlint.Finding("r", "p.py", 1, "s", "m")
        rep = astlint.LintReport([f, f, f])
        assert len(astlint.compare_to_baseline(rep, {f.key: 2})) == 1
        assert astlint.compare_to_baseline(rep, {f.key: 3}) == []


class TestCli:
    def test_json_mode_and_exit_codes(self, tmp_path, capsys):
        import json as jsonlib

        from kubeflow_tpu.analysis.__main__ import main

        # clean repo vs its baseline -> 0
        assert main(["--json"]) == 0
        out = jsonlib.loads(capsys.readouterr().out)
        assert out["new"] == []
        assert out["total"] == out["baseline_total"] == 0
        # a seeded violation against the (empty) baseline -> exit 1
        bad = tmp_path / "kubeflow_tpu" / "serving" / "_drift.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("class XEngine:\n"
                       "    def _loop(self):\n"
                       "        return self.buf.item()\n")
        assert main(["--root", str(tmp_path)]) == 1
        capsys.readouterr()

    def test_update_baseline_roundtrip(self, tmp_path):
        from kubeflow_tpu.analysis.__main__ import main

        bl = tmp_path / "bl.json"
        assert main(["--update-baseline", "--baseline", str(bl)]) == 0
        # immediately after freezing, the ratchet is green
        assert main(["--baseline", str(bl)]) == 0

    def test_rule_group_aliases(self, capsys):
        from kubeflow_tpu.analysis.__main__ import main, resolve_rules

        assert resolve_rules(["threads"]) == ["thread-affinity"]
        assert resolve_rules(["protocol"]) == ["op-table", "fault-pairing"]
        assert resolve_rules(["op-table", "protocol"]) == [
            "op-table", "fault-pairing"]
        # the aliases are real argv: a subset lint over a clean repo
        assert main(["--rule", "threads", "--rule", "protocol"]) == 0
        capsys.readouterr()

    def test_self_test_green_and_rule_filterable(self, capsys):
        from kubeflow_tpu.analysis.__main__ import main

        assert main(["--self-test"]) == 0
        out = capsys.readouterr().out
        assert "op-table/true-positive" in out
        assert "FAIL" not in out
        assert main(["--self-test", "--rule", "protocol"]) == 0
        out = capsys.readouterr().out
        assert "host-sync" not in out  # filtered down to the group

    def test_self_test_rejects_lint_flags(self, capsys):
        """--self-test never honors the lint contract (--json output,
        baseline writes), so combining them is a usage error (exit 2),
        not a silent success with the wrong stdout."""
        from kubeflow_tpu.analysis.__main__ import main

        for argv in (["--self-test", "--json"],
                     ["--self-test", "--update-baseline"],
                     ["--self-test", "somefile.py"]):
            with pytest.raises(SystemExit) as ei:
                main(argv)
            assert ei.value.code == 2
            capsys.readouterr()

    def test_self_test_catches_a_broken_rule(self, capsys, monkeypatch):
        """The self-test is a real check, not a rubber stamp: gut a
        fixture's expectation and the binary exits 1."""
        from kubeflow_tpu.analysis import selftest

        broken = tuple(
            selftest.Fixture(fx.rule, fx.name, fx.rel, "x = 1\n",
                             fx.expect, fx.needle)
            if fx.name == "op-table/true-positive" else fx
            for fx in selftest.FIXTURES)
        monkeypatch.setattr(selftest, "FIXTURES", broken)
        assert selftest.run_selftest(rules=["op-table"],
                                     out=lambda *_: None) == 1

    def test_new_rule_group_aliases(self, capsys):
        from kubeflow_tpu.analysis.__main__ import main, resolve_rules

        assert resolve_rules(["persist"]) == ["torn-write"]
        assert resolve_rules(["locks"]) == ["lock-order",
                                            "lock-blocking-call"]
        assert main(["--rule", "persist", "--rule", "locks"]) == 0
        capsys.readouterr()

    def test_json_reports_timing(self, capsys):
        import json as jsonlib

        from kubeflow_tpu.analysis.__main__ import main

        assert main(["--json"]) == 0
        out = jsonlib.loads(capsys.readouterr().out)
        assert isinstance(out["elapsed_s"], float)
        assert out["changed_only"] is False

    def test_changed_mode_scopes_to_git_diff(self, tmp_path, capsys):
        import subprocess

        from kubeflow_tpu.analysis.__main__ import main

        def git(*argv):
            subprocess.run(
                ("git", "-c", "user.name=t", "-c", "user.email=t@t")
                + argv,
                cwd=tmp_path, check=True, capture_output=True)

        bad = ("class XEngine:\n"
               "    def _loop(self):\n"
               "        return self.buf.item()\n")
        committed = tmp_path / "kubeflow_tpu" / "serving" / "_old.py"
        committed.parent.mkdir(parents=True)
        committed.write_text(bad)
        git("init", "-q")
        git("add", "-A")
        git("commit", "-q", "-m", "seed")
        # the violation exists but is NOT in the diff: --changed skips
        # it, the full ratchet still sees it
        assert main(["--root", str(tmp_path)]) == 1
        capsys.readouterr()
        assert main(["--root", str(tmp_path), "--changed"]) == 0
        assert "--changed" in capsys.readouterr().out
        # an UNTRACKED violating file is in scope for both
        (tmp_path / "kubeflow_tpu" / "serving" / "_new.py").write_text(
            bad.replace("XEngine", "YEngine"))
        assert main(["--root", str(tmp_path), "--changed"]) == 1
        out = capsys.readouterr().out
        assert "_new.py" in out and "_old.py" not in out

    def test_changed_rejects_update_baseline_and_paths(self, capsys):
        from kubeflow_tpu.analysis.__main__ import main

        for argv in (["--changed", "--update-baseline"],
                     ["--changed", "somefile.py"]):
            with pytest.raises(SystemExit) as ei:
                main(argv)
            assert ei.value.code == 2
            capsys.readouterr()


class TestRatchetRoundTripNewRules:
    """ISSUE 11: the two new rule modules ride the same ratchet — a
    seeded drift is a NEW finding against any baseline that froze the
    clean state."""

    def test_thread_affinity_drift_fails_ratchet(self, tmp_path):
        clean = """
class FooEngine:
    def _loop(self):
        pass

    def submit(self, req):
        self._migrate_q.put(("admit", req))
"""
        drifted = clean.replace(
            'self._migrate_q.put(("admit", req))',
            "self._waiting.append(req)")
        target = tmp_path / "kubeflow_tpu/serving/_eng.py"
        target.parent.mkdir(parents=True)
        target.write_text(clean)
        report = astlint.run_lint(str(tmp_path), paths=[str(target)],
                                  rules=["thread-affinity"])
        baseline = {k: v for k, v in report.counts().items()}
        assert baseline == {}  # clean state froze empty
        target.write_text(drifted)
        report2 = astlint.run_lint(str(tmp_path), paths=[str(target)],
                                   rules=["thread-affinity"])
        new = astlint.compare_to_baseline(report2, baseline)
        assert len(new) == 1 and "_waiting" in new[0].message

    def test_op_table_drift_fails_ratchet(self, tmp_path):
        """The acceptance bar end to end: freeze a complete protocol,
        delete one follow() arm, the ratchet goes red."""
        complete = """
def leader(ch, toks):
    ch.publish(("alpha", toks))
    ch.publish(("beta", toks))

def follow(channel):
    while True:
        msg = channel.next()
        op = msg[0]
        if op == "alpha":
            continue
        elif op == "beta":
            continue
"""
        drifted = complete.replace("        elif op == \"beta\":\n"
                                   "            continue\n", "")
        target = tmp_path / "kubeflow_tpu/serving/_gang.py"
        target.parent.mkdir(parents=True)
        target.write_text(complete)
        report = astlint.run_lint(str(tmp_path), paths=[str(target)],
                                  rules=["op-table"])
        assert report.findings == []
        baseline = report.counts()
        target.write_text(drifted)
        report2 = astlint.run_lint(str(tmp_path), paths=[str(target)],
                                   rules=["op-table"])
        new = astlint.compare_to_baseline(report2, baseline)
        assert len(new) == 1
        assert "`beta`" in new[0].message
        assert "no follower replay arm" in new[0].message


def lint_files(tmp_path, files, rules):
    """Lint several synthetic modules TOGETHER (the cross-module rules
    need the effect and the root in different files)."""
    paths = []
    for rel, code in files:
        target = tmp_path / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(code)
        paths.append(str(target))
    report = astlint.run_lint(str(tmp_path), paths=paths,
                              rules=list(rules))
    return report.findings


def graph_of(tmp_path, files):
    """The cross-module call graph over synthetic modules."""
    from kubeflow_tpu.analysis.callgraph import get_graph

    paths = []
    for rel, code in files:
        target = tmp_path / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(code)
        paths.append(str(target))
    ctx = astlint.parse_paths(str(tmp_path), paths)
    return get_graph(ctx)


def _fq(graph, suffix):
    """The unique fqual ending in ``suffix`` (modname-independent)."""
    hits = [k for k in graph.funcs if k.endswith(suffix)]
    assert len(hits) == 1, (suffix, hits)
    return hits[0]


class TestCallGraphEngine:
    """ISSUE 18 tentpole: the effect-propagation engine itself —
    fixpoint convergence, graceful degradation on dynamic calls, and
    cross-module effect flow."""

    def test_self_recursion_converges(self, tmp_path):
        g = graph_of(tmp_path, [("kubeflow_tpu/serving/_rec.py", """
import time

def drain(n):
    if n:
        drain(n - 1)
    time.sleep(0.01)
""")])
        assert "sleep" in g.effects(_fq(g, "::drain"))

    def test_mutual_recursion_converges_and_shares_effects(self, tmp_path):
        g = graph_of(tmp_path, [("kubeflow_tpu/serving/_mut.py", """
import time

def ping(n):
    if n:
        pong(n - 1)

def pong(n):
    time.sleep(0.01)
    ping(n)
""")])
        # the cycle reaches a fixpoint and BOTH members carry the
        # effect (each reaches the sleep through the other)
        assert "sleep" in g.effects(_fq(g, "::ping"))
        assert "sleep" in g.effects(_fq(g, "::pong"))

    def test_unresolved_dynamic_calls_degrade_to_no_edge(self, tmp_path):
        g = graph_of(tmp_path, [("kubeflow_tpu/serving/_dyn.py", """
def dispatch(table, key, obj, name):
    table[key]()
    getattr(obj, name)()
    fn = table[key]
    fn()
""")])
        fq = _fq(g, "::dispatch")
        assert g.funcs[fq].edges == []  # under-approximate, no crash
        assert g.effects(fq) == set()

    def test_cross_module_effect_propagates(self, tmp_path):
        g = graph_of(tmp_path, [
            ("kubeflow_tpu/serving/_xa.py", """
from ._xb import push

def caller():
    push(1)
"""),
            ("kubeflow_tpu/serving/_xb.py", """
import time

def push(x):
    time.sleep(0.01)
"""),
        ])
        assert "sleep" in g.effects(_fq(g, "::caller"))


class TestTornWriteRule:
    TW = ["torn-write"]

    def test_bare_final_write_in_persistence_core(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/storage.py", """
import json

def save_index(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
""")], self.TW)
        assert len(fs) == 1 and "commit protocol" in fs[0].message

    def test_rename_without_fsync(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_persist.py", """
import json
import os

def save_index(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)
""")], self.TW)
        assert len(fs) == 1 and "preceding fsync" in fs[0].message

    def test_file_fsync_after_replace_flagged(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_persist.py", """
import json
import os

def save_index(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    os.fsync(f.fileno())
""")], self.TW)
        assert len(fs) == 1 and "AFTER the rename" in fs[0].message

    def test_full_protocol_with_helper_fsync_is_clean(self, tmp_path):
        # the fsync may live in a helper — the call graph supplies the
        # effect; a dir fsync AFTER the rename is the correct final step
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_persist.py", """
import os

def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    os.fsync(fd)
    os.close(fd)

def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    os.fsync(fd)
    os.close(fd)

def save_index(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    _fsync_file(tmp)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
""")], self.TW)
        assert fs == []

    def test_pragma_declares_append_log(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/storage.py", """
def open_log(path):
    # analysis: ok torn-write — append-only, torn tail repaired on replay
    return open(path, "ab")
""")], self.TW)
        assert fs == []

    def test_modules_outside_protocol_stay_quiet(self, tmp_path):
        # no lexical fsync/rename and not the persistence core: a bench
        # script's open(path, "w") is not a finding
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_report.py", """
def dump(path, text):
    with open(path, "w") as f:
        f.write(text)
""")], self.TW)
        assert fs == []


class TestLockBlockingCallRule:
    LB = ["lock-blocking-call"]

    def test_blocking_reached_through_helper(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_lb.py", """
import os

class BatchWriter:
    def flush_batch(self):
        with self._lock:
            self._flush()

    def _flush(self):
        self._f.flush()
        os.fsync(self._f.fileno())
""")], self.LB)
        assert len(fs) == 1
        assert "while holding" in fs[0].message
        assert "`os.fsync`" in fs[0].message
        assert "_flush" in fs[0].message  # names the terminal boundary

    def test_blocking_reached_cross_module(self, tmp_path):
        fs = lint_files(tmp_path, [
            ("kubeflow_tpu/serving/_lbq.py", """
from ._lbdisk import push

class MailQueue:
    def put(self, item):
        with self._lock:
            push(item)
"""),
            ("kubeflow_tpu/serving/_lbdisk.py", """
import time

def push(item):
    time.sleep(0.05)
"""),
        ], self.LB)
        assert len(fs) == 1 and "`time.sleep`" in fs[0].message
        assert fs[0].path.endswith("_lbq.py")  # flagged at the lock site

    def test_lifecycle_scope_is_exempt(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_lb.py", """
import os

class BatchWriter:
    def close(self):
        with self._lock:
            self._flush()

    def _flush(self):
        os.fsync(self._f.fileno())
""")], self.LB)
        assert fs == []  # close() serializes a phase transition

    def test_direct_site_is_lock_orders_finding(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_lb.py", """
import time

class Pump:
    def run_once(self):
        with self._lock:
            time.sleep(0.1)
""")], self.LB)
        assert fs == []  # one site, one rule: lock-order reports it

    def test_pragma_declares_the_boundary(self, tmp_path):
        fs = lint_files(tmp_path, [("kubeflow_tpu/serving/_lb.py", """
import os

class BatchWriter:
    def flush_batch(self):
        with self._lock:
            # analysis: ok lock-blocking-call — batched-fsync contract
            self._flush()

    def _flush(self):
        os.fsync(self._f.fileno())
""")], self.LB)
        assert fs == []


class TestCrossModuleHostSync:
    """The acceptance case: a violation the old intra-file walk could
    never see — the blocking helper lives one module away from the
    ``*Engine`` root that reaches it."""

    HS = ["host-sync-in-dispatch"]
    HELPER = ("kubeflow_tpu/serving/_xhelper.py", """
import jax

def fetch_stats(buf):
    return jax.device_get(buf)
""")

    def test_cross_module_violation_caught(self, tmp_path):
        fs = lint_files(tmp_path, [
            ("kubeflow_tpu/serving/_xengine.py", """
from ._xhelper import fetch_stats

class FooEngine:
    def _loop(self):
        return fetch_stats(self.buf)
"""),
            self.HELPER,
        ], self.HS)
        assert len(fs) == 1 and "host sync" in fs[0].message
        # flagged AT the effect site, in the helper's file
        assert fs[0].path.endswith("_xhelper.py")

    def test_unreached_helper_stays_quiet(self, tmp_path):
        fs = lint_files(tmp_path, [
            ("kubeflow_tpu/serving/_xengine.py", """
from ._xhelper import fetch_stats

class FooEngine:
    def _loop(self):
        return 1

    def debug_dump(self):
        return fetch_stats(self.buf)
"""),
            self.HELPER,
        ], self.HS)
        assert fs == []  # reachability, not mere import, is the test


class TestLintWallTime:
    def test_whole_platform_lint_stays_fast(self):
        """ISSUE 18: the call-graph engine must not quietly make tier-1
        slow.  Wall clock on this box swings ~2x with load, so the
        budget is the <2 s bar OR 4x the cost of raw ``ast.parse`` over
        the same sources, whichever is larger — the multiplier is what
        the engine actually controls (a quietly quadratic graph pass
        blows it regardless of box speed)."""
        import ast as ast_mod
        import time

        paths = list(astlint.discover(REPO_ROOT))
        texts = []
        for p in paths:
            with open(p, "r", encoding="utf-8") as fh:
                texts.append(fh.read())
        raw = min(self._timed(lambda: [ast_mod.parse(t) for t in texts])
                  for _ in range(3))
        full = min(self._timed(lambda: astlint.run_lint(REPO_ROOT))
                   for _ in range(2))
        budget = max(2.0, 4.0 * raw)
        assert full < budget, (
            f"whole-platform parse+lint took {full:.2f}s "
            f"(budget {budget:.2f}s = max(2.0, 4 x {raw:.2f}s raw parse))")

    @staticmethod
    def _timed(fn):
        import time
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0


class TestRecompileGuard:
    def test_counts_only_armed_growth(self):
        import jax
        import jax.numpy as jnp

        counter = RecompileCounter()
        prog = recompile_guard(jax.jit(lambda x: x + 1), counter)
        prog(jnp.zeros(2))           # first compile = warm, unarmed
        prog(jnp.zeros(3))           # warmup ladder growth, unarmed
        assert counter.count == 0
        counter.armed = True
        prog(jnp.zeros(2))           # cache hit
        prog(jnp.zeros(3))           # cache hit
        assert counter.count == 0
        prog(jnp.zeros(4))           # NEW shape post-arm = recompile
        assert counter.count == 1
        prog(jnp.zeros(4))           # now warm
        assert counter.count == 1
        assert prog.cache_entries == 3

    def test_idempotent_wrap_and_opaque_passthrough(self):
        counter = RecompileCounter()
        g = recompile_guard(lambda x: x, counter)
        assert recompile_guard(g, counter) is g
        assert g(5) == 5             # uncounted, never broken
        assert counter.count == 0


class TestLockAudit:
    def test_inversion_detected(self):
        audit = LockAudit()
        a = audit.wrap(threading.Lock(), "a")
        b = audit.wrap(threading.Lock(), "b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert audit.inversions() == [("a", "b")]
        rep = audit.report()
        assert rep["inversions"] == ["a <-> b"]
        assert rep["edges"]["a -> b"] == 1

    def test_consistent_order_clean_across_threads(self):
        audit = LockAudit()
        a = audit.wrap(threading.Lock(), "a")
        b = audit.wrap(threading.Lock(), "b")

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert audit.inversions() == []
        assert audit.edges()[("a", "b")] == 200

    def test_instrument_real_platform_objects(self):
        """Audit the store + expectations locks through real reconcile-
        shaped traffic (the chaos harness instruments the same way)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.controlplane.expectations import Expectations
        from kubeflow_tpu.controlplane.objects import Pod
        from kubeflow_tpu.controlplane.store import Store

        store = Store()
        exp = Expectations()
        audit = LockAudit()
        audit.instrument(store, "_lock", "Store._lock")
        audit.instrument(exp, "_lock", "Expectations._lock")

        def worker(i):
            for j in range(20):
                key = f"default/p{i}-{j}"
                exp.expect_creations(key, 1)
                store.create(Pod(metadata=ObjectMeta(
                    name=f"p{i}-{j}", namespace="default")))
                exp.creation_observed(key)
                store.list("Pod")

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert audit.inversions() == []
        assert "Store._lock" in audit.report()["locks"]


class TestBlockLedger:
    """ISSUE 11 tentpole: the block-economy runtime auditor."""

    def _alloc(self, n=8, bs=4):
        from kubeflow_tpu.serving.paged import BlockAllocator

        return BlockAllocator(n, bs)

    def test_conservation_through_alloc_ref_release(self):
        a = self._alloc()
        led = BlockLedger()
        led.attach(a, name="unit")
        t = a.alloc(3)
        led.annotate(a, t, "seqA")
        a.ref(t[:1])          # prefix share
        a.release(t[:1])      # sharer retires
        a.release(t)          # owner retires
        assert led.conservation_errors == []
        assert led.audit_quiesced(a) == []
        assert led.verify(a) == []
        assert led.leaked_total == 0

    def test_leak_detected_once_with_attribution(self):
        a = self._alloc()
        led = BlockLedger()
        led.attach(a, name="unit")
        t = a.alloc(2)
        led.annotate(a, t, "seq7")
        leaks = led.audit_quiesced(a)          # nothing held -> leaks
        assert [d["block"] for d in leaks] == sorted(int(b) for b in t)
        assert all(d["owner"] == "seq7" for d in leaks)
        assert led.leaked_total == 2
        # re-audit of the SAME leak is free (gauge, not a treadmill)
        led.audit_quiesced(a)
        assert led.leaked_total == 2
        # a held block is not a leak
        assert led.audit_quiesced(a, held=t) == []
        a.release(t)
        assert led.audit_quiesced(a) == []

    def test_resurrection_and_double_grant_detection(self):
        a = self._alloc()
        led = BlockLedger()
        led.attach(a, name="unit")
        t = a.alloc(2)
        a.release(t)
        a.ref(t)              # resurrect out of the free list
        assert led.conservation_errors == []
        assert sorted(led.live(a)) == sorted(int(b) for b in t)
        a.release(t)
        # bypassing the wrapped verbs IS the drift the ledger exists
        # to catch: fake an unbalanced release
        a._refs[int(t[0])] = 1
        led.verify(a)
        assert led.conservation_errors  # shadow/real drift recorded

    def test_attach_is_idempotent_and_books_preexisting(self):
        a = self._alloc()
        pre = a.alloc(2)
        led = BlockLedger()
        led.attach(a)
        led.attach(a)          # no double wrap
        assert sorted(led.live(a)) == sorted(int(b) for b in pre)
        a.release(pre)
        assert led.audit_quiesced(a) == []

    def test_engine_end_to_end_seeded_leak_is_caught(self):
        """The acceptance fixture: a deliberate leak in a LIVE engine
        is caught by the automatic idle audit and surfaces on the
        kv_blocks_leaked_total stats gauge."""
        import time

        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        eng = ContinuousEngine(cfg, params, num_slots=2, block_size=16,
                               decode_chunk=2, prefix_cache=False)
        ledger = BlockLedger()
        eng.attach_block_ledger(ledger)
        try:
            req = eng.submit([1, 2, 3, 4, 5], max_new_tokens=6)
            req.wait(180)
            assert len(req.tokens) == 6
            # clean run: boundary audit + gauge both at zero
            assert eng.audit_blocks() == []
            assert eng.stats()["kv_blocks_leaked_total"] == 0
            assert ledger.conservation_errors == []
            # seed the leak: grab blocks and "forget" them
            eng._alloc.alloc(2)
            deadline = time.time() + 15
            while (time.time() < deadline
                   and eng.stats()["kv_blocks_leaked_total"] == 0):
                eng._wake.set()
                time.sleep(0.05)
            assert eng.stats()["kv_blocks_leaked_total"] == 2
            leaks = eng.audit_blocks()
            assert len(leaks) == 2
        finally:
            eng.stop()

    def test_stop_runs_terminal_audit(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        eng = ContinuousEngine(cfg, params, num_slots=2, block_size=16,
                               decode_chunk=2, prefix_cache=False)
        ledger = BlockLedger()
        eng.attach_block_ledger(ledger)
        eng._alloc.alloc(1)    # leak, never audited while running
        eng.stop()             # terminal boundary audit fires here
        assert ledger.leaked_total == 1
        # post-shutdown audit_blocks answers without a scheduler
        assert len(eng.audit_blocks()) == 1
