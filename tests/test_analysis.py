"""Platform analyzer (kubeflow_tpu/analysis): lint rules + ratchet + auditors.

Three layers, matching the package:

- per-rule FIXTURE tests: one true positive and one near-miss false
  positive per rule, linted as tmp files placed under the path prefixes
  the rules scope to;
- the RATCHET: the whole repo lints with zero findings above
  ``analysis/baseline.json`` — this is the tier-1 gate every future PR
  inherits (a new host sync / lock inversion / silent swallow fails
  here, not in production);
- the RUNTIME auditors: RecompileGuard counting real jit cache misses
  and LockAudit catching real acquisition-order inversions.

Pure-stdlib imports only at module level (plus jax inside the guard
test) so this file stays cheap — it runs first alphabetically.
"""

import os
import threading

import pytest

from kubeflow_tpu.analysis import astlint
from kubeflow_tpu.analysis.runtime import (
    LockAudit,
    RecompileCounter,
    recompile_guard,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_snippet(tmp_path, code: str, rules,
                 rel="kubeflow_tpu/serving/_fixture.py"):
    """Lint one synthetic module placed at ``rel`` under a tmp root (the
    path matters: lock-order scopes to platform dirs)."""
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(code)
    report = astlint.run_lint(str(tmp_path), paths=[str(target)],
                              rules=list(rules))
    return report.findings


class TestHostSyncRule:
    TP = """
import jax
import numpy as np

class FooEngine:
    def _loop(self):
        self._step()

    def _step(self):
        x = self._fetch()
        return x.item()

    def _fetch(self):
        return jax.device_get(self.buf)
"""

    def test_true_positive_via_reachability(self, tmp_path):
        found = lint_snippet(tmp_path, self.TP, ["host-sync-in-dispatch"])
        kinds = {f.message for f in found}
        assert any(".item()" in m for m in kinds)
        assert any("device_get" in m for m in kinds)
        # reachability names the offending scopes
        assert {f.scope for f in found} == {"FooEngine._step",
                                            "FooEngine._fetch"}

    def test_near_miss_unreachable_helper(self, tmp_path):
        code = """
import jax

class FooEngine:
    def _loop(self):
        return 1

    def debug_dump(self):
        # host sync, but NOT reachable from the dispatch loop
        return jax.device_get(self.buf)

class LoopHelper:
    def _loop(self):
        return jax.device_get(self.buf)
"""
        # LoopHelper's class name doesn't end in Engine -> no roots
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []

    def test_pragma_silences(self, tmp_path):
        code = """
import jax

class FooEngine:
    def _process(self):
        # analysis: ok host-sync-in-dispatch — the fetch boundary
        return jax.device_get(self.buf)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []

    def test_allocator_methods_are_roots(self, tmp_path):
        """ISSUE 6 satellite: the paged-KV allocator runs ON the
        scheduler's dispatch path, so EVERY ``*Allocator`` method is a
        root — a ``.item()`` on the free list is flagged even though no
        ``_loop``/``_admit`` exists in the file."""
        code = """
import numpy as np

class BlockAllocator:
    def alloc(self, n):
        return int(self._refs.sum().item())

    def tables(self, bt):
        return np.asarray(bt)
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_palloc.py")
        scopes = {f.scope for f in found}
        assert "BlockAllocator.alloc" in scopes
        assert "BlockAllocator.tables" in scopes

    def test_allocator_near_miss_other_class(self, tmp_path):
        code = """
import numpy as np

class BlockTableHelper:
    def tables(self, bt):
        return np.asarray(bt)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_palloc.py") == []

    def test_traffic_plane_methods_are_roots(self, tmp_path):
        """ISSUE 9 satellite: token-bucket/queue accounting runs on
        router/HTTP threads and the engine's admission hook — a device
        fetch or blocking socket in a ``*TrafficPlane``/``*Admission``
        method stalls every live request, so EVERY method is a root."""
        code = """
import numpy as np

class QosTrafficPlane:
    def acquire(self, tenant):
        return self._charge(tenant)

    def _charge(self, tenant):
        return float(self._tokens.sum())

class PolicyAdmission:
    def admit(self, req, sock):
        sock.sendall(b"ping")
        return np.asarray(self._live)

class EnginePreemptor:
    def _step(self):
        return self._victim.tokens.tolist()
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_traffic.py")
        scopes = {f.scope for f in found}
        assert "QosTrafficPlane._charge" in scopes
        assert "PolicyAdmission.admit" in scopes
        assert "EnginePreemptor._step" in scopes
        assert any("socket" in f.message for f in found)

    def test_traffic_near_miss_other_class(self, tmp_path):
        code = """
import numpy as np

class TrafficReport:
    def render(self):
        return np.asarray(self._rows)
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_traffic.py") == []

    def test_resizer_and_reshard_classes_rooted(self, tmp_path):
        """ISSUE 10 satellite (the PR 8 ``*Preemptor`` lesson): every
        method of a ``*Resizer``/``*Reshard`` class is a lint root —
        elastic-resize orchestration touches scheduler state, so an
        undeclared device fetch or blocking socket there must surface,
        pragma'd with a reason or moved off-thread."""
        code = """
import jax

class GangResizer:
    def _copy_weights(self):
        return jax.device_get(self._params)

class WeightReshard:
    def _stream(self):
        self._sock.sendall(self._frame)
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"],
                             rel="kubeflow_tpu/serving/_resize.py")
        scopes = {f.scope for f in found}
        assert "GangResizer._copy_weights" in scopes
        assert "WeightReshard._stream" in scopes
        assert any("socket" in f.message for f in found)

    def test_resizer_near_miss_other_class(self, tmp_path):
        """Prefix lookalikes (``Reshard*``/``Resize*`` without the
        suffix) are helper/plan classes, not the orchestrator — clean."""
        code = """
import numpy as np

class ReshardPlanner:
    def table(self):
        return np.asarray(self._rows)

class ResizeReport:
    def render(self):
        return self._latency.tolist()
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"],
                            rel="kubeflow_tpu/serving/_resize.py") == []

    def test_blocking_socket_send_in_scheduler_flagged(self, tmp_path):
        """ISSUE 8 satellite: a blocking socket send reachable from an
        engine's scheduler roots stalls every live request for a
        network round trip — the migrate path must run off-thread."""
        code = """
class FooEngine:
    def _loop(self):
        while True:
            self._stream_block()

    def _stream_block(self):
        self.sock.sendall(self._next_frame())
"""
        found = lint_snippet(tmp_path, code, ["host-sync-in-dispatch"])
        assert len(found) == 1
        assert "socket" in found[0].message
        assert found[0].scope == "FooEngine._stream_block"

    def test_blocking_socket_near_miss_worker_thread(self, tmp_path):
        """sendall in a method NOT reachable from scheduler roots (the
        migration worker pattern) — and in a non-Engine server class —
        is clean."""
        code = """
import socket

class FooEngine:
    def _loop(self):
        self._mailbox.get_nowait()

    def _migration_worker(self):
        # runs on its own thread; never called from _loop
        self.sock.sendall(b"frame")

class KvMigrationServer:
    def _serve_one(self, c):
        c.sendall(b"ack")
        return socket.create_connection(("h", 1))
"""
        assert lint_snippet(tmp_path, code,
                            ["host-sync-in-dispatch"]) == []


class TestJitInLoopRule:
    def test_true_positive(self, tmp_path):
        code = """
import jax

def bad(fns):
    progs = []
    for f in fns:
        progs.append(jax.jit(f))
    return progs

def also_bad(buckets):
    while buckets:
        p = make_decode_program(buckets.pop())
"""
        found = lint_snippet(tmp_path, code, ["jit-in-loop"])
        assert len(found) == 2
        assert {f.scope for f in found} == {"bad", "also_bad"}

    def test_near_miss_cached_getter(self, tmp_path):
        code = """
import jax

def good(fns):
    cache = {}
    def getter(k):
        # construction inside a def inside nothing-loopy: fine
        if k not in cache:
            cache[k] = jax.jit(fns[k])
        return cache[k]
    out = []
    for k in range(8):
        out.append(getter(k)(k))  # CALLING a cached program is fine
    return out
"""
        assert lint_snippet(tmp_path, code, ["jit-in-loop"]) == []


class TestLockOrderRule:
    def test_cycle_true_positive(self, tmp_path):
        code = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with b_lock:
        with a_lock:
            pass
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "lock-order cycle" in found[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        code = """
import threading

a_lock = threading.Lock()
b_lock = threading.Lock()

def one():
    with a_lock:
        with b_lock:
            pass

def two():
    with a_lock:
        with b_lock:
            pass
"""
        assert lint_snippet(tmp_path, code, ["lock-order"]) == []

    def test_blocking_under_lock(self, tmp_path):
        code = """
import threading
import time

class Pump:
    def run(self):
        with self._lock:
            time.sleep(1.0)
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "time.sleep" in found[0].message
        assert "Pump._lock" in found[0].message

    def test_near_miss_sleep_in_nested_def(self, tmp_path):
        code = """
import threading
import time

class Pump:
    def run(self):
        with self._lock:
            def later():
                time.sleep(1.0)  # runs on another thread, NOT under lock
            self._spawn(later)
"""
        assert lint_snippet(tmp_path, code, ["lock-order"]) == []

    def test_interprocedural_cycle_one_level(self, tmp_path):
        code = """
import threading

class Gang:
    def pub(self):
        with self._lock:
            self._flush()

    def _flush(self):
        with self._sendgate:
            pass

    def other(self):
        with self._sendgate:
            with self._lock:
                pass
"""
        found = lint_snippet(tmp_path, code, ["lock-order"])
        assert len(found) == 1
        assert "cycle" in found[0].message

    def test_outside_scoped_dirs_ignored(self, tmp_path):
        code = """
import threading, time
class P:
    def run(self):
        with self._lock:
            time.sleep(1)
"""
        assert lint_snippet(tmp_path, code, ["lock-order"],
                            rel="kubeflow_tpu/models/_fixture.py") == []


class TestSwallowedExceptionRule:
    def test_true_positive(self, tmp_path):
        code = """
def f():
    try:
        risky()
    except Exception:  # noqa: BLE001
        pass
"""
        found = lint_snippet(tmp_path, code, ["swallowed-exception"])
        assert len(found) == 1
        # a bare noqa without a reason is NOT a justification
        assert found[0].scope == "f"

    def test_near_misses(self, tmp_path):
        code = """
import logging
log = logging.getLogger(__name__)

def logs():
    try:
        risky()
    except Exception:  # noqa: BLE001
        log.debug("risky failed", exc_info=True)

def reraises():
    try:
        risky()
    except Exception as e:
        raise RuntimeError("wrapped") from e

def justified():
    try:
        risky()
    except Exception:  # noqa: BLE001 — db unavailable: retry next pass
        pass

def pragma_ok():
    try:
        risky()
    # analysis: ok swallowed-exception — probing an optional backend
    except Exception:
        pass

def narrow():
    try:
        risky()
    except ValueError:
        pass
"""
        assert lint_snippet(tmp_path, code, ["swallowed-exception"]) == []


class TestUnsafePickleRule:
    def test_true_positive(self, tmp_path):
        code = """
import pickle

def recv(sock):
    return pickle.loads(sock.recv(4096))
"""
        found = lint_snippet(tmp_path, code, ["unsafe-pickle"])
        assert len(found) == 1
        assert "arbitrary code execution" in found[0].message

    def test_near_miss_dumps_and_allowlist(self, tmp_path):
        code = """
import pickle

def send(obj):
    return pickle.dumps(obj)
"""
        assert lint_snippet(tmp_path, code, ["unsafe-pickle"]) == []
        # the real allowlisted ingestion point stays clean
        gang = os.path.join(REPO_ROOT, "kubeflow_tpu", "serving", "gang.py")
        report = astlint.run_lint(REPO_ROOT, paths=[gang],
                                  rules=["unsafe-pickle"])
        assert report.findings == []


class TestNondaemonThreadRule:
    def test_true_positive(self, tmp_path):
        code = """
import threading

def start():
    t = threading.Thread(target=work)
    t.start()
"""
        found = lint_snippet(tmp_path, code, ["nondaemon-thread"])
        assert len(found) == 1

    def test_near_misses(self, tmp_path):
        code = """
import threading

def kwarg():
    threading.Thread(target=work, daemon=True).start()

def attr():
    t = threading.Thread(target=work)
    t.daemon = True
    t.start()

def pragma():
    # analysis: ok nondaemon-thread — must survive main for drain
    t = threading.Thread(target=work)
    t.start()
"""
        assert lint_snippet(tmp_path, code, ["nondaemon-thread"]) == []


class TestRatchet:
    """The tier-1 gate: the repo must lint clean against its baseline."""

    def test_repo_has_no_new_findings(self):
        report = astlint.run_lint(REPO_ROOT)
        baseline = astlint.load_baseline(astlint.baseline_path(REPO_ROOT))
        new = astlint.compare_to_baseline(report, baseline)
        assert new == [], (
            "NEW platform-lint findings above analysis/baseline.json:\n"
            + "\n".join(f"  {f}" for f in new)
            + "\nFix them, pragma them with a reason (# analysis: ok "
            "<rule> — why), or for reviewed debt re-freeze with "
            "`python -m kubeflow_tpu.analysis --update-baseline`.")

    def test_baseline_shrank_from_prefix_count(self):
        """The rules landed with the debt burned down, not frozen: 33
        findings pre-fix (18 swallowed-exception, 11 host-sync, 4
        lock-order blocking-under-lock), <= 8 frozen after."""
        baseline = astlint.load_baseline(astlint.baseline_path(REPO_ROOT))
        assert 0 < sum(baseline.values()) <= 8

    def test_key_is_line_number_free(self):
        f1 = astlint.Finding("r", "p.py", 10, "S.f", "msg")
        f2 = astlint.Finding("r", "p.py", 99, "S.f", "msg")
        assert f1.key == f2.key

    def test_compare_counts_per_key(self):
        f = astlint.Finding("r", "p.py", 1, "s", "m")
        rep = astlint.LintReport([f, f, f])
        assert len(astlint.compare_to_baseline(rep, {f.key: 2})) == 1
        assert astlint.compare_to_baseline(rep, {f.key: 3}) == []


class TestCli:
    def test_json_mode_and_exit_codes(self, tmp_path, capsys):
        import json as jsonlib

        from kubeflow_tpu.analysis.__main__ import main

        # clean repo vs its baseline -> 0
        assert main(["--json"]) == 0
        out = jsonlib.loads(capsys.readouterr().out)
        assert out["new"] == []
        assert out["total"] == out["baseline_total"]
        # against an EMPTY baseline the frozen debt is "new" -> 1
        empty = tmp_path / "empty.json"
        empty.write_text('{"findings": {}}')
        assert main(["--baseline", str(empty)]) == 1

    def test_update_baseline_roundtrip(self, tmp_path):
        from kubeflow_tpu.analysis.__main__ import main

        bl = tmp_path / "bl.json"
        assert main(["--update-baseline", "--baseline", str(bl)]) == 0
        # immediately after freezing, the ratchet is green
        assert main(["--baseline", str(bl)]) == 0


class TestRecompileGuard:
    def test_counts_only_armed_growth(self):
        import jax
        import jax.numpy as jnp

        counter = RecompileCounter()
        prog = recompile_guard(jax.jit(lambda x: x + 1), counter)
        prog(jnp.zeros(2))           # first compile = warm, unarmed
        prog(jnp.zeros(3))           # warmup ladder growth, unarmed
        assert counter.count == 0
        counter.armed = True
        prog(jnp.zeros(2))           # cache hit
        prog(jnp.zeros(3))           # cache hit
        assert counter.count == 0
        prog(jnp.zeros(4))           # NEW shape post-arm = recompile
        assert counter.count == 1
        prog(jnp.zeros(4))           # now warm
        assert counter.count == 1
        assert prog.cache_entries == 3

    def test_idempotent_wrap_and_opaque_passthrough(self):
        counter = RecompileCounter()
        g = recompile_guard(lambda x: x, counter)
        assert recompile_guard(g, counter) is g
        assert g(5) == 5             # uncounted, never broken
        assert counter.count == 0


class TestLockAudit:
    def test_inversion_detected(self):
        audit = LockAudit()
        a = audit.wrap(threading.Lock(), "a")
        b = audit.wrap(threading.Lock(), "b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert audit.inversions() == [("a", "b")]
        rep = audit.report()
        assert rep["inversions"] == ["a <-> b"]
        assert rep["edges"]["a -> b"] == 1

    def test_consistent_order_clean_across_threads(self):
        audit = LockAudit()
        a = audit.wrap(threading.Lock(), "a")
        b = audit.wrap(threading.Lock(), "b")

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert audit.inversions() == []
        assert audit.edges()[("a", "b")] == 200

    def test_instrument_real_platform_objects(self):
        """Audit the store + expectations locks through real reconcile-
        shaped traffic (the chaos harness instruments the same way)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.controlplane.expectations import Expectations
        from kubeflow_tpu.controlplane.objects import Pod
        from kubeflow_tpu.controlplane.store import Store

        store = Store()
        exp = Expectations()
        audit = LockAudit()
        audit.instrument(store, "_lock", "Store._lock")
        audit.instrument(exp, "_lock", "Expectations._lock")

        def worker(i):
            for j in range(20):
                key = f"default/p{i}-{j}"
                exp.expect_creations(key, 1)
                store.create(Pod(metadata=ObjectMeta(
                    name=f"p{i}-{j}", namespace="default")))
                exp.creation_observed(key)
                store.list("Pod")

        threads = [threading.Thread(target=worker, args=(i,), daemon=True)
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert audit.inversions() == []
        assert "Store._lock" in audit.report()["locks"]
