"""Fleet-scale digital twin (ISSUE 20): the virtual-clock simulator
that drives the REAL policy objects through seeded outage scenarios.

Layers covered here:

- **Seams**: the ``clock=``/``rng=`` injection points grown this PR —
  a :class:`VirtualClock` driving the real ``TokenBucket`` and
  ``BackendHealth`` state machines deterministically, and the seeded
  ``jittered_retry_after`` draw.
- **Determinism**: the same (scenario, seed) twice from fresh
  processes-worth of state must serialize to byte-identical score
  rows — the property every regression bisect and CI ratchet on the
  catalog depends on.
- **Smoke** (tier-1): one short scenario exercising the full
  door -> route -> decide -> actuate chain in well under a second.
- **Parity**: the policy-sharing proof.  The twin records the raw
  ``(now, signals)`` stream its autoscaler saw; replaying exactly
  that stream through a FRESH production :class:`ClusterAutoscaler`
  (no fleet, no sim — just ``tick(now=...)``) must reproduce the
  twin's decision sequence bit-for-bit.  If the twin had re-modeled
  the policy, this is where the fork would show.
- **Catalog rows** (``slow``): the fleet-scale scenarios with their
  acceptance invariants — 500-replica diurnal under the wall-clock
  budget, zone loss reproducing the PR 16 invariants at 100 replicas
  (exactly-once outage detection, bounded retry amplification, zero
  leaks), and seeded chaos with every injected fault consumed.
"""

import random
import time

import pytest

from kubeflow_tpu.serving.autoscale import ClusterAutoscaler
from kubeflow_tpu.serving.traffic import (
    BackendHealth,
    TokenBucket,
    jittered_retry_after,
)
from kubeflow_tpu.sim import (
    VirtualClock,
    diurnal_policy,
    run_scenario,
    score_json,
)
from kubeflow_tpu.sim.scenarios import scenario_diurnal


def _no_leaks(score: dict) -> None:
    leaked = score["leaked"]
    assert not any(leaked.values()), f"leak audit failed: {leaked}"


# -- the seams: real policy objects on virtual time -----------------------


class TestVirtualClockSeams:
    def test_token_bucket_refills_on_virtual_time(self):
        clk = VirtualClock()
        bucket = TokenBucket(rate=1.0, burst=1.0, clock=clk)
        assert bucket.try_take() == 0.0
        # empty now: the retry-after hint is a full token's accrual
        assert bucket.try_take() == pytest.approx(1.0)
        # no wall time passes — only the virtual clock moves
        clk.advance_to(0.5)
        assert bucket.try_take() == pytest.approx(0.5)
        clk.advance_to(1.0)
        assert bucket.try_take() == 0.0

    def test_backend_health_full_cycle_on_virtual_time(self):
        clk = VirtualClock()
        health = BackendHealth(fail_threshold=2, open_s=1.0,
                               probe_jitter=0.0, clock=clk,
                               rng=random.Random(0))
        url = "sim://r0"
        health.note_failure(url)
        assert health.state(url) == BackendHealth.CLOSED
        health.note_failure(url)
        assert health.state(url) == BackendHealth.OPEN
        assert health.routable([url]) == []
        # past the (unjittered) reopen deadline: exactly one probe
        clk.advance_to(1.01)
        assert health.routable([url]) == [url]
        health.on_routed(url)
        assert health.routable([url]) == []  # probe in flight
        health.note_success(url)
        assert health.state(url) == BackendHealth.CLOSED
        assert health.routable([url]) == [url]

    def test_reopen_backoff_doubles_in_virtual_seconds(self):
        clk = VirtualClock()
        health = BackendHealth(fail_threshold=1, open_s=1.0,
                               open_cap_s=30.0, probe_jitter=0.0,
                               clock=clk, rng=random.Random(0))
        url = "sim://r0"
        health.note_failure(url)
        clk.advance_to(1.01)
        health.on_routed(url)
        health.note_failure(url)  # failed probe: backoff doubles
        clk.advance_to(2.0)       # 1s after re-open — not enough
        assert health.routable([url]) == []
        clk.advance_to(3.02)      # > 1.01 + 2.0
        assert health.routable([url]) == [url]

    def test_jittered_retry_after_is_seeded(self):
        a = jittered_retry_after(1.0, rng=random.Random(7))
        b = jittered_retry_after(1.0, rng=random.Random(7))
        assert a == b
        rng = random.Random(7)
        draws = {jittered_retry_after(1.0, rng=rng) for _ in range(8)}
        assert len(draws) > 1  # it does actually spread the herd


# -- determinism: same seed, same bytes -----------------------------------


class TestDeterminism:
    def test_same_seed_byte_identical_score(self):
        first = score_json(run_scenario("smoke", seed=3))
        second = score_json(run_scenario("smoke", seed=3))
        assert first == second

    def test_same_seed_byte_identical_diurnal(self):
        first = score_json(run_scenario("diurnal", seed=1, replicas=3))
        second = score_json(run_scenario("diurnal", seed=1, replicas=3))
        assert first == second


# -- smoke: door -> route -> decide -> actuate, tier-1 fast ---------------


def test_smoke_door_route_decide_actuate():
    score = run_scenario("smoke", seed=0)
    assert score["admitted"] > 0
    assert score["completed"] > 0
    # the real door queued/shed under its 3-slot concurrency cap
    assert score["requests_total"] > score["completed"]
    # the real autoscaler saw the burst and actuated a scale-up
    assert score["scaled_up"] == 1
    assert score["decisions"].get("scale_up", 0) >= 1
    _no_leaks(score)


# -- parity: the twin's decisions ARE production decide()/tick() ----------


def test_autoscaler_parity_replay_small_diurnal():
    """Policy-sharing proof (acceptance): record the twin's raw
    ``(now, signals)`` stream at the parity scale (<= 4 replicas),
    then replay it through a fresh production autoscaler with no-op
    actuators.  Identical (t, action, reason) sequence or the twin is
    running a re-model, not the real policy.

    The replay installs real no-op callables — NOT an empty actuator
    dict — because a missing channel short-circuits ``tick`` before
    ``note_fired`` arms the cooldown, which would silently diverge
    the gating state from the twin's."""
    signals: list = []
    decisions: list = []
    score = scenario_diurnal(seed=0, replicas=4,
                             record_signals=signals,
                             record_decisions=decisions)
    assert decisions and signals
    assert len(signals) == len(decisions)
    # precondition: a twin-side actuator failure arms failure backoff
    # the no-op replay cannot see, so the parity config must be clean
    assert score["actuator_failures_total"] == 0

    stream = [dict(sig) for _t, sig in signals]
    replay = ClusterAutoscaler(
        diurnal_policy(),
        sensors=lambda: stream.pop(0),
        actuators={"replica_up": lambda dec: None,
                   "replica_down": lambda dec: None,
                   "zero": lambda dec: None})
    replayed = []
    for t, _sig in signals:
        dec = replay.tick(now=t)
        replayed.append((round(t, 6), dec.action, dec.reason))
    assert replayed == decisions


# -- the catalog rows at fleet scale (slow tier) --------------------------


@pytest.mark.slow
class TestFleetCatalog:
    def test_diurnal_500_replicas_under_wall_budget(self):
        t0 = time.perf_counter()
        score = run_scenario("diurnal", seed=0, replicas=500)
        wall = time.perf_counter() - t0
        assert wall < 60.0, f"500-replica diurnal took {wall:.1f}s"
        assert score["replicas_peak"] >= 100  # it really ramped
        assert score["decisions"].get("scale_up", 0) > 0
        _no_leaks(score)

    def test_domain_outage_pr16_invariants_at_100_replicas(self):
        score = run_scenario("domain_outage", seed=7, replicas=100)
        # exactly-once mass detection of the dead zone
        assert score["domain_outages_total"] == 1
        # herd re-route stayed inside the retry budget's bound
        assert score["retry_amplification"] <= 1.2
        assert score["completed"] > 0
        _no_leaks(score)

    def test_chaos_fleet_consumes_every_fault(self):
        score = run_scenario("chaos_fleet", seed=1)
        assert score["domain_outages_total"] == 1
        assert len(score["faults_fired"]) == 1
        # both seeded actuator faults were pulled through the real
        # bounded-retry machinery, none left pending
        assert score["autoscale_faults_pending"] == 0
        assert score["actuator_failures_total"] == 2
        assert score["retry_amplification"] <= 1.2
        _no_leaks(score)

    def test_cold_start_storm_uses_warm_path_after_first_boot(self):
        score = run_scenario("cold_start_storm", seed=0)
        assert score["zero_decisions"] >= 1
        assert score["wakes"] >= 1
        # r21 split: wakes after the first boot ride the warm path
        assert score["cold_starts_warm"] >= 1
        assert 0 < score["cold_start_warm_ewma_s"] \
            <= score["cold_start_ewma_s"]
        _no_leaks(score)

    def test_noisy_neighbor_is_shed_at_the_door(self):
        score = run_scenario("noisy_neighbor", seed=0)
        assert score["noisy_shed"] > 0
        assert score["shed"].get("rate_limited", 0) > 0
        # the flood never starved the well-behaved classes
        assert score["completed"] > 0
        _no_leaks(score)
