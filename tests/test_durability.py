"""Control-plane durability (ISSUE 5): WAL + snapshot recovery edges.

The unit/integration half of the crash story — byte-level WAL edge
cases (torn tail, mid-log corruption, snapshot+replay equivalence,
``resourceVersion`` monotonicity), the bounded-watch TOO_OLD relist
contract at all three layers (store, controller, apiserver), the
request-body cap, the InferenceLogger drain-on-stop, and
restart-mid-reconcile producing zero duplicate pods.  The seeded
chaos kill/restart schedules live in tests/test_chaos.py.
"""

import json
import os
import socket
import threading
import time

import pytest

from kubeflow_tpu.api import Container, JaxJob, ObjectMeta, ReplicaSpec, Resources
from kubeflow_tpu.api.common import RestartPolicy
from kubeflow_tpu.api.jaxjob import KIND_JAXJOB
from kubeflow_tpu.api.yaml_io import to_dict
from kubeflow_tpu.chaos import FaultPlan
from kubeflow_tpu.controlplane import Cluster, FakeKubelet, KIND_POD, PodScript
from kubeflow_tpu.controlplane.apiserver import MAX_BODY_BYTES, ApiServer
from kubeflow_tpu.controlplane.objects import KIND_SERVICE, PodPhase, Service
from kubeflow_tpu.controlplane.store import TOO_OLD, Store, WatchEvent
from kubeflow_tpu.controlplane.wal import LOG_NAME, SNAP_NAME, Wal, WalCorrupt


def wait_for(fn, timeout=15.0, interval=0.02, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def _svc(name, **labels):
    return Service(metadata=ObjectMeta(name=name, labels=labels))


def _dump(store):
    """Canonical object-set image for replay-equivalence comparison."""
    out = {}
    for kind in ("Service", "Pod", "Node", "JaxJob"):
        for o in store.list(kind):
            out[(o.kind, o.key)] = to_dict(o)
    return out


class TestWalRecovery:
    def test_replay_equivalence_and_rv_resume(self, tmp_path):
        """Reopened store == live store, and the rv counter resumes past
        everything recovered so optimistic concurrency holds."""
        d = str(tmp_path)
        s = Store.open(d, fsync_every=4)
        for i in range(12):
            s.create(_svc(f"svc-{i}"))
        s.delete(KIND_SERVICE, "svc-3")
        s.update_with_retry(
            KIND_SERVICE, "svc-5", "default",
            lambda o: o.metadata.labels.update({"touched": "yes"}))
        live, live_rv = _dump(s), s._last_rv
        s.close()

        s2 = Store.open(d)
        assert _dump(s2) == live
        assert s2._last_rv == live_rv
        assert s2.try_get(KIND_SERVICE, "svc-3") is None  # delete replayed
        assert s2.get(KIND_SERVICE, "svc-5").metadata.labels["touched"] == "yes"
        # a post-restart write wins any rv conflict against recovered state
        created = s2.create(_svc("after"))
        assert created.metadata.resource_version > live_rv
        s2.close()

    def test_rv_strictly_monotonic_across_many_restarts(self, tmp_path):
        d = str(tmp_path)
        seen = []
        for gen in range(4):
            s = Store.open(d)
            obj = s.create(_svc(f"gen-{gen}"))
            seen.append(obj.metadata.resource_version)
            s.close()
        assert seen == sorted(set(seen)), seen

    def test_torn_tail_tolerated_and_truncated(self, tmp_path):
        """A record cut mid-write by the crash is dropped and the file
        truncated back — that write was never acknowledged durable."""
        d = str(tmp_path)
        s = Store.open(d)
        for i in range(5):
            s.create(_svc(f"svc-{i}"))
        s.close()
        log_path = os.path.join(d, LOG_NAME)
        good = os.path.getsize(log_path)
        for torn in (b"d3adb33f {\"rv\": 99",        # no newline
                     b"00000000 {\"rv\": 99}\n"):    # bad CRC at tail
            with open(log_path, "ab") as f:
                f.write(torn)
            s2 = Store.open(d)
            assert len(s2.list(KIND_SERVICE)) == 5
            s2.close()
            assert os.path.getsize(log_path) == good  # truncated back

    def test_midlog_corruption_fails_loudly(self, tmp_path):
        """A bad record with committed records AFTER it means the medium
        lied — replay must raise, never silently skip history."""
        d = str(tmp_path)
        s = Store.open(d)
        for i in range(6):
            s.create(_svc(f"svc-{i}"))
        s.close()
        log_path = os.path.join(d, LOG_NAME)
        lines = open(log_path, "rb").read().splitlines(keepends=True)
        assert len(lines) >= 3
        lines[1] = b"00000000" + lines[1][8:]  # CRC now wrong, not the tail
        with open(log_path, "wb") as f:
            f.writelines(lines)
        with pytest.raises(WalCorrupt):
            Store.open(d)

    def test_snapshot_compaction_replay(self, tmp_path):
        """Past ``snapshot_every`` the log compacts into snapshot.json;
        replay = snapshot + newer records, same object set."""
        d = str(tmp_path)
        s = Store.open(d, snapshot_every=8)
        for i in range(30):
            s.create(_svc(f"svc-{i}"))
        s.delete(KIND_SERVICE, "svc-0")
        live = _dump(s)
        s.close()
        assert os.path.exists(os.path.join(d, SNAP_NAME))
        # compaction kept the log to the post-snapshot suffix
        raw = open(os.path.join(d, LOG_NAME), "rb").read()
        assert 0 < len(raw.splitlines()) <= 8
        s2 = Store.open(d)
        assert _dump(s2) == live
        s2.close()

    def test_stale_records_behind_snapshot_skipped(self, tmp_path):
        """A crash between snapshot rename and log truncation leaves
        already-snapshotted records in the log; replay filters them by
        rv instead of double-applying."""
        d = str(tmp_path)
        s = Store.open(d)
        for i in range(4):
            s.create(_svc(f"svc-{i}"))
        live = _dump(s)
        # snapshot everything, then put the pre-snapshot records BACK
        # (the crash-between-rename-and-truncate picture)
        stale = open(os.path.join(d, LOG_NAME), "rb").read()
        s._wal.write_snapshot(
            s._last_rv, [to_dict(o) for o in s._objs.values()])
        s.close()
        with open(os.path.join(d, LOG_NAME), "ab") as f:
            f.write(stale)
        s2 = Store.open(d)
        assert _dump(s2) == live
        assert s2._last_rv == 4
        s2.close()

    def test_crashpoint_drops_later_writes_and_tears_tail(self, tmp_path):
        """The chaos kill switch: at the seeded offset nothing later
        persists and at most torn_bytes of the in-flight record do."""
        d = str(tmp_path)
        plan = FaultPlan(seed=3).control_plane_crash(after_records=3,
                                                     torn_bytes=9)
        cp = plan.wal_crashpoint()
        assert plan.wal_crashpoint() is cp  # memoized: one shared handle
        s = Store.open(d, crashpoint=cp)
        for i in range(10):
            s.create(_svc(f"svc-{i}"))
        assert cp.fired.is_set()
        assert len(s.list(KIND_SERVICE)) == 10  # the dying process's view
        s.close()
        s2 = Store.open(d)  # recovery: 3 durable records, tail torn away
        assert sorted(o.metadata.name for o in s2.list(KIND_SERVICE)) == [
            "svc-0", "svc-1", "svc-2"]
        s2.close()

    def test_oversized_torn_bytes_never_persists_whole_record(self, tmp_path):
        """torn_bytes past the record length clamps below it — the
        in-flight write died with the machine, it must NOT replay as
        committed."""
        d = str(tmp_path)
        plan = FaultPlan(seed=1).control_plane_crash(after_records=2,
                                                     torn_bytes=10_000)
        s = Store.open(d, crashpoint=plan.wal_crashpoint())
        for i in range(4):
            s.create(_svc(f"svc-{i}"))
        s.close()
        s2 = Store.open(d)
        assert sorted(o.metadata.name for o in s2.list(KIND_SERVICE)) == [
            "svc-0", "svc-1"]
        s2.close()

    def test_compaction_triggers_across_restarts(self, tmp_path):
        """The reopened log's backlog counts toward snapshot_every: a
        plane restarted every few writes still compacts instead of
        growing wal.jsonl forever."""
        d = str(tmp_path)
        for gen in range(4):
            s = Store.open(d, snapshot_every=8)
            for i in range(3):  # 3 < snapshot_every per incarnation
                s.create(_svc(f"g{gen}-{i}"))
            s.close()
        assert os.path.exists(os.path.join(d, SNAP_NAME))
        raw = open(os.path.join(d, LOG_NAME), "rb").read()
        assert len(raw.splitlines()) < 12  # compacted, not 12 records
        s = Store.open(d)
        assert len(s.list(KIND_SERVICE)) == 12
        s.close()

    def test_wal_append_after_close_is_noop(self, tmp_path):
        w = Wal(str(tmp_path))
        w.recover()
        w.append({"rv": 1, "op": "put", "obj": {}})
        w.close()
        w.append({"rv": 2, "op": "put", "obj": {}})  # must not raise
        w2 = Wal(str(tmp_path))
        _, _, records = w2.recover()
        assert [r["rv"] for r in records] == [1]
        w2.close()


class TestBoundedWatch:
    def test_overflow_closes_watch_with_too_old_marker(self):
        """A slow subscriber's queue hits its bound: the watch closes
        with a TOO_OLD marker instead of growing memory or silently
        dropping events."""
        s = Store()
        w = s.watch([KIND_SERVICE], maxsize=4)
        for i in range(8):
            s.create(_svc(f"svc-{i}"))
        assert w.closed and w.too_old
        assert w not in s._watches  # no further fan-out to it
        events = []
        while not w.q.empty():
            events.append(w.q.get_nowait())
        assert events[-1].type == TOO_OLD and events[-1].obj is None
        # bounded: never held more than maxsize events
        assert len(events) <= 4

    def test_healthy_watch_unaffected(self):
        s = Store()
        w = s.watch([KIND_SERVICE])
        s.create(_svc("a"))
        ev = w.q.get(timeout=1)
        assert ev.type == "ADDED" and ev.obj.metadata.name == "a"
        assert not w.too_old

    def test_controller_relists_after_too_old(self):
        """A controller that sees TOO_OLD re-watches and relists — the
        overflowed events are recovered by listing, never missed."""
        from kubeflow_tpu.controlplane.jaxjob_controller import JaxJobController

        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=2, chips_per_host=4)
        kubelet = FakeKubelet(
            c.store, lambda pod: PodScript(run_seconds=30.0))
        with c:
            kubelet.start()
            try:
                ctrl = next(x for x in c.controllers
                            if isinstance(x, JaxJobController))
                c.store.create(JaxJob(
                    metadata=ObjectMeta(name="j"),
                    spec={"replica_specs": {"worker": ReplicaSpec(
                        replicas=2,
                        template=Container(
                            resources=Resources(cpu=1, memory_gb=1, tpu=4)),
                    )}}))
                wait_for(
                    lambda: sum(
                        p.status.phase == PodPhase.RUNNING
                        for p in c.store.list(KIND_POD)) == 2,
                    desc="gang running")
                # simulate the overflow: store closed the watch and left
                # the marker; then delete a pod THROUGH the store (an
                # event the dead watch never delivers)
                old_watch = ctrl._watch
                c.store.stop_watch(old_watch)
                victim = c.store.list(KIND_POD)[0]
                c.store.delete(KIND_POD, victim.metadata.name,
                               victim.metadata.namespace)
                old_watch.q.put(WatchEvent(TOO_OLD, None))
                # the relist must notice the missing gang member and the
                # controller re-create it
                wait_for(
                    lambda: sum(
                        p.status.phase == PodPhase.RUNNING
                        for p in c.store.list(KIND_POD)) == 2,
                    desc="gang re-formed after relist")
                assert ctrl._watch is not old_watch
            finally:
                kubelet.stop()

    def test_local_kubelet_relists_and_kills_on_too_old(self, tmp_path):
        """The real runtime's deletion watcher: a TOO_OLD marker means
        deletes were dropped — it must re-subscribe and kill any local
        process whose pod no longer exists, never leave it unkilled."""
        from kubeflow_tpu.runtime.launcher import LocalKubelet

        s = Store()
        k = LocalKubelet(s, root_dir=str(tmp_path))
        k._watch = s.watch([KIND_POD])
        killed = []
        k._kill = killed.append
        k._procs = {"default/ghost": object()}  # pod deleted in the gap
        old_watch = k._watch
        old_watch.q.put(WatchEvent(TOO_OLD, None))
        k._drain_deletions()
        assert killed == ["default/ghost"]
        assert k._watch is not old_watch  # fresh subscription

    def test_apiserver_pump_resubscribes_and_410s_cursors(self):
        """The apiserver's store watch overflowing expires EVERY client
        cursor (410 Gone) — events dropped before they got a seq can
        never be resumed over."""
        import urllib.error
        import urllib.request

        s = Store()
        api = ApiServer(s)
        try:
            s.create(_svc("first"))
            # a client cursor established before the overflow
            with urllib.request.urlopen(
                    f"{api.url}/apis/Service?watch=1&cursor=0&timeout=5",
                    timeout=10) as r:
                cursor = json.load(r)["cursor"]
            assert cursor >= 1
            old_watch = api._store_watch
            old_watch.q.put(WatchEvent(TOO_OLD, None))
            wait_for(lambda: api._store_watch is not old_watch,
                     desc="pump resubscribe")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{api.url}/apis/Service?watch=1&cursor={cursor}"
                    "&timeout=5", timeout=10)
            assert ei.value.code == 410
            # the new subscription still delivers future events
            s.create(_svc("second"))
            resync = json.loads(ei.value.read())["cursor"]
            with urllib.request.urlopen(
                    f"{api.url}/apis/Service?watch=1&cursor={resync}"
                    "&timeout=5", timeout=10) as r:
                items = json.load(r)["items"]
            assert any(i["object"]["metadata"]["name"] == "second"
                       for i in items)
        finally:
            api.stop()


class TestBodyCap:
    def test_oversized_content_length_rejected_413(self):
        """The server must not allocate whatever the client's
        Content-Length claims — reject before reading."""
        s = Store()
        api = ApiServer(s)
        try:
            with socket.create_connection(("127.0.0.1", api.port),
                                          timeout=5) as sock:
                sock.sendall(
                    b"POST /apis/Service HTTP/1.1\r\n"
                    b"Host: x\r\nContent-Type: application/json\r\n"
                    + f"Content-Length: {MAX_BODY_BYTES + 1}\r\n\r\n".encode())
                sock.settimeout(5)
                # the server closes the poisoned connection: read to EOF
                # (status line and JSON body may arrive in separate
                # segments)
                chunks = []
                while True:
                    b = sock.recv(4096)
                    if not b:
                        break
                    chunks.append(b)
                head = b"".join(chunks).decode()
            assert " 413 " in head.splitlines()[0]
            assert "RequestEntityTooLarge" in head
            # the server is still healthy for well-formed requests
            import urllib.request

            with urllib.request.urlopen(f"{api.url}/healthz", timeout=5) as r:
                assert json.load(r)["ok"] is True
        finally:
            api.stop()


class TestRestartMidReconcile:
    def test_zero_duplicate_pods_after_crash_during_scaleup(self, tmp_path):
        """kill -9 while the controller is mid-way through creating gang
        pods: the restarted control plane rebuilds Expectations from
        observed pods and adopts kubelet-re-reported survivors — never
        double-creates a (replica-type, index) slot."""
        d = str(tmp_path / "data")
        plan = FaultPlan(seed=11).control_plane_crash(after_records=10,
                                                      torn_bytes=7)
        cp = plan.wal_crashpoint()
        c = Cluster(data_dir=d, wal_crashpoint=cp)
        c.add_tpu_slice("s0", num_hosts=4, chips_per_host=4)
        kubelet = FakeKubelet(
            c.store, lambda pod: PodScript(run_seconds=60.0), chaos=plan)
        c.start()
        kubelet.start()
        try:
            c.store.create(JaxJob(
                metadata=ObjectMeta(name="j"),
                spec={"replica_specs": {"worker": ReplicaSpec(
                    replicas=4, restart_policy=RestartPolicy.ON_FAILURE,
                    template=Container(
                        resources=Resources(cpu=1, memory_gb=1, tpu=4)),
                )}}))
            assert cp.fired.wait(20), "crashpoint never fired"
        finally:
            c.stop()  # the dead incarnation's threads reaped

        c2 = Cluster(data_dir=d)
        kubelet.attach_store(c2.store)  # node survived; relist BEFORE start
        c2.start()
        try:
            wait_for(
                lambda: sum(
                    p.status.phase == PodPhase.RUNNING
                    for p in c2.store.list(KIND_POD)
                    if p.metadata.name.startswith("j-")) == 4,
                desc="gang running after restart")
            pods = [p for p in c2.store.list(KIND_POD)
                    if p.metadata.name.startswith("j-")]
            slots = [(p.metadata.labels.get("replica-type"),
                      p.metadata.labels.get("replica-index"))
                     for p in pods]
            assert len(pods) == 4
            assert len(set(slots)) == 4, f"duplicate slots: {slots}"
            # zero orphans: every pod owned by the recovered job
            assert all(
                any(r.kind == KIND_JAXJOB and r.name == "j" and r.controller
                    for r in p.metadata.owner_references)
                for p in pods)
        finally:
            kubelet.stop()
            c2.stop()


class TestInferenceLoggerDrain:
    def _sink(self, delay=0.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        hits = []

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                if delay:
                    time.sleep(delay)
                n = int(self.headers.get("Content-Length", 0))
                hits.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, f"http://127.0.0.1:{httpd.server_address[1]}", hits

    def test_stop_drains_queued_events(self):
        """Events enqueued before stop() are delivered, not silently
        dropped with the pump's exit."""
        from kubeflow_tpu.serving.server import InferenceLogger

        httpd, url, hits = self._sink(delay=0.02)
        try:
            logger = InferenceLogger(url, service="svc")
            for i in range(10):
                logger.log("request", "m", f"r{i}", {"i": i})
            logger.stop(drain_timeout=10.0)
            assert len(hits) + logger.dropped == 10
            assert len(hits) == 10, f"dropped {logger.dropped} on shutdown"
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_undeliverable_remainder_counted_dropped(self):
        """A dead sink under a tight deadline: what could not be flushed
        lands in ``dropped`` instead of vanishing."""
        from kubeflow_tpu.serving.server import InferenceLogger

        # nothing listens on this port (connect fails fast)
        logger = InferenceLogger("http://127.0.0.1:9/", service="svc")
        logger._stop.set()  # park the pump path: nothing will drain
        logger._thread.join(timeout=2)
        for i in range(5):
            logger.log("request", "m", f"r{i}", {"i": i})
        logger.stop(drain_timeout=0.1)
        assert logger.dropped == 5
