"""LoRA fine-tuning (SURVEY §3.5 — the reference train()'s peft path;
r4 verdict missing #5).

Frozen base + rank-r q/v adapters via optax.multi_transform, adapter-
only checkpoints + save_adapter snapshots, serve-side merge.  The merge
bar: a merged plain model must generate the SAME greedy tokens as the
adapter model.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.train import trainer as trainlib


def _base_snapshot(tmp_path, seed=0):
    cfg = llamalib.tiny()
    params = nn.meta.unbox(llamalib.Llama(cfg).init(
        jax.random.PRNGKey(seed), jnp.ones((1, 8), jnp.int32))["params"])
    path = str(tmp_path / "base")
    llamalib.save_pretrained(path, cfg, params)
    return cfg, params, path


def _param_sizes(params):
    from flax import traverse_util

    flat = traverse_util.flatten_dict(params)
    lora = sum(v.size for k, v in flat.items() if llamalib.is_lora_path(k))
    total = sum(v.size for v in flat.values())
    return lora, total


class TestLoraModel:
    def test_zero_init_b_means_base_function(self, tmp_path):
        """B = 0 at init: the adapter model's step-0 logits ARE the base
        model's (the property that makes fine-tuning start from the
        snapshot, not near it)."""
        import dataclasses

        cfg, params, path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=8)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=lcfg, steps=1, global_batch=8, seq_len=16,
            init_from=path))
        state = t.init_state()
        toks = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
        base = llamalib.Llama(cfg).apply({"params": params}, toks)
        lora = llamalib.Llama(lcfg).apply(
            {"params": jax.device_get(state["params"])}, toks)
        assert np.array_equal(np.asarray(base), np.asarray(lora))

    def test_trainable_fraction_under_5pct(self, tmp_path):
        import dataclasses

        cfg, _, path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=8)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=lcfg, steps=1, global_batch=8, seq_len=16,
            init_from=path))
        lora, total = _param_sizes(t.init_state()["params"])
        assert 0 < lora < 0.05 * total, (lora, total)

    def test_base_frozen_adapters_move(self, tmp_path):
        import dataclasses

        cfg, base_params, path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=4)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=lcfg, steps=3, global_batch=8, seq_len=16,
            init_from=path, warmup_steps=1, log_every=1))
        t.train()
        final = jax.device_get(t.final_state["params"])
        # base kernels: bit-identical to the snapshot
        wq = final["layers"]["block"]["attn"]["wq"]
        assert np.array_equal(
            np.asarray(wq["kernel"]),
            np.asarray(base_params["layers"]["block"]["attn"]["wq"]["kernel"]))
        # adapters: B must have left zero
        assert np.abs(np.asarray(wq["lora_b"])).max() > 0
        # non-target projection has no adapters at all
        assert "lora_a" not in final["layers"]["block"]["attn"]["wo"]

    def test_merge_math_parity(self, tmp_path):
        """Serve-side merge: merged plain model == adapter model, on
        logits (tolerance: merged folds the delta into the kernel, so
        float association differs) AND on greedy tokens (exact)."""
        import dataclasses

        cfg, _, path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=4)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=lcfg, steps=3, global_batch=8, seq_len=16,
            init_from=path, warmup_steps=1))
        t.train()
        params = jax.device_get(t.final_state["params"])
        base, adapters = llamalib.split_lora(params)
        mcfg, merged = llamalib.merge_adapter(lcfg, base, adapters)
        assert mcfg.lora_rank == 0
        toks = jnp.asarray([[3, 1, 4, 1, 5, 9]], jnp.int32)
        want = np.asarray(llamalib.Llama(lcfg).apply(
            {"params": params}, toks), np.float32)
        got = np.asarray(llamalib.Llama(mcfg).apply(
            {"params": merged}, toks), np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        assert np.array_equal(want.argmax(-1), got.argmax(-1))


class TestLoraCheckpointAndPublish:
    def test_adapter_only_checkpoint_resume(self, tmp_path):
        """Checkpoints persist {step, opt_state, adapters} only; resume
        rebuilds the base from init_from and restores the adapters."""
        import dataclasses

        cfg, base_params, path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=4)
        ckpt = str(tmp_path / "ckpt")
        tc = trainlib.TrainConfig(
            model=lcfg, steps=2, global_batch=8, seq_len=16,
            init_from=path, checkpoint_dir=ckpt, save_interval_steps=1,
            warmup_steps=1)
        t = trainlib.Trainer(tc)
        t.train()
        trained = jax.device_get(t.final_state["params"])

        t2 = trainlib.Trainer(tc)
        state = t2.restore_or_init()
        assert int(jax.device_get(state["step"])) == 2
        restored = jax.device_get(state["params"])
        wq = restored["layers"]["block"]["attn"]["wq"]
        assert np.array_equal(
            np.asarray(wq["lora_b"]),
            np.asarray(trained["layers"]["block"]["attn"]["wq"]["lora_b"]))
        assert np.array_equal(
            np.asarray(wq["kernel"]),
            np.asarray(base_params["layers"]["block"]["attn"]["wq"]["kernel"]))

    def test_save_adapter_is_small_and_serves_merged(self, tmp_path):
        """save_adapter writes MB-scale artifacts; the serving config's
        adapter_path merges at load and the engine serves it."""
        import dataclasses

        from kubeflow_tpu.serving.continuous import ContinuousLlamaGenerator

        cfg, _, base_path = _base_snapshot(tmp_path)
        lcfg = dataclasses.replace(cfg, lora_rank=4)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=lcfg, steps=2, global_batch=8, seq_len=16,
            init_from=base_path, warmup_steps=1))
        t.train()
        params = jax.device_get(t.final_state["params"])
        adapter_path = str(tmp_path / "adapter")
        llamalib.save_adapter(adapter_path, lcfg, params)
        base_bytes = os.path.getsize(
            os.path.join(base_path, "weights.msgpack"))
        adapter_bytes = os.path.getsize(
            os.path.join(adapter_path, "adapter.msgpack"))
        assert adapter_bytes < 0.05 * base_bytes

        want = [t_greedy(lcfg, params, [1, 2, 3], 4)]
        gen = ContinuousLlamaGenerator("ft", {
            "storage_path": base_path, "adapter_path": adapter_path,
            "num_slots": 2, "decode_chunk": 2, "max_new_tokens": 4,
            "warmup_groups": []})
        gen.start()
        try:
            got = gen.predict_batch([[1, 2, 3]])
        finally:
            gen.stop()
        assert got == want


def t_greedy(cfg, params, prompt, n):
    model = llamalib.Llama(cfg)
    toks = list(prompt)
    for _ in range(n):
        logits = model.apply(
            {"params": params}, jnp.asarray([toks], jnp.int32))
        toks.append(int(np.argmax(np.asarray(logits[0, -1], np.float32))))
    return toks[len(prompt):]


@pytest.mark.e2e
class TestLoraE2E:
    def test_two_worker_lora_finetune_publish_serve(self, tmp_path):
        """The verdict's e2e: pretrain -> publish base ->
        TrainingClient.train(model=..., lora_rank=8) as a 2-worker gang
        (loss continues from the converged base, FAR below scratch ~5.55
        — proof the frozen base loaded) -> adapter published -> served
        merged."""
        import re

        from kubeflow_tpu.api.common import JobConditionType, has_condition
        from kubeflow_tpu.runtime.platform import LocalPlatform
        from kubeflow_tpu.sdk import TrainingClient
        from kubeflow_tpu.serving.continuous import ContinuousLlamaGenerator

        # pretrain in-process to convergence, publish the base
        cfg = llamalib.tiny()
        pre = trainlib.Trainer(trainlib.TrainConfig(
            model=cfg, steps=80, learning_rate=1e-2, global_batch=8,
            seq_len=32, warmup_steps=5, log_every=20))
        final = pre.train()
        assert final.loss < 3.0, f"pretrain did not converge: {final.loss}"
        base_path = str(tmp_path / "base")
        llamalib.save_pretrained(
            base_path, cfg, jax.device_get(pre.final_state["params"]))

        adapter_pub = str(tmp_path / "published_adapter")
        with LocalPlatform(num_hosts=2, chips_per_host=4,
                           root_dir=str(tmp_path / "plat")) as p:
            client = TrainingClient(p)
            job = client.train(
                name="lora-ft", entrypoint="kubeflow_tpu.train.llm:train_main",
                num_workers=2, model=f"file://{base_path}", lora_rank=8,
                publish_to=adapter_pub,
                env={"KFT_STEPS": "4", "KFT_BATCH": "8",
                     "KFT_SEQ_LEN": "32", "KFT_LOG_EVERY": "1",
                     "KFT_LR": "1e-4"},
                timeout=420.0)
            assert has_condition(
                job.status.conditions, JobConditionType.SUCCEEDED)
            log = client.get_job_logs("lora-ft")["lora-ft-worker-0"]
        losses = [float(m) for m in re.findall(r"loss=([0-9.]+)", log)]
        assert losses, log
        # scratch starts at ~ln(256)=5.55; the frozen base left off <3
        assert losses[0] < 3.5, losses
        # the published artifact is the ADAPTER, not a full snapshot
        assert os.path.exists(os.path.join(adapter_pub, "adapter.msgpack"))
        assert not os.path.exists(
            os.path.join(adapter_pub, "weights.msgpack"))

        # serve base + published adapter, merged at load
        gen = ContinuousLlamaGenerator("ft", {
            "storage_path": base_path, "adapter_path": adapter_pub,
            "num_slots": 2, "decode_chunk": 2, "max_new_tokens": 4,
            "warmup_groups": []})
        gen.start()
        try:
            out = gen.predict_batch([[1, 2, 3]])
        finally:
            gen.stop()
        assert len(out[0]) == 4
