"""Paged-KV block allocator (ISSUE 6): one block-granular KV economy.

Four layers, matching the tentpole:

- BlockAllocator units: free-list discipline, refcounts, LRU reuse
  WITHOUT clearing, registry invalidation on reallocation;
- engine parity: the paged programs are pinned BIT-IDENTICAL (greedy)
  to the pre-paged slot pool across plain / chunked / prefix-shared /
  speculative / int8-KV variants, with ``jit_recompiles_total == 0``
  in steady state;
- allocator edge cases THROUGH the engine: pool-exhaustion admission
  backpressure, COW fork on shared-prefix divergence,
  refcount-to-zero block reuse, cancel-mid-prefill returning blocks
  while the partial prefix stays matchable;
- the gang: followers replay block-table ops bit-identically, and a
  seeded chaos socket drop mid-paged-decode converges after replay.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine, TieredEngine
from kubeflow_tpu.serving.paged import BlockAllocator


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64 tokens = 4 blocks at block_size 16


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("block_size", 16)
    return ContinuousEngine(cfg, params, **kw)


@pytest.fixture(scope="module")
def slot_pool_tokens(tiny_llama):
    """Greedy oracle: the pre-paged contiguous slot pool."""
    cfg, params = tiny_llama
    eng = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                           prefix_cache=False)
    try:
        return {
            "long": eng.generate(LONG, max_new_tokens=6),
            "short": eng.generate([7, 8, 9], max_new_tokens=6),
            "victim": eng.generate([7, 8, 9], max_new_tokens=40),
        }
    finally:
        eng.stop()


class TestBlockAllocator:
    def test_alloc_release_refcounts(self):
        a = BlockAllocator(num_blocks=4, block_size=8)
        b1 = a.alloc(3)
        assert len(b1) == 3 and a.free_blocks == 1
        assert a.alloc(2) is None          # backpressure, nothing taken
        assert a.free_blocks == 1
        a.ref(b1[:2])                      # shared by a second sequence
        a.release(b1)
        assert a.free_blocks == 2          # 2 still referenced
        a.release(b1[:2])
        assert a.free_blocks == 4
        with pytest.raises(RuntimeError, match="over-released"):
            a.release([b1[0]])

    def test_reuse_is_lru_and_unclered_registry_survives(self):
        """Freed blocks recycle oldest-freed first, and a registered
        sequence stays matchable until one of ITS blocks is actually
        handed out again (the free list doubles as the prefix cache)."""
        a = BlockAllocator(num_blocks=4, block_size=4)
        s1 = a.alloc(2)
        s2 = a.alloc(2)
        a.register(list(range(8)), s1)     # 8 tokens over s1
        a.release(s1)                      # freed FIRST
        a.release(s2)
        blocks, lcp = a.match(np.arange(8, dtype=np.int64), 7)
        assert tuple(blocks) == tuple(s1) and lcp == 7
        # resurrect by ref: comes OFF the free list, stays registered
        a.ref(s1)
        assert a.free_blocks == 2
        a.release(s1)
        # the resurrection cycle made s1 most-recently-freed: alloc
        # recycles OLDEST-freed first, so s2 is consumed and the
        # registration over s1 SURVIVES (LRU as cache retention)
        got = a.alloc(2)
        assert set(got) == set(s2)
        blocks, lcp = a.match(np.arange(8, dtype=np.int64), 7)
        assert tuple(blocks) == tuple(s1) and lcp == 7
        # consuming s1's blocks finally kills the registration
        got2 = a.alloc(2)
        assert set(got2) == set(s1)
        assert a.match(np.arange(8, dtype=np.int64), 7) == ((), 0)

    def test_partial_registration_needs_full_block(self):
        a = BlockAllocator(num_blocks=2, block_size=16)
        s = a.alloc(1)
        a.register([1, 2, 3], s)           # < one block: not shareable
        a.release(s)
        assert a.match(np.asarray([1, 2, 3], np.int64), 2) == ((), 0)

    def test_registry_bounded_by_num_blocks(self):
        """A hot prefix re-registering on every retirement must not grow
        the registry (and the per-admission match scan) without bound —
        capped at num_blocks, oldest registration evicted first."""
        a = BlockAllocator(num_blocks=3, block_size=2)
        s = a.alloc(1)
        for i in range(10):
            a.register([i, i + 1], s)
        assert len(a._seqs) == 3
        # the newest registration still matches
        blocks, n = a.match(np.asarray([9, 10], np.int64), 2)
        assert n == 2 and tuple(blocks) == tuple(s)

    def test_match_caps_at_registered_blocks(self):
        a = BlockAllocator(num_blocks=4, block_size=4)
        s = a.alloc(3)
        a.register(list(range(12)), s)
        blocks, lcp = a.match(np.arange(12, dtype=np.int64), 11)
        assert lcp == 11 and tuple(blocks) == tuple(s)


class TestPagedParity:
    """Greedy tokens BIT-IDENTICAL to the pre-paged slot pool — the bar
    the whole rewrite holds (acceptance criterion 3)."""

    def test_plain_decode_parity(self, tiny_llama, slot_pool_tokens):
        eng = make_engine(tiny_llama)
        try:
            assert eng.generate(LONG, max_new_tokens=6) == \
                slot_pool_tokens["long"]
            assert eng.generate([7, 8, 9], max_new_tokens=6) == \
                slot_pool_tokens["short"]
            st = eng.stats()
            assert st["kv_blocks_total"] > 0
            assert st["jit_recompiles_total"] == 0
        finally:
            eng.stop()

    def test_chunked_admission_under_live_decode_parity(
            self, tiny_llama, slot_pool_tokens):
        """The paged fused path: a long prompt chunk-prefills through
        the gathered view WHILE another request decodes."""
        eng = make_engine(tiny_llama, decode_chunk=1, prefill_budget=8)
        try:
            victim = eng.submit([7, 8, 9], max_new_tokens=40)
            while eng.step_counter < 5:
                time.sleep(0.005)
            late = eng.submit(LONG, max_new_tokens=6)
            assert late.wait(300) == slot_pool_tokens["long"]
            assert victim.wait(300) == slot_pool_tokens["victim"]
            assert eng.prefill_chunks_dispatched >= 8
        finally:
            eng.stop()

    def test_block_prefix_sharing_parity_and_zero_copy(
            self, tiny_llama, slot_pool_tokens):
        """A resent prompt shares its full blocks by refcount — no
        prefill for the shared span — and still emits the oracle's
        exact tokens."""
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            a = eng.generate(LONG, max_new_tokens=6)
            b = eng.generate(LONG, max_new_tokens=6)
            assert eng.prefix_hits == 1
            assert eng.stats()["prefix_block_hits_total"] >= 3
            assert eng.prefix_tokens_saved >= 48  # 3 full blocks + COW
        finally:
            eng.stop()
        assert a == slot_pool_tokens["long"]
        assert b == slot_pool_tokens["long"]

    @pytest.mark.slow
    def test_speculative_parity(self, tiny_llama):
        """Paged verify: spec-on greedy == spec-off greedy, block tables
        under the (k+1)-wide forward."""
        cfg, params = tiny_llama
        loopy = [5, 6, 5, 6, 5, 6, 5]
        off = make_engine(tiny_llama, decode_chunk=1)
        try:
            want = off.generate(loopy, max_new_tokens=24)
        finally:
            off.stop()
        on = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            got = on.generate(loopy, max_new_tokens=24, timeout=300)
            assert on.spec_dispatches_total > 0
        finally:
            on.stop()
        assert got == want

    @pytest.mark.slow
    def test_int8_kv_parity(self, tiny_llama):
        """The int8-KV scale buffers keep seq LAST — the probed-axis
        gather/scatter must honor that layout bit-for-bit."""
        cfg, params = tiny_llama
        qcfg, qparams = llamalib.quantize_for_serving(
            cfg, params, weights=False, kv=True)
        ref = ContinuousEngine(qcfg, qparams, num_slots=2, decode_chunk=2,
                               prefix_cache=False)
        try:
            want = ref.generate(LONG, max_new_tokens=6)
        finally:
            ref.stop()
        eng = ContinuousEngine(qcfg, qparams, num_slots=2, decode_chunk=2,
                               prefix_cache=False, block_size=16)
        try:
            got = eng.generate(LONG, max_new_tokens=6)
        finally:
            eng.stop()
        assert got == want

    def test_zero_steady_state_recompiles(self, tiny_llama):
        """The paged dispatch ladder reaches steady state — admissions,
        chunked prefill through views, retirement, block reuse, prefix
        hits — without re-tracing one compiled program."""
        eng = make_engine(tiny_llama, prefill_budget=4,
                          prefix_cache=True, min_prefix=8)
        try:
            eng.warmup()
            reqs = [eng.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=6)
                    for _ in range(3)]
            for r in reqs:
                r.wait(300)
            reqs = [eng.submit([1, 2, 3, 4, 5, 6, 7, 8, 9],
                               max_new_tokens=4) for _ in range(2)]
            for r in reqs:
                r.wait(300)
            st = eng.stats()
            assert st["prefill_chunks_dispatched"] > 0
            assert st["jit_recompiles_total"] == 0, st
        finally:
            eng.stop()


class TestPagedEdgeCases:
    def test_pool_exhaustion_admission_backpressure(self, tiny_llama):
        """Too few free blocks: the request WAITS (no crash, no
        eviction) and admits once a retirement returns blocks."""
        # each request reserves ceil((3 + 30) / 16) = 3 blocks
        eng = make_engine(tiny_llama, num_slots=2, decode_chunk=1,
                          num_blocks=3)
        try:
            r1 = eng.submit([1, 2, 3], max_new_tokens=30)
            time.sleep(0.1)
            r2 = eng.submit([4, 5, 6], max_new_tokens=30)
            # r2 must be waiting on blocks, not admitted, not failed
            time.sleep(0.2)
            assert not r2.done.is_set()
            assert eng.stats()["queue_depth"] >= 1
            o1 = r1.wait(120)
            o2 = r2.wait(120)
            assert len(o1) == 30 and len(o2) == 30
            assert eng.stats()["kv_blocks_free"] == 3
        finally:
            eng.stop()

    def test_impossible_span_fails_not_spins(self, tiny_llama):
        """A request whose worst-case span exceeds the WHOLE pool can
        never admit: it must resolve with an error naming the sizing,
        not park forever in the queue (which would also busy-spin an
        idle scheduler)."""
        eng = make_engine(tiny_llama, num_slots=2, num_blocks=2)
        try:
            # ceil((30 + 40) / 16) = 5 blocks > 2 in the whole pool
            req = eng.submit(list(range(1, 31)), max_new_tokens=40)
            with pytest.raises(RuntimeError, match="num_blocks"):
                req.wait(30)
            # the engine keeps serving feasible requests afterwards
            assert len(eng.generate([1, 2, 3], max_new_tokens=4)) == 4
        finally:
            eng.stop()

    def test_cow_fork_on_shared_prefix_divergence(self, tiny_llama):
        """A prompt diverging MID-block forks the boundary block with
        one device copy: the source sequence's block is untouched, the
        fork's tokens match a cold run exactly."""
        cfg, params = tiny_llama
        div = LONG[:40] + [200, 201, 202]  # diverges inside block 2
        ref = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                               prefix_cache=False)
        try:
            want_long = ref.generate(LONG, max_new_tokens=6)
            want_div = ref.generate(div, max_new_tokens=6)
        finally:
            ref.stop()
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            assert eng.generate(LONG, max_new_tokens=6) == want_long
            got = eng.generate(div, max_new_tokens=6)
            st = eng.stats()
            assert st["kv_blocks_cow_copies_total"] >= 1
            # shared 2 full blocks by ref + forked to token 40
            assert eng.prefix_tokens_saved >= 40
            assert got == want_div
            # the ORIGINAL conversation's prefix must still be intact:
            # resend it and check tokens again (a COW bug would have
            # let the fork scribble on the shared source block)
            assert eng.generate(LONG, max_new_tokens=6) == want_long
        finally:
            eng.stop()

    def test_refcount_zero_block_reuse_without_clearing(self, tiny_llama):
        """Retired blocks recycle to NEW occupants uncleaned; stale
        bytes must never leak into a later generation (the slot pool's
        stale-KV argument at block granularity)."""
        cfg, params = tiny_llama
        prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
        ref = ContinuousEngine(cfg, params, num_slots=2, decode_chunk=2,
                               prefix_cache=False)
        try:
            want = [ref.generate(p, max_new_tokens=4) for p in prompts]
        finally:
            ref.stop()
        # 2 slots x 6 requests: every admission after the second reuses
        # freed blocks; num_blocks sized so reuse MUST happen
        eng = make_engine(tiny_llama, num_slots=2, num_blocks=2)
        try:
            reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
            got = [r.wait(300) for r in reqs]
        finally:
            eng.stop()
        assert got == want

    def test_cancel_mid_prefill_returns_blocks_keeps_prefix(
            self, tiny_llama, slot_pool_tokens):
        """Cancel mid-chunked-prefill: the slot AND its blocks free at
        the next boundary, yet the partial KV stays prefix-matchable —
        the resubmit resurrects the written blocks instead of
        re-prefilling them."""
        eng = make_engine(tiny_llama, num_slots=2, decode_chunk=1,
                          prefix_cache=True, min_prefix=8,
                          prefill_budget=16)
        from kubeflow_tpu.analysis.runtime import BlockLedger

        ledger = BlockLedger()
        eng.attach_block_ledger(ledger)
        inner_c, inner_f = eng._paged_chunk_for, eng._paged_fused_for

        def slow(getter):
            def for_(*key):
                prog = getter(*key)

                def call(*args):
                    time.sleep(0.02)
                    return prog(*args)

                return call

            return for_

        eng._paged_chunk_for = slow(inner_c)
        eng._paged_fused_for = slow(inner_f)
        try:
            req = eng.submit(LONG, max_new_tokens=6)
            while eng.prefill_chunks_dispatched < 3:
                time.sleep(0.002)
            req.cancel()
            assert req.wait(5) == []
            deadline = time.time() + 10
            while time.time() < deadline and any(
                    r is not None for r in eng._slots):
                time.sleep(0.01)
            assert all(r is None for r in eng._slots)
            assert eng.stats()["prefill_tokens_inflight"] == 0
            # blocks returned: the ledger audit replaces the ad-hoc
            # free == total compare — it also catches refcount drift
            # the count equality could mask
            assert eng.audit_blocks() == []
            assert eng.stats()["kv_blocks_leaked_total"] == 0
            assert ledger.conservation_errors == []
            # ... and the >= 3 written chunks are still matchable
            got = eng.generate(LONG, max_new_tokens=6)
            assert eng.prefix_hits >= 1
            assert eng.stats()["prefix_block_hits_total"] >= 1
            assert got == slot_pool_tokens["long"]
        finally:
            eng.stop()

    def test_retired_sequence_resurrection(self, tiny_llama,
                                           slot_pool_tokens):
        """A conversation retired long ago (slot since REUSED by other
        traffic) still shares its blocks as long as they sat unclaimed
        on the free list."""
        eng = make_engine(tiny_llama, num_slots=1, prefix_cache=True,
                          min_prefix=8, num_blocks=16)
        try:
            assert eng.generate(LONG, max_new_tokens=6) == \
                slot_pool_tokens["long"]
            # unrelated traffic reuses the ONLY slot (not the blocks)
            eng.generate([9, 8, 7], max_new_tokens=4)
            got = eng.generate(LONG, max_new_tokens=6)
            assert eng.prefix_hits >= 1
            assert got == slot_pool_tokens["long"]
        finally:
            eng.stop()


class TestPagedTierPolicy:
    def test_quota_blocks_class_not_pool(self, tiny_llama):
        """The ladder-as-policy: a long-class burst saturating its quota
        queues BEHIND the quota while short-class admission stays open —
        on ONE paged pool."""
        cfg, params = tiny_llama
        eng = TieredEngine(cfg, params, tier_lens=[16], tier_slots=[2],
                           num_slots=4, decode_chunk=1,
                           prefix_cache=False)
        try:
            # class 1 (>=16 total): quota 2 — the third queues
            longs = [eng.submit(list(range(1, 30)), max_new_tokens=40)
                     for _ in range(3)]
            time.sleep(0.3)
            live_long = sum(
                1 for r in eng.engine._slots
                if r is not None and eng._classify(r) == 1)
            assert live_long <= 2
            # short class admits immediately despite the long backlog
            short = eng.submit([1, 2], max_new_tokens=3)
            out = short.wait(60)
            assert len(out) == 3
            for r in longs:
                r.wait(300)
        finally:
            eng.stop()

    def test_parity_against_untiered_pool(self, tiny_llama,
                                          slot_pool_tokens):
        cfg, params = tiny_llama
        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=2, prefix_cache=False)
        try:
            assert eng.generate([7, 8, 9], max_new_tokens=6) == \
                slot_pool_tokens["short"]
            assert eng.generate(LONG, max_new_tokens=6) == \
                slot_pool_tokens["long"]
        finally:
            eng.stop()


class TestPagedKnobs:
    def test_bad_block_knobs_rejected_at_engine(self, tiny_llama):
        cfg, params = tiny_llama
        with pytest.raises(ValueError, match="block_size"):
            ContinuousEngine(cfg, params, block_size=-1)
        with pytest.raises(ValueError, match="num_blocks"):
            ContinuousEngine(cfg, params, block_size=16, num_blocks=-4)
        with pytest.raises(ValueError, match="superseded"):
            ContinuousEngine(cfg, params, block_size=16,
                             prefix_segments=2, segment_len=64)
        with pytest.raises(ValueError, match="max_seq_len"):
            ContinuousEngine(cfg, params, block_size=cfg.max_seq_len)

    def test_bad_block_knob_fails_isvc_at_conf_freeze(self):
        """Satellite: a bad ``block_size`` on an ISvc is ONE Failed
        status with the knob named — caught at conf-freeze, before any
        replica constructs (no crash-looping pods)."""
        import time as _time

        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="bad-paged"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="llama-continuous"),
                    config={"params_ref": "mem://never-fetched",
                            "block_size": -8}))))
            deadline = _time.time() + 20
            isvc = None
            while _time.time() < deadline:
                isvc = cluster.store.try_get(
                    "InferenceService", "bad-paged")
                if (isvc is not None and isvc.status.phase
                        == InferenceServicePhase.FAILED):
                    break
                _time.sleep(0.05)
            assert isvc is not None
            assert isvc.status.phase == InferenceServicePhase.FAILED, \
                isvc.status
            assert "block_size" in (isvc.status.message or "")


class TestPagedGang:
    """Block-table ops cross the control stream; follower block pools
    are the leader's bit for bit (the tentpole's gang requirement)."""

    def _run_pair(self, kw, drive, sock_wrap=None, chan_kw=None):
        """(leader_tokens, ops, leader_engine, follower_engine) after a
        full leader run + follower drain over a loopback channel."""
        from flax import linen as nn

        from kubeflow_tpu.serving.gang import (
            GangChannel,
            GangEngine,
            follow,
        )
        from kubeflow_tpu.utils.net import allocate_port

        cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
        params = nn.meta.unbox(llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        port = allocate_port()
        follower_engine = ContinuousEngine(cfg, params, **kw)
        ops: list[str] = []
        chan_kw = chan_kw or {}

        def run_follower():
            ch = GangChannel.connect(
                "127.0.0.1", port, rank=1, token="t",
                sock_wrap=sock_wrap, **chan_kw)
            orig_next = ch.next

            def tap():
                m = orig_next()
                ops.append(m[0])
                return m

            ch.next = tap
            try:
                follow(follower_engine, ch)
            finally:
                ch.close()

        t = threading.Thread(target=run_follower, daemon=True)
        t.start()
        chan = GangChannel.listen(port, 1, token="t", **chan_kw)
        leader = GangEngine(cfg, params, channel=chan, **kw)
        try:
            got = drive(leader)
        finally:
            leader.stop()
            t.join(timeout=300)
        assert not t.is_alive(), "follower did not drain the stream"
        return got, ops, leader, follower_engine, cfg, params

    @staticmethod
    def _assert_pools_equal(leader, follower):
        ll = np.asarray(jax.device_get(leader._pool_logits))
        fl = np.asarray(jax.device_get(follower._pool_logits))
        assert np.array_equal(ll, fl)
        for a, b in zip(
                jax.tree.leaves(jax.device_get(leader._pool_cache)),
                jax.tree.leaves(jax.device_get(follower._pool_cache))):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_follower_replays_paged_stream_bit_identically(self):
        kw = dict(num_slots=3, decode_chunk=2, temperature=0.0,
                  eos_id=None, seq_buckets=[32], prefix_cache=True,
                  min_prefix=8, prefill_budget=8, block_size=8,
                  mesh_axes={"model": 8})
        prompt = list(range(1, 25))

        def drive(leader):
            v = leader.submit([7, 8, 9], max_new_tokens=12)
            time.sleep(0.2)
            late = leader.submit(prompt, max_new_tokens=5)
            rep = leader.submit(prompt, max_new_tokens=5)  # prefix hit
            return [v.wait(300), late.wait(300), rep.wait(300)]

        got, ops, leader, follower, cfg, params = self._run_pair(kw, drive)
        ref = ContinuousEngine(cfg, params, **kw)
        try:
            r1 = ref.submit([7, 8, 9], max_new_tokens=12)
            time.sleep(0.2)
            r2 = ref.submit(prompt, max_new_tokens=5)
            r3 = ref.submit(prompt, max_new_tokens=5)
            want = [r1.wait(300), r2.wait(300), r3.wait(300)]
        finally:
            ref.stop()
        assert got == want
        assert "paged_fused" in ops or "paged_chunk" in ops
        assert "paged_decode" in ops
        self._assert_pools_equal(leader, follower)

    @pytest.mark.slow
    def test_chaos_follower_socket_drop_mid_paged_decode_converges(self):
        """Seeded chaos compose: the follower's socket dies mid-paged-
        decode; the channel reconnects, rank 0 replays the missed
        block-table ops, and the pools converge bit-identically."""
        from kubeflow_tpu.chaos import FaultPlan

        plan = FaultPlan(seed=0).socket_drop(role="follower",
                                             after_calls=25)
        kw = dict(num_slots=2, decode_chunk=1, temperature=0.0,
                  eos_id=None, seq_buckets=[32], prefix_cache=False,
                  prefill_budget=8, block_size=8,
                  mesh_axes={"model": 8})
        chan = dict(hb_interval=0.05, dead_peer_timeout=0.5,
                    reattach_timeout=10.0, reconnect_timeout=10.0)

        def drive(leader):
            r = leader.submit(list(range(1, 20)), max_new_tokens=24)
            return r.wait(300)

        got, ops, leader, follower, cfg, params = self._run_pair(
            kw, drive, sock_wrap=plan.socket_wrapper("follower"),
            chan_kw=chan)
        assert len(got) == 24
        assert "paged_decode" in ops
        self._assert_pools_equal(leader, follower)
