"""Grouped-GEMM Pallas kernel (ops/grouped_matmul.py) — the dropless-MoE
expert compute.  Interpret mode on CPU exercises the identical kernel
the TPU runs (flash-attention convention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.ops.grouped_matmul import grouped_matmul


def _ref(x, w, sizes):
    out = np.zeros((x.shape[0], w.shape[-1]), np.float32)
    s = 0
    for e, n in enumerate(sizes):
        out[s:s + n] = np.asarray(x[s:s + n] @ w[e])
        s += n
    return out


@pytest.mark.parametrize("sizes", [
    [10, 0, 15],          # empty group + trailing no-group rows
    [32, 32, 32, 32],     # exact tile alignment (B=128, bm=128)
    [1, 127],             # boundary mid-tile
    [0, 0, 64],           # leading empty groups
])
def test_forward_matches_reference(sizes):
    rng = np.random.default_rng(0)
    b = 128
    e, h, m = len(sizes), 64, 96
    x = jnp.asarray(rng.normal(size=(b, h)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, h, m)), jnp.float32)
    offs = jnp.asarray(np.concatenate([[0], np.cumsum(sizes)]), jnp.int32)
    out = grouped_matmul(x, w, offs)
    np.testing.assert_allclose(np.asarray(out), _ref(x, w, sizes), atol=2e-5)


def test_grads_match_dense_construction():
    rng = np.random.default_rng(1)
    b, e, h, m = 64, 3, 32, 48
    sizes = [20, 0, 30]
    x = jnp.asarray(rng.normal(size=(b, h)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, h, m)), jnp.float32)
    offs = jnp.asarray(np.concatenate([[0], np.cumsum(sizes)]), jnp.int32)

    def loss(x, w):
        return (grouped_matmul(x, w, offs) ** 2).sum()

    def loss_ref(x, w):
        parts, s = [], 0
        for ee, n in enumerate(sizes):
            parts.append(x[s:s + n] @ w[ee])
            s += n
        o = jnp.concatenate(parts + [jnp.zeros((b - s, m))], axis=0)
        return (o ** 2).sum()

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r), atol=1e-4)


def test_jit_and_dynamic_offsets():
    """Offsets are runtime data (routing-dependent): one compiled program
    serves every load distribution."""
    rng = np.random.default_rng(2)
    b, e, h, m = 64, 2, 32, 32
    x = jnp.asarray(rng.normal(size=(b, h)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(e, h, m)), jnp.float32)
    f = jax.jit(grouped_matmul)
    for sizes in ([40, 24], [0, 64], [64, 0], [10, 10]):
        offs = jnp.asarray(np.concatenate([[0], np.cumsum(sizes)]), jnp.int32)
        np.testing.assert_allclose(
            np.asarray(f(x, w, offs)), _ref(x, w, sizes), atol=2e-5)
