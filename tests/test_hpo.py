"""HPO plane: algorithms, the gRPC service boundary, and the controller loop.

Mirrors the reference test pyramid (SURVEY.md §4): pure unit tests for the
suggestion algorithms, a real-socket service test, an envtest-style
controller run on the fake kubelet, and a full e2e with real trial
processes in test_e2e_local-style fashion.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.api.experiment import (
    AlgorithmSpec,
    Experiment,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    TrialTemplate,
)
from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.hpo import algorithms as alg
from kubeflow_tpu.hpo.service import SuggestionClient, SuggestionServer

DOUBLE_LR = ParameterSpec(
    name="lr",
    parameter_type=ParameterType.DOUBLE,
    feasible_space=FeasibleSpace(min=0.001, max=0.1, log_scale=True),
)
INT_LAYERS = ParameterSpec(
    name="layers",
    parameter_type=ParameterType.INT,
    feasible_space=FeasibleSpace(min=1, max=4),
)
CAT_OPT = ParameterSpec(
    name="opt",
    parameter_type=ParameterType.CATEGORICAL,
    feasible_space=FeasibleSpace(**{"list": ["sgd", "adam"]}),
)


def _req(history=None, count=1, obj=ObjectiveType.MINIMIZE, seed=0):
    return alg.SuggestRequest(
        parameters=[DOUBLE_LR, INT_LAYERS, CAT_OPT],
        objective_type=obj,
        history=history or [],
        count=count,
        seed=seed,
    )


def _quadratic(assignments):
    # minimized at lr=0.03
    return (assignments["lr"] - 0.03) ** 2


class TestAlgorithms:
    def test_random_respects_space(self):
        out = alg.RandomSearch().suggest(_req(count=20))
        assert len(out) == 20
        for a in out:
            assert 0.001 <= a["lr"] <= 0.1
            assert 1 <= a["layers"] <= 4 and isinstance(a["layers"], int)
            assert a["opt"] in ("sgd", "adam")

    def test_grid_enumerates_exactly_once(self):
        p = [INT_LAYERS, CAT_OPT]
        req = alg.SuggestRequest(
            parameters=p, objective_type=ObjectiveType.MINIMIZE, count=100)
        out = alg.GridSearch().suggest(req)
        assert len(out) == 8  # 4 ints x 2 cats
        assert len({tuple(sorted(a.items())) for a in out}) == 8
        # a second call with full history walks off the end -> empty
        req.history = [alg.Observation(assignments=a, value=0.0) for a in out]
        assert alg.GridSearch().suggest(req) == []

    @pytest.mark.parametrize("name", ["tpe", "bayesianoptimization", "cmaes"])
    def test_model_based_beats_random_closed_loop(self, name):
        """Sequential optimize-observe loop at equal budget: the model-based
        suggester's best observed value should beat random search's."""

        def run(suggester_name: str, budget: int = 24) -> float:
            history = []
            s = alg.get_suggester(suggester_name)
            for i in range(budget):
                req = _req(history, count=1, seed=i)
                a = s.suggest(req)[0]
                history.append(
                    alg.Observation(assignments=a, value=_quadratic(a)))
            return min(ob.value for ob in history)

        assert run(name) < run("random")

    def test_cmaes_stateless_replay(self):
        """Service-restart property: identical (history, seed, issued) must
        reconstruct the identical evolution state and suggestions."""
        history = []
        s = alg.get_suggester("cmaes")
        for i in range(16):
            a = s.suggest(_req(history, count=1, seed=7))[0]
            history.append(alg.Observation(assignments=a, value=_quadratic(a)))
        again = alg.get_suggester("cmaes").suggest(_req(history, count=3, seed=7))
        first = s.suggest(_req(history, count=3, seed=7))
        assert again == first

    def test_cmaes_parallel_suggestions_distinct(self):
        req = _req([], count=4, seed=1)
        out = alg.get_suggester("cmaes").suggest(req)
        assert len({tuple(sorted(a.items())) for a in out}) == 4

    def test_unknown_algorithm_raises(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            alg.get_suggester("nope")

    def test_grid_parallel_trials_get_distinct_cells(self):
        """Caught regression: the grid cursor must follow issued assignments
        (running trials included), not completed history."""
        p = [INT_LAYERS]
        req = alg.SuggestRequest(
            parameters=p, objective_type=ObjectiveType.MINIMIZE,
            count=2, issued=2,
            history=[alg.Observation(assignments={"layers": 1}, value=0.0)],
        )
        out = alg.GridSearch().suggest(req)
        assert [a["layers"] for a in out] == [3, 4]

    def test_random_does_not_replay_after_failure(self):
        """Caught regression: with no explicit seed, two calls at the same
        history length must not return identical points."""
        req1 = alg.SuggestRequest(
            parameters=[DOUBLE_LR], objective_type=ObjectiveType.MINIMIZE, count=1)
        req2 = alg.SuggestRequest(
            parameters=[DOUBLE_LR], objective_type=ObjectiveType.MINIMIZE, count=1)
        a = alg.RandomSearch().suggest(req1)[0]["lr"]
        b = alg.RandomSearch().suggest(req2)[0]["lr"]
        assert a != b


class TestService:
    def test_round_trip_over_real_socket(self):
        server = SuggestionServer().start()
        try:
            client = SuggestionClient(server.address)
            out = client.get_suggestions(
                algorithm="random",
                parameters=[DOUBLE_LR],
                objective_type=ObjectiveType.MINIMIZE,
                history=[alg.Observation(assignments={"lr": 0.01}, value=1.0)],
                count=3,
            )
            assert len(out) == 3 and all(0.001 <= a["lr"] <= 0.1 for a in out)
            client.close()
        finally:
            server.stop()

    def test_bad_algorithm_is_rpc_error(self):
        import grpc

        server = SuggestionServer().start()
        try:
            client = SuggestionClient(server.address)
            with pytest.raises(grpc.RpcError):
                client.get_suggestions(
                    algorithm="nope",
                    parameters=[],
                    objective_type=ObjectiveType.MINIMIZE,
                    history=[],
                    count=1,
                )
            client.close()
        finally:
            server.stop()


def _experiment(name, max_trials=6, parallel=2, algorithm="random", goal=None):
    return Experiment(
        metadata=ObjectMeta(name=name),
        spec=ExperimentSpec(
            objective=ObjectiveSpec(
                type=ObjectiveType.MAXIMIZE,
                objective_metric_name="score",
                goal=goal,
            ),
            algorithm=AlgorithmSpec(algorithm_name=algorithm),
            parameters=[DOUBLE_LR],
            parallel_trial_count=parallel,
            max_trial_count=max_trials,
            trial_template=TrialTemplate(
                job_manifest={
                    "kind": "JaxJob",
                    "metadata": {"name": "placeholder"},
                    "spec": {
                        "replica_specs": {
                            "worker": {
                                "replicas": 1,
                                "template": {
                                    "entrypoint": "tests.hpo_objective:objective_main",
                                    "env": {"KFT_LR": "${trialParameters.lr}"},
                                },
                            }
                        }
                    },
                }
            ),
        ),
    )


class TestControllersEnvtestStyle:
    """Cluster + FakeKubelet: no real processes; metrics written by a stub
    collector thread, the envtest analog (SURVEY.md §4)."""

    def test_experiment_completes_and_finds_optimum(self, tmp_path):
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))
        kubelet = FakeKubelet(cluster.store)
        stop = threading.Event()

        def metric_writer():
            # stands in for the trial process: score from the pod's env
            while not stop.is_set():
                for pod in cluster.store.list(KIND_POD):
                    assert isinstance(pod, Pod)
                    lr = pod.spec.container.env.get("KFT_LR")
                    if lr is None:
                        continue
                    d = tmp_path / "status" / pod.metadata.namespace / pod.metadata.name
                    d.mkdir(parents=True, exist_ok=True)
                    score = 1.0 - (float(lr) - 0.03) ** 2 * 100.0
                    (d / "metrics.jsonl").write_text(
                        json.dumps({"name": "score", "value": score}) + "\n")
                stop.wait(0.01)

        writer = threading.Thread(target=metric_writer, daemon=True)
        with cluster:
            kubelet.start()
            writer.start()
            try:
                cluster.store.create(_experiment("sweep", max_trials=6))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "sweep")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed, (
                    exp.status if exp else None)
                assert exp.status.trials_succeeded == 6
                assert exp.status.current_optimal_value is not None
                assert exp.status.current_optimal_value <= 1.0
                assert exp.status.current_optimal_assignments[0].name == "lr"
            finally:
                stop.set()
                kubelet.stop()

    def test_metricless_trial_fails_not_succeeds(self, tmp_path):
        """Caught regression: a job that never emits the objective metric
        must produce a Failed trial (MetricsUnavailable), not a silent
        Succeeded-with-None."""
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))  # no metric writer
        kubelet = FakeKubelet(cluster.store)
        with cluster:
            kubelet.start()
            try:
                cluster.store.create(
                    _experiment("nometrics", max_trials=1, parallel=1))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "nometrics")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed
                assert exp.status.trials_failed == 1
                assert exp.status.trials_succeeded == 0
                trial = cluster.store.try_get("Trial", "nometrics-t0000")
                assert trial.status.phase == "Failed"
            finally:
                kubelet.stop()

    def test_goal_stops_early(self, tmp_path):
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))
        kubelet = FakeKubelet(cluster.store)
        stop = threading.Event()

        def metric_writer():
            while not stop.is_set():
                for pod in cluster.store.list(KIND_POD):
                    assert isinstance(pod, Pod)
                    if "KFT_LR" not in pod.spec.container.env:
                        continue
                    d = tmp_path / "status" / pod.metadata.namespace / pod.metadata.name
                    d.mkdir(parents=True, exist_ok=True)
                    (d / "metrics.jsonl").write_text(
                        json.dumps({"name": "score", "value": 0.99}) + "\n")
                stop.wait(0.01)

        writer = threading.Thread(target=metric_writer, daemon=True)
        with cluster:
            kubelet.start()
            writer.start()
            try:
                # any trial hits goal=0.5 -> completes well before 50 trials
                cluster.store.create(
                    _experiment("quick", max_trials=50, parallel=1, goal=0.5))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "quick")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed
                assert exp.status.trials_created < 50
            finally:
                stop.set()
                kubelet.stop()

    def test_observations_survive_control_plane_restart(self, tmp_path):
        """katib-db-manager capability (SURVEY §2.3): kill the control plane
        mid-experiment; a new control plane on the same observation db
        replays completed trials — the experiment finishes with full
        history and does not re-run finished work."""
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod
        from kubeflow_tpu.hpo.db import ObservationDb

        db_path = str(tmp_path / "observations.sqlite")

        def make(cluster):
            kubelet = FakeKubelet(cluster.store)
            stop = threading.Event()

            def metric_writer():
                while not stop.is_set():
                    for pod in cluster.store.list(KIND_POD):
                        assert isinstance(pod, Pod)
                        lr = pod.spec.container.env.get("KFT_LR")
                        if lr is None:
                            continue
                        d = tmp_path / "status" / pod.metadata.namespace / pod.metadata.name
                        d.mkdir(parents=True, exist_ok=True)
                        score = 1.0 - (float(lr) - 0.03) ** 2 * 100.0
                        (d / "metrics.jsonl").write_text(
                            json.dumps({"name": "score", "value": score}) + "\n")
                    stop.wait(0.01)

            writer = threading.Thread(target=metric_writer, daemon=True)
            return kubelet, stop, writer

        # -- incarnation 1: run partway, then kill the control plane ------
        c1 = Cluster()
        c1.add_tpu_slice("slice-0", 2, 4)
        c1.enable_hpo(metrics_root=str(tmp_path), db_path=db_path)
        kubelet1, stop1, writer1 = make(c1)
        c1.start()
        kubelet1.start()
        writer1.start()
        try:
            c1.store.create(_experiment("durable", max_trials=6, parallel=2))
            deadline = time.time() + 60
            done_before_kill = 0
            while time.time() < deadline:
                exp = c1.store.try_get("Experiment", "durable")
                if exp is not None and exp.status.trials_succeeded >= 2:
                    done_before_kill = exp.status.trials_succeeded
                    break
                time.sleep(0.02)
            assert done_before_kill >= 2
        finally:
            stop1.set()
            kubelet1.stop()
            c1.stop()

        recorded = len(ObservationDb(db_path).observations("durable"))
        assert recorded >= 2

        # -- incarnation 2: fresh store, same db -------------------------
        c2 = Cluster()
        c2.add_tpu_slice("slice-0", 2, 4)
        c2.enable_hpo(metrics_root=str(tmp_path), db_path=db_path)
        kubelet2, stop2, writer2 = make(c2)
        with c2:
            kubelet2.start()
            writer2.start()
            try:
                c2.store.create(_experiment("durable", max_trials=6, parallel=2))
                deadline = time.time() + 60
                exp = None
                while time.time() < deadline:
                    exp = c2.store.try_get("Experiment", "durable")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed, (
                    exp.status if exp else None)
                # full history: replayed + freshly-run == max_trial_count
                assert exp.status.trials_succeeded == 6
                assert exp.status.replayed
                from kubeflow_tpu.controlplane import events_for

                reasons = [e.reason for e in events_for(c2.store, "Experiment", "durable")]
                assert "ObservationsReplayed" in reasons
                # replayed trials were NOT re-run: fewer jobs than trials
                jobs = [
                    j for j in c2.store.list("JaxJob")
                    if j.metadata.name.startswith("durable-")
                ]
                assert len(jobs) <= 6 - recorded
                assert len(ObservationDb(db_path).observations("durable")) == 6
            finally:
                stop2.set()
                kubelet2.stop()


class TestAshaEarlyStopping:
    def test_unit_rungs_and_promotion(self):
        from kubeflow_tpu.hpo.early_stopping import Asha

        asha = Asha(min_resource=10, reduction_factor=3)
        assert asha.rung_for(9) is None
        assert asha.rung_for(10) == 0
        assert asha.rung_for(29) == 0
        assert asha.rung_for(30) == 1
        assert asha.milestone(1) == 30
        # maximize: bottom of 3 recorded values at a rung is cut
        assert asha.should_stop(ObjectiveType.MAXIMIZE, 0, 0.1, [0.9, 0.8])
        assert not asha.should_stop(ObjectiveType.MAXIMIZE, 0, 0.95, [0.9, 0.8])
        # fewer than reduction_factor records: always promote
        assert not asha.should_stop(ObjectiveType.MAXIMIZE, 0, 0.1, [0.9])

    def test_asha_saves_steps_at_equal_best_objective(self, tmp_path):
        """Closed loop vs no early stopping on the same grid: same optimum,
        strictly fewer total training steps spent."""
        from kubeflow_tpu.api.experiment import EarlyStoppingSpec
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet, PodScript
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod

        layers_param = ParameterSpec(
            name="layers",
            parameter_type=ParameterType.INT,
            feasible_space=FeasibleSpace(min=1, max=6),
        )

        def quality(layers: int) -> float:
            return 1.0 - abs(layers - 2) * 0.2

        def run(name: str, early_stopping) -> tuple[float, int, object]:
            cluster = Cluster()
            cluster.add_tpu_slice("slice-0", 2, 4)
            root = tmp_path / name
            cluster.enable_hpo(metrics_root=str(root))
            kubelet = FakeKubelet(
                cluster.store, script=lambda pod: PodScript(run_seconds=1.5))
            stop = threading.Event()
            steps_written: dict[str, int] = {}

            def metric_writer():
                while not stop.is_set():
                    for pod in cluster.store.list(KIND_POD):
                        assert isinstance(pod, Pod)
                        layers = pod.spec.container.env.get("KFT_LAYERS")
                        if layers is None:
                            continue
                        step = steps_written.get(pod.metadata.name, 0) + 1
                        steps_written[pod.metadata.name] = step
                        # value ramps to its asymptote by step 10, so rung
                        # observations at step>=10 equal the final quality
                        val = quality(int(layers)) * min(1.0, step / 10.0)
                        d = root / "status" / pod.metadata.namespace / pod.metadata.name
                        d.mkdir(parents=True, exist_ok=True)
                        with open(d / "metrics.jsonl", "a") as f:
                            f.write(json.dumps(
                                {"name": "score", "value": val, "step": step}) + "\n")
                    stop.wait(0.02)

            exp = _experiment(name, max_trials=6, parallel=3, algorithm="grid")
            exp.spec.parameters = [layers_param]
            exp.spec.trial_template.job_manifest["spec"]["replica_specs"]["worker"][
                "template"]["env"] = {"KFT_LAYERS": "${trialParameters.layers}"}
            exp.spec.early_stopping = early_stopping

            writer = threading.Thread(target=metric_writer, daemon=True)
            with cluster:
                kubelet.start()
                writer.start()
                try:
                    cluster.store.create(exp)
                    deadline = time.time() + 60
                    out = None
                    while time.time() < deadline:
                        out = cluster.store.try_get("Experiment", name)
                        if out is not None and out.status.completed:
                            break
                        time.sleep(0.05)
                    assert out is not None and out.status.completed, (
                        out.status if out else None)
                finally:
                    stop.set()
                    kubelet.stop()
            return out.status.current_optimal_value, sum(steps_written.values()), out

        es = EarlyStoppingSpec(
            algorithm_name="asha",
            settings={"min_resource": "10", "reduction_factor": "3"},
        )
        best_asha, steps_asha, exp_asha = run("asha", es)
        best_plain, steps_plain, _ = run("plain", None)

        assert exp_asha.status.trials_early_stopped >= 1
        # equal best objective: the grid's best cell (layers=2) completes
        assert best_asha == pytest.approx(best_plain, abs=1e-6) == pytest.approx(1.0)
        # and it cost strictly fewer total steps
        assert steps_asha < steps_plain


@pytest.mark.e2e
def test_hpo_e2e_real_processes():
    """Full composition with real trial processes (SURVEY.md §3.4): the
    sweep's outer loop drives JaxJobs whose pods actually run."""
    from kubeflow_tpu.runtime.platform import LocalPlatform

    with LocalPlatform() as p:
        p.store.create(_experiment("e2e-sweep", max_trials=4, parallel=2))
        deadline = time.time() + 120
        exp = None
        while time.time() < deadline:
            exp = p.store.try_get("Experiment", "e2e-sweep")
            if exp is not None and exp.status.completed:
                break
            time.sleep(0.2)
        assert exp is not None and exp.status.completed, exp.status if exp else None
        assert exp.status.trials_succeeded == 4
        assert exp.status.current_optimal_value is not None


class TestPbt:
    def _req(self, history, count=1, issued=None, pop=4):
        return alg.SuggestRequest(
            parameters=[DOUBLE_LR],
            objective_type=ObjectiveType.MAXIMIZE,
            history=history,
            count=count,
            settings={"population_size": str(pop), "truncation": "0.25"},
            seed=3,
            issued=len(history) if issued is None else issued,
        )

    def test_generation_zero_is_fresh(self):
        out = alg.get_suggester("pbt").suggest(self._req([], count=4))
        assert len(out) == 4
        for a in out:
            assert a[alg.PBT_PARENT_KEY] == ""
            assert 0.001 <= a["lr"] <= 0.1

    def test_survivors_continue_losers_fork_top(self):
        gen0 = [
            alg.Observation({"lr": 0.03}, value=1.0, trial="e-t0000"),
            alg.Observation({"lr": 0.05}, value=0.9, trial="e-t0001"),
            alg.Observation({"lr": 0.08}, value=0.5, trial="e-t0002"),
            alg.Observation({"lr": 0.10}, value=0.1, trial="e-t0003"),
        ]
        out = alg.get_suggester("pbt").suggest(self._req(gen0, count=4))
        # truncation 0.25 of pop 4 -> exactly the worst member is replaced
        for slot in (0, 1, 2):
            assert out[slot][alg.PBT_PARENT_KEY] == gen0[slot].trial
            assert out[slot]["lr"] == gen0[slot].assignments["lr"]
        loser = out[3]
        assert loser[alg.PBT_PARENT_KEY] == "e-t0000"  # forked the best
        # explored: perturbed off the donor's value, clamped to the space
        assert loser["lr"] != 0.03
        assert 0.001 <= loser["lr"] <= 0.1


    def test_failed_trial_leaves_a_hole_not_misalignment(self):
        """A Failed trial (absent from history) must not degrade PBT to
        random sampling — remaining members still rank and fork."""
        gen0 = [
            alg.Observation({"lr": 0.03}, value=1.0, trial="e-t0000"),
            # e-t0001 failed: no observation
            alg.Observation({"lr": 0.08}, value=0.5, trial="e-t0002"),
            alg.Observation({"lr": 0.10}, value=0.1, trial="e-t0003"),
        ]
        out = alg.get_suggester("pbt").suggest(
            self._req(gen0, count=4, issued=4))
        # survivors continue; the failed slot and the worst slot fork a top
        assert out[0][alg.PBT_PARENT_KEY] == "e-t0000"
        assert out[1][alg.PBT_PARENT_KEY] == "e-t0000"  # hole -> exploit
        assert out[2][alg.PBT_PARENT_KEY] == "e-t0002"
        assert out[3][alg.PBT_PARENT_KEY] == "e-t0000"  # worst -> exploit

    def test_stateless_replay(self):
        gen0 = [
            alg.Observation({"lr": v}, value=s, trial=f"e-t{i:04d}")
            for i, (v, s) in enumerate(
                [(0.03, 1.0), (0.05, 0.9), (0.08, 0.5), (0.1, 0.1)])
        ]
        a = alg.get_suggester("pbt").suggest(self._req(gen0, count=4))
        b = alg.get_suggester("pbt").suggest(self._req(gen0, count=4))
        assert a == b


@pytest.mark.e2e
class TestPbtE2E:
    def test_forked_lineage_beats_single_generation(self, tmp_path):
        """Closed loop over real trial processes: scores > 1.0 are only
        reachable by continuing a parent's state, so the optimum proves the
        checkpoint-fork contract end to end."""
        from kubeflow_tpu.runtime.platform import LocalPlatform
        from kubeflow_tpu.sdk import KatibClient, search_double

        pbt_root = str(tmp_path / "pbt")
        with LocalPlatform(num_hosts=2, chips_per_host=4,
                           root_dir=str(tmp_path / "plat")) as p:
            client = KatibClient(p)
            exp = client.tune(
                name="pbt-loop",
                entrypoint="tests.pbt_objective:objective_main",
                parameters={"lr": search_double(0.001, 0.1)},
                objective_metric="score",
                algorithm="pbt",
                algorithm_settings={
                    "population_size": "3", "truncation": "0.34"},
                max_trials=9,
                parallel_trials=3,
                base_env={
                    "KFT_PBT_ROOT": pbt_root,
                    "KFT_RESUME_FROM": "${trialParameters.__parent}",
                },
                timeout=400,
            )
            assert exp.status.completed
            assert exp.status.trials_succeeded == 9
            best = client.get_optimal_hyperparameters("pbt-loop")
            assert best["value"] > 1.0, best  # impossible without forking
            # at least one later-generation trial carries a fork edge
            parents = [
                a.value
                for t in client.list_trials("pbt-loop")
                for a in t.spec.assignments
                if a.name == alg.PBT_PARENT_KEY
            ]
            assert any(parents[3:]), parents


class TestArchitectureSearch:
    """NAS capability (SURVEY §2.3 suggestion zoo): architecture search is
    HPO over model-shape parameters — the search space is layers/heads/
    width ints and categoricals, driven by the same suggesters."""

    ARCH_SPACE = [
        ParameterSpec(name="layers", parameter_type=ParameterType.INT,
                      feasible_space=FeasibleSpace(min=2, max=12)),
        ParameterSpec(name="heads", parameter_type=ParameterType.INT,
                      feasible_space=FeasibleSpace(min=2, max=16)),
        ParameterSpec(name="ffn_mult", parameter_type=ParameterType.CATEGORICAL,
                      feasible_space=FeasibleSpace(**{"list": [2.0, 2.667, 4.0]})),
    ]

    @staticmethod
    def _quality(a) -> float:
        # analytic proxy: quality peaks at layers=8, heads=8, ffn_mult=2.667
        return -(
            (a["layers"] - 8) ** 2 / 36
            + (a["heads"] - 8) ** 2 / 49
            + (0.0 if a["ffn_mult"] == 2.667 else 0.3)
        )

    @pytest.mark.parametrize("algorithm", ["tpe", "cmaes"])
    def test_search_finds_good_architectures(self, algorithm):
        history = []
        s = alg.get_suggester(algorithm)
        for i in range(24):
            req = alg.SuggestRequest(
                parameters=self.ARCH_SPACE,
                objective_type=ObjectiveType.MAXIMIZE,
                history=history, count=1, seed=i,
                issued=len(history))
            a = s.suggest(req)[0]
            history.append(alg.Observation(
                assignments=a, value=self._quality(a), trial=f"n-t{i:04d}"))
        best = max(history, key=lambda ob: ob.value)
        assert best.value > -0.35, best  # near the optimum shape
        assert 5 <= best.assignments["layers"] <= 11
