"""HPO plane: algorithms, the gRPC service boundary, and the controller loop.

Mirrors the reference test pyramid (SURVEY.md §4): pure unit tests for the
suggestion algorithms, a real-socket service test, an envtest-style
controller run on the fake kubelet, and a full e2e with real trial
processes in test_e2e_local-style fashion.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from kubeflow_tpu.api.experiment import (
    AlgorithmSpec,
    Experiment,
    ExperimentSpec,
    FeasibleSpace,
    ObjectiveSpec,
    ObjectiveType,
    ParameterSpec,
    ParameterType,
    TrialTemplate,
)
from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.hpo import algorithms as alg
from kubeflow_tpu.hpo.service import SuggestionClient, SuggestionServer

DOUBLE_LR = ParameterSpec(
    name="lr",
    parameter_type=ParameterType.DOUBLE,
    feasible_space=FeasibleSpace(min=0.001, max=0.1, log_scale=True),
)
INT_LAYERS = ParameterSpec(
    name="layers",
    parameter_type=ParameterType.INT,
    feasible_space=FeasibleSpace(min=1, max=4),
)
CAT_OPT = ParameterSpec(
    name="opt",
    parameter_type=ParameterType.CATEGORICAL,
    feasible_space=FeasibleSpace(**{"list": ["sgd", "adam"]}),
)


def _req(history=None, count=1, obj=ObjectiveType.MINIMIZE, seed=0):
    return alg.SuggestRequest(
        parameters=[DOUBLE_LR, INT_LAYERS, CAT_OPT],
        objective_type=obj,
        history=history or [],
        count=count,
        seed=seed,
    )


def _quadratic(assignments):
    # minimized at lr=0.03
    return (assignments["lr"] - 0.03) ** 2


class TestAlgorithms:
    def test_random_respects_space(self):
        out = alg.RandomSearch().suggest(_req(count=20))
        assert len(out) == 20
        for a in out:
            assert 0.001 <= a["lr"] <= 0.1
            assert 1 <= a["layers"] <= 4 and isinstance(a["layers"], int)
            assert a["opt"] in ("sgd", "adam")

    def test_grid_enumerates_exactly_once(self):
        p = [INT_LAYERS, CAT_OPT]
        req = alg.SuggestRequest(
            parameters=p, objective_type=ObjectiveType.MINIMIZE, count=100)
        out = alg.GridSearch().suggest(req)
        assert len(out) == 8  # 4 ints x 2 cats
        assert len({tuple(sorted(a.items())) for a in out}) == 8
        # a second call with full history walks off the end -> empty
        req.history = [alg.Observation(assignments=a, value=0.0) for a in out]
        assert alg.GridSearch().suggest(req) == []

    @pytest.mark.parametrize("name", ["tpe", "bayesianoptimization"])
    def test_model_based_beats_random_closed_loop(self, name):
        """Sequential optimize-observe loop at equal budget: the model-based
        suggester's best observed value should beat random search's."""

        def run(suggester_name: str, budget: int = 24) -> float:
            history = []
            s = alg.get_suggester(suggester_name)
            for i in range(budget):
                req = _req(history, count=1, seed=i)
                a = s.suggest(req)[0]
                history.append(
                    alg.Observation(assignments=a, value=_quadratic(a)))
            return min(ob.value for ob in history)

        assert run(name) < run("random")

    def test_unknown_algorithm_raises(self):
        with pytest.raises(ValueError, match="unknown algorithm"):
            alg.get_suggester("nope")

    def test_grid_parallel_trials_get_distinct_cells(self):
        """Caught regression: the grid cursor must follow issued assignments
        (running trials included), not completed history."""
        p = [INT_LAYERS]
        req = alg.SuggestRequest(
            parameters=p, objective_type=ObjectiveType.MINIMIZE,
            count=2, issued=2,
            history=[alg.Observation(assignments={"layers": 1}, value=0.0)],
        )
        out = alg.GridSearch().suggest(req)
        assert [a["layers"] for a in out] == [3, 4]

    def test_random_does_not_replay_after_failure(self):
        """Caught regression: with no explicit seed, two calls at the same
        history length must not return identical points."""
        req1 = alg.SuggestRequest(
            parameters=[DOUBLE_LR], objective_type=ObjectiveType.MINIMIZE, count=1)
        req2 = alg.SuggestRequest(
            parameters=[DOUBLE_LR], objective_type=ObjectiveType.MINIMIZE, count=1)
        a = alg.RandomSearch().suggest(req1)[0]["lr"]
        b = alg.RandomSearch().suggest(req2)[0]["lr"]
        assert a != b


class TestService:
    def test_round_trip_over_real_socket(self):
        server = SuggestionServer().start()
        try:
            client = SuggestionClient(server.address)
            out = client.get_suggestions(
                algorithm="random",
                parameters=[DOUBLE_LR],
                objective_type=ObjectiveType.MINIMIZE,
                history=[alg.Observation(assignments={"lr": 0.01}, value=1.0)],
                count=3,
            )
            assert len(out) == 3 and all(0.001 <= a["lr"] <= 0.1 for a in out)
            client.close()
        finally:
            server.stop()

    def test_bad_algorithm_is_rpc_error(self):
        import grpc

        server = SuggestionServer().start()
        try:
            client = SuggestionClient(server.address)
            with pytest.raises(grpc.RpcError):
                client.get_suggestions(
                    algorithm="nope",
                    parameters=[],
                    objective_type=ObjectiveType.MINIMIZE,
                    history=[],
                    count=1,
                )
            client.close()
        finally:
            server.stop()


def _experiment(name, max_trials=6, parallel=2, algorithm="random", goal=None):
    return Experiment(
        metadata=ObjectMeta(name=name),
        spec=ExperimentSpec(
            objective=ObjectiveSpec(
                type=ObjectiveType.MAXIMIZE,
                objective_metric_name="score",
                goal=goal,
            ),
            algorithm=AlgorithmSpec(algorithm_name=algorithm),
            parameters=[DOUBLE_LR],
            parallel_trial_count=parallel,
            max_trial_count=max_trials,
            trial_template=TrialTemplate(
                job_manifest={
                    "kind": "JaxJob",
                    "metadata": {"name": "placeholder"},
                    "spec": {
                        "replica_specs": {
                            "worker": {
                                "replicas": 1,
                                "template": {
                                    "entrypoint": "tests.hpo_objective:objective_main",
                                    "env": {"KFT_LR": "${trialParameters.lr}"},
                                },
                            }
                        }
                    },
                }
            ),
        ),
    )


class TestControllersEnvtestStyle:
    """Cluster + FakeKubelet: no real processes; metrics written by a stub
    collector thread, the envtest analog (SURVEY.md §4)."""

    def test_experiment_completes_and_finds_optimum(self, tmp_path):
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))
        kubelet = FakeKubelet(cluster.store)
        stop = threading.Event()

        def metric_writer():
            # stands in for the trial process: score from the pod's env
            while not stop.is_set():
                for pod in cluster.store.list(KIND_POD):
                    assert isinstance(pod, Pod)
                    lr = pod.spec.container.env.get("KFT_LR")
                    if lr is None:
                        continue
                    d = tmp_path / "status" / pod.metadata.namespace / pod.metadata.name
                    d.mkdir(parents=True, exist_ok=True)
                    score = 1.0 - (float(lr) - 0.03) ** 2 * 100.0
                    (d / "metrics.jsonl").write_text(
                        json.dumps({"name": "score", "value": score}) + "\n")
                stop.wait(0.01)

        writer = threading.Thread(target=metric_writer, daemon=True)
        with cluster:
            kubelet.start()
            writer.start()
            try:
                cluster.store.create(_experiment("sweep", max_trials=6))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "sweep")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed, (
                    exp.status if exp else None)
                assert exp.status.trials_succeeded == 6
                assert exp.status.current_optimal_value is not None
                assert exp.status.current_optimal_value <= 1.0
                assert exp.status.current_optimal_assignments[0].name == "lr"
            finally:
                stop.set()
                kubelet.stop()

    def test_metricless_trial_fails_not_succeeds(self, tmp_path):
        """Caught regression: a job that never emits the objective metric
        must produce a Failed trial (MetricsUnavailable), not a silent
        Succeeded-with-None."""
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))  # no metric writer
        kubelet = FakeKubelet(cluster.store)
        with cluster:
            kubelet.start()
            try:
                cluster.store.create(
                    _experiment("nometrics", max_trials=1, parallel=1))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "nometrics")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed
                assert exp.status.trials_failed == 1
                assert exp.status.trials_succeeded == 0
                trial = cluster.store.try_get("Trial", "nometrics-t0000")
                assert trial.status.phase == "Failed"
            finally:
                kubelet.stop()

    def test_goal_stops_early(self, tmp_path):
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.controlplane.fake_kubelet import FakeKubelet
        from kubeflow_tpu.controlplane.objects import KIND_POD, Pod

        cluster = Cluster()
        cluster.add_tpu_slice("slice-0", 2, 4)
        cluster.enable_hpo(metrics_root=str(tmp_path))
        kubelet = FakeKubelet(cluster.store)
        stop = threading.Event()

        def metric_writer():
            while not stop.is_set():
                for pod in cluster.store.list(KIND_POD):
                    assert isinstance(pod, Pod)
                    if "KFT_LR" not in pod.spec.container.env:
                        continue
                    d = tmp_path / "status" / pod.metadata.namespace / pod.metadata.name
                    d.mkdir(parents=True, exist_ok=True)
                    (d / "metrics.jsonl").write_text(
                        json.dumps({"name": "score", "value": 0.99}) + "\n")
                stop.wait(0.01)

        writer = threading.Thread(target=metric_writer, daemon=True)
        with cluster:
            kubelet.start()
            writer.start()
            try:
                # any trial hits goal=0.5 -> completes well before 50 trials
                cluster.store.create(
                    _experiment("quick", max_trials=50, parallel=1, goal=0.5))
                deadline = time.time() + 30
                exp = None
                while time.time() < deadline:
                    exp = cluster.store.try_get("Experiment", "quick")
                    if exp is not None and exp.status.completed:
                        break
                    time.sleep(0.05)
                assert exp is not None and exp.status.completed
                assert exp.status.trials_created < 50
            finally:
                stop.set()
                kubelet.stop()


@pytest.mark.e2e
def test_hpo_e2e_real_processes():
    """Full composition with real trial processes (SURVEY.md §3.4): the
    sweep's outer loop drives JaxJobs whose pods actually run."""
    from kubeflow_tpu.runtime.platform import LocalPlatform

    with LocalPlatform() as p:
        p.store.create(_experiment("e2e-sweep", max_trials=4, parallel=2))
        deadline = time.time() + 120
        exp = None
        while time.time() < deadline:
            exp = p.store.try_get("Experiment", "e2e-sweep")
            if exp is not None and exp.status.completed:
                break
            time.sleep(0.2)
        assert exp is not None and exp.status.completed, exp.status if exp else None
        assert exp.status.trials_succeeded == 4
        assert exp.status.current_optimal_value is not None
