"""Live paged-KV migration + prefill/decode disaggregation (ISSUE 8).

Four layers, matching the tentpole:

- the PRIMITIVE: a conversation exported mid-decode and resumed on a
  second engine produces BIT-IDENTICAL greedy tokens to the unmigrated
  run (plain/chunked paged variants here; spec + int8-KV in the slow
  set), with ``jit_recompiles_total == 0`` on both ends and every block
  returned to both free lists;
- SAFETY: copy-then-cutover — a rejected transfer (destination pool
  exhausted) resumes the source in place, and a released sequence stays
  prefix-matchable on the source until its blocks are reused;
- DISAGGREGATION: the pool routes admissions to prefill-role engines,
  hands finished sequences to the decode engine with the most free
  blocks (in-process and over the wire kv_migrate framing), and SSE
  streams survive the hop on the same request handle;
- DRAIN + controller: ``migrate_live_sequences`` empties a replica
  losslessly, the ISvc scale-down path invokes it, and bad ``role`` /
  ``disaggregation`` knobs are ONE Failed status at conf-freeze.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.analysis.runtime import BlockLedger
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import (
    ContinuousEngine,
    DisaggregatedPool,
    migrate_live_sequences,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64 tokens = 4 blocks at block_size 16


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("block_size", 16)
    eng = ContinuousEngine(cfg, params, **kw)
    # every engine in this suite runs under the analyzer's block-economy
    # audit (ISSUE 11): conservation checked per op, leaks counted into
    # the kv_blocks_leaked_total gauge the tests assert
    eng.attach_block_ledger(BlockLedger())
    return eng


def assert_no_leaks(*engines):
    """The ONE zero-leak assert (replaces the suite's ad-hoc free-count
    bookkeeping): a consistent-boundary audit on each engine, plus the
    gauge and the per-op conservation record."""
    for eng in engines:
        assert eng.audit_blocks() == []
        assert eng.stats()["kv_blocks_leaked_total"] == 0
        assert eng.block_ledger.conservation_errors == []


@pytest.fixture(scope="module")
def oracle(tiny_llama):
    """Unmigrated greedy truth on a single paged engine."""
    eng = make_engine(tiny_llama)
    try:
        return {
            "long40": eng.generate(LONG, max_new_tokens=40),
            "long24": eng.generate(LONG, max_new_tokens=24),
            "short12": eng.generate([7, 8, 9], max_new_tokens=12),
        }
    finally:
        eng.stop()


def _export_after(src, req, n_tokens: int):
    """Export once ``req`` has emitted >= n_tokens (mid-decode)."""
    deadline = time.time() + 120
    while len(req.tokens) < n_tokens:
        assert time.time() < deadline, "no tokens emitted"
        time.sleep(0.002)
    return src.export_sequence(req)


class TestMigrationParity:
    """Acceptance: migration is invisible to greedy correctness."""

    def test_mid_decode_migration_bit_identical(self, tiny_llama, oracle):
        src = make_engine(tiny_llama)
        dst = make_engine(tiny_llama)
        src.warmup()
        dst.warmup()
        try:
            req = src.submit(LONG, max_new_tokens=40)
            snap = _export_after(src, req, 3)
            assert snap is not None and snap["phase"] == "decode"
            assert dst.import_sequence(snap, req=req) is req
            src.release_sequence(req)
            assert req.wait(120) == oracle["long40"]
            # zero recompiles on BOTH ends (warmed kv programs)
            assert src.stats()["jit_recompiles_total"] == 0
            assert dst.stats()["jit_recompiles_total"] == 0
            # zero leaked blocks on BOTH ends: the ledger audit runs on
            # each scheduler thread at a consistent boundary (replaces
            # the old free-count bookkeeping + poll)
            assert_no_leaks(src, dst)
            # one migration counts ONCE, on the importing side; the
            # source's outbound view is bytes + the latency histogram
            assert src.kv_migrations_total == 0
            assert dst.kv_migrations_total == 1
            assert src.kv_migrate_bytes_total > 0
            assert dst.kv_migrate_bytes_total > 0
        finally:
            src.stop()
            dst.stop()

    def test_mid_prefill_migration_chunk_boundary(self, tiny_llama,
                                                  oracle):
        """A partially-prefilled sequence hands off at its chunk
        boundary: the destination runs the REMAINING chunks and the
        tokens still match the unmigrated run."""
        src = make_engine(tiny_llama, decode_chunk=1, prefill_budget=4)
        dst = make_engine(tiny_llama, decode_chunk=1, prefill_budget=4)
        src.warmup()
        dst.warmup()
        try:
            # keep the scheduler busy so the 16-chunk admission of LONG
            # is observably in flight when we export
            victim = src.submit([7, 8, 9], max_new_tokens=12)
            req = src.submit(LONG, max_new_tokens=24)
            deadline = time.time() + 60
            while src.prefill_chunks_dispatched < 3:
                assert time.time() < deadline, "prefill never started"
                time.sleep(0.002)
            snap = src.export_sequence(req)
            assert snap is not None
            assert dst.import_sequence(snap, req=req) is req
            src.release_sequence(req)
            assert req.wait(120) == oracle["long24"]
            assert victim.wait(120) == oracle["short12"]
            assert src.stats()["jit_recompiles_total"] == 0
            assert dst.stats()["jit_recompiles_total"] == 0
        finally:
            src.stop()
            dst.stop()

    @pytest.mark.slow
    def test_speculative_variant_parity(self, tiny_llama):
        """Spec-on paged engine: the residual ban and position front
        migrate with the sequence; greedy tokens stay the spec-off
        oracle's."""
        loopy = [5, 6, 5, 6, 5, 6, 5]
        off = make_engine(tiny_llama, decode_chunk=1)
        try:
            want = off.generate(loopy, max_new_tokens=24)
        finally:
            off.stop()
        src = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        dst = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        src.warmup()
        dst.warmup()
        try:
            req = src.submit(loopy, max_new_tokens=24)
            snap = _export_after(src, req, 2)
            if snap is not None:  # may already have finished dispatching
                dst.import_sequence(snap, req=req)
                src.release_sequence(req)
            assert req.wait(300) == want
            assert src.stats()["jit_recompiles_total"] == 0
            assert dst.stats()["jit_recompiles_total"] == 0
        finally:
            src.stop()
            dst.stop()

    @pytest.mark.slow
    def test_int8_kv_variant_parity(self, tiny_llama):
        """int8-KV blocks (values + seq-LAST scale buffers) survive the
        byte round trip bit-for-bit."""
        cfg, params = tiny_llama
        qcfg, qparams = llamalib.quantize_for_serving(
            cfg, params, weights=False, kv=True)
        kw = dict(num_slots=2, decode_chunk=2, prefix_cache=False,
                  block_size=16)
        ref = ContinuousEngine(qcfg, qparams, **kw)
        try:
            want = ref.generate(LONG, max_new_tokens=24)
        finally:
            ref.stop()
        src = ContinuousEngine(qcfg, qparams, **kw)
        dst = ContinuousEngine(qcfg, qparams, **kw)
        src.warmup()
        dst.warmup()
        try:
            req = src.submit(LONG, max_new_tokens=24)
            snap = _export_after(src, req, 2)
            assert snap is not None
            dst.import_sequence(snap, req=req)
            src.release_sequence(req)
            assert req.wait(300) == want
            assert src.stats()["jit_recompiles_total"] == 0
            assert dst.stats()["jit_recompiles_total"] == 0
        finally:
            src.stop()
            dst.stop()


class TestMigrationSafety:
    """Copy-then-cutover: failure leaves the source intact."""

    def test_destination_exhaustion_rejects_and_source_resumes(
            self, tiny_llama, oracle):
        src = make_engine(tiny_llama)
        dst = make_engine(tiny_llama, num_slots=2, num_blocks=2)
        try:
            req = src.submit(LONG, max_new_tokens=40)
            snap = _export_after(src, req, 3)
            assert snap is not None
            with pytest.raises(RuntimeError, match="blocks"):
                dst.import_sequence(snap, req=req)
            # nothing leaked on the destination, nothing held — the
            # ledger audit checks refcounts, not just the free count
            assert_no_leaks(dst)
            assert dst.stats()["kv_blocks_free"] == 2
            src.resume_sequence(req)
            assert req.wait(120) == oracle["long40"]
            assert len(req.tokens) == 40  # no duplicates, no drops
        finally:
            src.stop()
            dst.stop()

    def test_released_sequence_stays_prefix_matchable(self, tiny_llama):
        """Release registers the migrated-away content: a follow-up
        prompt sharing the conversation's prefix hits the source's
        block registry (the free list doubling as the prefix cache)."""
        src = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        dst = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            req = src.submit(LONG, max_new_tokens=24)
            snap = _export_after(src, req, 2)
            assert snap is not None
            dst.import_sequence(snap, req=req)
            src.release_sequence(req)
            req.wait(120)
            src.generate(LONG, max_new_tokens=4)
            assert src.prefix_hits >= 1
            assert src.stats()["prefix_block_hits_total"] >= 1
        finally:
            src.stop()
            dst.stop()

    def test_resume_after_other_slots_decoded_is_bit_identical(
            self, tiny_llama, oracle):
        """Regression (found by the ISSUE 10 resize parity suite): the
        pool decode scan recomputes EVERY row's logits — inactive rows
        included — so a slot frozen for migration had its next-token
        row silently clobbered while its neighbors kept decoding, and a
        failed transfer's resume sampled garbage.  The freeze now
        stashes the row and resume reinstalls it; a double export while
        frozen must also return the stable row, not the live one."""
        src = make_engine(tiny_llama)
        src.warmup()
        try:
            victim = src.submit([7, 8, 9], max_new_tokens=12)
            noisy = src.submit(LONG, max_new_tokens=40)
            snap1 = _export_after(src, victim, 3)
            assert snap1 is not None
            # the neighbor decodes on while the victim sits frozen
            n = len(noisy.tokens)
            deadline = time.time() + 60
            while len(noisy.tokens) < n + 6:
                assert time.time() < deadline
                time.sleep(0.005)
            # a re-export of the frozen slot reads the STASHED row
            snap2 = src.export_sequence(victim)
            assert np.array_equal(snap1["logits"], snap2["logits"])
            src.resume_sequence(victim)
            assert victim.wait(120) == oracle["short12"]
            assert noisy.wait(300) == oracle["long40"]
            assert src.stats()["jit_recompiles_total"] == 0
        finally:
            src.stop()

    def test_cancel_during_frozen_migration_frees_source(
            self, tiny_llama):
        """A client disconnect while the slot is frozen for transfer
        must still free the source slot (the sweep retires done
        requests, migrating or not)."""
        src = make_engine(tiny_llama)
        try:
            req = src.submit(LONG, max_new_tokens=40)
            snap = _export_after(src, req, 2)
            assert snap is not None
            req.cancel()
            # resume of a cancelled request is a no-op, never an error
            src.resume_sequence(req)
            # the cancel sweep retires the slot at the next boundary;
            # the ledger audit (mailbox-serviced AFTER that sweep's
            # cycle) replaces the free-count poll
            deadline = time.time() + 10
            while any(r is not None for r in src._slots):
                assert time.time() < deadline
                time.sleep(0.01)
            assert_no_leaks(src)
        finally:
            src.stop()


class TestDisaggregatedPool:
    KW = dict(num_slots=4, decode_chunk=2, prefix_cache=False,
              block_size=16, prefill_budget=16)

    def _mixed_oracle(self, tiny_llama):
        cfg, params = tiny_llama
        ref = ContinuousEngine(cfg, params, **self.KW)
        try:
            return (ref.generate(LONG, max_new_tokens=24),
                    ref.generate([7, 8, 9], max_new_tokens=12))
        finally:
            ref.stop()

    def test_roles_and_parity_in_process(self, tiny_llama):
        cfg, params = tiny_llama
        want_long, want_short = self._mixed_oracle(tiny_llama)
        pool = DisaggregatedPool(cfg, params, prefill_replicas=1,
                                 decode_replicas=2, **self.KW)
        try:
            pool.warmup()
            assert pool.generate(LONG, max_new_tokens=24,
                                 timeout=120) == want_long
            assert pool.generate([7, 8, 9], max_new_tokens=12,
                                 timeout=120) == want_short
            st = pool.stats()
            assert st["kv_migrations_total"] == 2  # one per handoff
            assert st["jit_recompiles_total"] == 0
            # role gate: decode engines never ran a prefill chunk, and
            # the decode tier emitted (essentially all) the tokens
            assert all(e.prefill_chunks_dispatched == 0
                       for e in pool.decode)
            assert sum(e.tokens_emitted for e in pool.decode) >= 30
            assert st["kv_migrate_latency_ms_count"] >= 2
        finally:
            pool.stop()

    @pytest.mark.slow
    def test_wire_transport_parity(self, tiny_llama):
        """The same handoffs over the authenticated kv_migrate TCP
        framing (the bytes a cross-host deployment ships)."""
        cfg, params = tiny_llama
        want_long, want_short = self._mixed_oracle(tiny_llama)
        pool = DisaggregatedPool(cfg, params, prefill_replicas=1,
                                 decode_replicas=1, wire=True,
                                 migrate_token="secret", **self.KW)
        try:
            pool.warmup()
            assert pool.generate(LONG, max_new_tokens=24,
                                 timeout=120) == want_long
            assert pool.generate([7, 8, 9], max_new_tokens=12,
                                 timeout=120) == want_short
            assert pool.stats()["kv_migrations_total"] >= 2
            assert pool._servers[0].imports_total >= 2
        finally:
            pool.stop()

    def test_sse_stream_survives_handoff(self, tiny_llama):
        """The front server re-targets the request handle when the KV
        moves from the prefill tier to the decode tier: one SSE stream,
        no reconnect, chunk concatenation == the blocking completion."""
        from kubeflow_tpu.serving.text import TextGenerator

        cfg, params = tiny_llama
        pool = DisaggregatedPool(cfg, params, prefill_replicas=1,
                                 decode_replicas=1, **self.KW)
        model = TextGenerator("m", {"tokenizer": "bytes"}, engine=pool)
        model.load()
        try:
            blocking = model.openai_completions(
                {"prompt": "hello world, this is a prompt",
                 "max_tokens": 16})
            want = blocking["choices"][0]["text"]
            chunks = []
            for raw in model.openai_stream(
                    {"prompt": "hello world, this is a prompt",
                     "max_tokens": 16, "stream": True}):
                line = raw.decode()
                if line.startswith("data: ") and "[DONE]" not in line:
                    import json as _json

                    chunks.append(_json.loads(
                        line[len("data: "):])["choices"][0]["text"])
            assert "".join(chunks) == want
            assert pool.stats()["kv_migrations_total"] >= 2
        finally:
            model.stop()


class TestDrainRebalance:
    def test_drain_moves_every_live_conversation(self, tiny_llama,
                                                 oracle):
        """migrate_live_sequences empties the source losslessly: all
        conversations resume on the destination with exact tokens, the
        source pool returns to its free baseline, and the latency
        histogram records every move."""
        src = make_engine(tiny_llama)
        dst = make_engine(tiny_llama)
        try:
            r1 = src.submit(LONG, max_new_tokens=40)
            r2 = src.submit([7, 8, 9], max_new_tokens=12)
            deadline = time.time() + 120
            while len(r1.tokens) < 2 or len(r2.tokens) < 2:
                assert time.time() < deadline, "no tokens emitted"
                time.sleep(0.002)
            moved, failed = migrate_live_sequences(src, dst)
            assert failed == 0 and moved >= 1
            assert r1.wait(120) == oracle["long40"]
            assert r2.wait(120) == oracle["short12"]
            # the drained source leaked nothing (ledger audit at a
            # scheduler boundary, replacing the free-baseline compare)
            assert_no_leaks(src, dst)
            assert src.stats()["kv_migrate_latency_ms_count"] == moved
            # defrag-for-free: the destination packed the sequences
            # into fresh blocks; nothing fragmented remains on src
            assert all(not b for b in src._slot_blocks)
        finally:
            src.stop()
            dst.stop()

    def test_controller_scale_down_migrates_replica(self, tiny_llama):
        """The ISvc drain hook: a retiring replica's live conversations
        move to a ready peer before the bounded drain runs."""
        from kubeflow_tpu.serving.controller import (
            InferenceServiceController,
        )
        from kubeflow_tpu.serving.server import ModelServer

        class _Shim:
            def __init__(self, engine):
                self.engine = engine

        src = make_engine(tiny_llama)
        dst = make_engine(tiny_llama)
        srv_a, srv_b = ModelServer(), ModelServer()
        srv_a._models["m"] = _Shim(src)
        srv_b._models["m"] = _Shim(dst)
        events = []

        class _Ctl:
            emit_event = staticmethod(
                lambda isvc, reason, msg: events.append((reason, msg)))

        class _Rev:
            predictors = [srv_a, srv_b]

        try:
            req = src.submit(LONG, max_new_tokens=120)
            deadline = time.time() + 120
            while len(req.tokens) < 2:
                assert time.time() < deadline, "no tokens emitted"
                time.sleep(0.002)
            moved = InferenceServiceController._migrate_replica_conversations(
                _Ctl(), None, _Rev(), srv_a)
            assert moved == 1
            assert events and events[0][0] == "ConversationsMigrated"
            assert dst._find_req_slot(req) is not None or req.done.is_set()
            assert len(req.wait(300)) == 120
        finally:
            src.stop()
            dst.stop()


class TestRoleKnobs:
    def test_bad_role_rejected_at_engine(self, tiny_llama):
        cfg, params = tiny_llama
        with pytest.raises(ValueError, match="role"):
            ContinuousEngine(cfg, params, block_size=16, role="sideways")
        with pytest.raises(ValueError, match="paged"):
            ContinuousEngine(cfg, params, role="prefill")
        with pytest.raises(ValueError, match="paged"):
            DisaggregatedPool(cfg, params)

    def test_bad_role_fails_isvc_at_conf_freeze(self):
        """Satellite: a bad ``role`` on an ISvc is ONE Failed status
        with the knob named — caught at conf-freeze, before any replica
        constructs (no crash-looping pods)."""
        import time as _time

        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="bad-role"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="llama-continuous"),
                    config={"params_ref": "mem://never-fetched",
                            "block_size": 16, "role": "sideways"}))))
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="bad-disagg"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="llama-continuous"),
                    config={"params_ref": "mem://never-fetched",
                            "disaggregation": {"prefill": 0}}))))
            for name, needle in (("bad-role", "role"),
                                 ("bad-disagg", "disaggregation")):
                deadline = _time.time() + 20
                isvc = None
                while _time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    _time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    isvc.status
                assert needle in (isvc.status.message or "")


class TestScatterWindow:
    """Satellite r11: the scatter write-window mask is a pure subset of
    the old full write-back — shared-prefix COW integrity and parity
    already pin it across the suite; here the helper's mask logic."""

    def test_write_window_mask(self):
        from kubeflow_tpu.serving.paged import write_window_tables

        bt = jnp.asarray([[3, 4, 5], [6, 7, 8]], jnp.int32)
        front = jnp.asarray([17, 48], jnp.int32)  # blocks of 16
        out = np.asarray(write_window_tables(bt, front, 16))
        # row 0 writes from pos 17 -> block 1 on: entry 0 masked
        assert out[0, 0] > 1 << 20 and (out[0, 1:] == [4, 5]).all()
        # row 1 writes from pos 48 = block 3 -> beyond the table: all
        # entries masked (an idle/inactive row scatters nothing)
        assert (out[1] > 1 << 20).all()
