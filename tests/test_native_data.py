"""Native data loader: C++/NumPy parity, packing semantics, corpus source."""

import numpy as np
import pytest

from kubeflow_tpu.native import load_library
from kubeflow_tpu.train.native_data import (
    PackedLmCorpus,
    TokenCorpus,
    gather_batch,
    pack_sequences,
    shuffle_indices,
)

EOS = 99


def _docs():
    rng = np.random.default_rng(0)
    return [rng.integers(1, 90, size=n).astype(np.int32)
            for n in (5, 17, 3, 40, 11, 29, 8)]


def _corpus(tmp_path):
    return TokenCorpus.write(str(tmp_path / "corpus"), _docs())


class TestNativeBuild:
    def test_library_builds(self):
        """g++ is part of this image; the native path must actually engage
        (the fallback exists for hosts without a toolchain)."""
        assert load_library() is not None


class TestParity:
    def test_shuffle_native_matches_fallback(self):
        for n, seed in ((1, 0), (2, 1), (100, 7), (1000, 12345)):
            a = shuffle_indices(n, seed)
            b = shuffle_indices(n, seed, force_fallback=True)
            np.testing.assert_array_equal(a, b)
            assert sorted(a.tolist()) == list(range(n))

    def test_shuffle_is_deterministic_and_seed_sensitive(self):
        np.testing.assert_array_equal(
            shuffle_indices(500, 3), shuffle_indices(500, 3))
        assert not np.array_equal(shuffle_indices(500, 3), shuffle_indices(500, 4))

    def test_pack_native_matches_fallback(self, tmp_path):
        c = _corpus(tmp_path)
        order = shuffle_indices(c.n_docs, 42)
        for row0, n_seqs, seq_len in ((0, 4, 7), (2, 3, 7), (0, 64, 5), (10, 8, 3)):
            a, rows_a = pack_sequences(
                c.tokens, c.offsets, order, EOS, seq_len, row0, n_seqs)
            b, rows_b = pack_sequences(
                c.tokens, c.offsets, order, EOS, seq_len, row0, n_seqs,
                force_fallback=True)
            np.testing.assert_array_equal(a, b)
            assert rows_a == rows_b

    def test_gather_native_matches_fallback(self):
        data = np.arange(60, dtype=np.int32).reshape(10, 6)
        idx = np.array([3, 3, 0, 9, 5], dtype=np.uint64)
        np.testing.assert_array_equal(
            gather_batch(data, idx), gather_batch(data, idx, force_fallback=True))


class TestPackingSemantics:
    def test_stream_reconstruction(self, tmp_path):
        """Unpacking the packed rows reproduces the shuffled EOS-separated
        stream exactly — no token lost, duplicated, or reordered."""
        c = _corpus(tmp_path)
        docs = _docs()
        order = shuffle_indices(c.n_docs, 1)
        seq_len = 6
        row = seq_len + 1
        stream = np.concatenate(
            [np.concatenate([docs[int(d)], [EOS]]) for d in order])
        epoch_rows = (len(stream) + row - 1) // row
        out, reported = pack_sequences(
            c.tokens, c.offsets, order, EOS, seq_len, 0, epoch_rows)
        assert reported == epoch_rows
        flat = out.reshape(-1)
        np.testing.assert_array_equal(flat[: len(stream)], stream)
        assert (flat[len(stream):] == EOS).all()  # tail padding

    def test_windowed_equals_full(self, tmp_path):
        c = _corpus(tmp_path)
        order = shuffle_indices(c.n_docs, 2)
        full, rows = pack_sequences(c.tokens, c.offsets, order, EOS, 4, 0, 12)
        for row0 in (0, 3, 7):
            win, _ = pack_sequences(c.tokens, c.offsets, order, EOS, 4, row0, 3)
            np.testing.assert_array_equal(win, full[row0: row0 + 3])


class TestPackedLmCorpus:
    def test_process_shards_are_disjoint_and_cover(self, tmp_path):
        c = _corpus(tmp_path)
        gb, seq = 4, 5
        shards = [
            PackedLmCorpus(c, gb, seq, eos=EOS, process_index=p,
                           process_count=2, seed=9).local_batch(0)["tokens"]
            for p in (0, 2 // 2)
        ]
        whole = PackedLmCorpus(
            c, gb, seq, eos=EOS, process_index=0, process_count=1,
            seed=9).local_batch(0)["tokens"]
        np.testing.assert_array_equal(np.concatenate(shards), whole)

    def test_resume_reproduces_batches(self, tmp_path):
        c = _corpus(tmp_path)
        src = PackedLmCorpus(c, 2, 5, eos=EOS, process_index=0,
                             process_count=1, seed=5)
        want = [src.local_batch(s)["tokens"] for s in range(6)]
        fresh = PackedLmCorpus(c, 2, 5, eos=EOS, process_index=0,
                               process_count=1, seed=5)
        got = [fresh.local_batch(s)["tokens"] for s in range(6)]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)

    def test_epochs_reshuffle(self, tmp_path):
        c = _corpus(tmp_path)
        src = PackedLmCorpus(c, 2, 5, eos=EOS, process_index=0,
                             process_count=1, seed=5)
        e0 = np.concatenate(
            [src.local_batch(s)["tokens"] for s in range(src.batches_per_epoch)])
        e1 = np.concatenate(
            [src.local_batch(src.batches_per_epoch + s)["tokens"]
             for s in range(src.batches_per_epoch)])
        assert not np.array_equal(e0, e1)
        # same multiset of non-padding tokens both epochs
        assert sorted(e0[e0 != EOS].tolist()) == sorted(e1[e1 != EOS].tolist())

    def test_too_small_corpus_rejected(self, tmp_path):
        c = TokenCorpus.write(
            str(tmp_path / "small"), [np.array([1, 2, 3], np.int32)])
        with pytest.raises(ValueError, match="smaller than one global batch"):
            PackedLmCorpus(c, 64, 1024, process_index=0, process_count=1)


class TestTrainerIntegration:
    def test_llama_trains_on_packed_corpus(self, tmp_path):
        """The real-corpus path end to end: TokenCorpus -> native packing ->
        sharded trainer; loss drops on structured (repetitive) data."""
        from kubeflow_tpu.models import llama
        from kubeflow_tpu.train import trainer as trainlib

        rng = np.random.default_rng(3)
        # repetitive documents = learnable next-token structure
        base = rng.integers(1, 250, size=64).astype(np.int32)
        docs = [np.tile(base, 4) for _ in range(64)]
        c = TokenCorpus.write(str(tmp_path / "c"), docs)
        cfg = trainlib.TrainConfig(
            model=llama.tiny(), mesh_axes={"data": 4, "model": 2},
            global_batch=8, seq_len=32, steps=20, learning_rate=1e-2,
            warmup_steps=2, log_every=2)
        src = PackedLmCorpus(c, cfg.global_batch, cfg.seq_len, eos=0,
                             process_index=0, process_count=1)
        seen = []
        trainlib.Trainer(cfg).train(source=src, on_metrics=seen.append)
        assert seen[-1].loss < seen[0].loss
