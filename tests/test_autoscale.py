"""Predictive cluster autoscaler (ISSUE 15) units.

- TABLE-DRIVEN decide(): one sensor window -> exactly one expected
  action (or none inside the hysteresis band), enumerated row by row
  over the decision priority list.
- Cooldown / backoff / park mechanics: ActuatorState math and the
  ClusterAutoscaler tick gating built on it.
- TrendPredictor: EWMA level, least-squares slope, forecast.
- validate_autoscale: the ISvc ``autoscale:`` conf-freeze contract.
- SessionReaper: a quiet session-tagged sequence is hibernated to the
  spill store by the idle clock and thaws BIT-IDENTICALLY (the PR 11
  parity bar), on the same engine or a fresh replica.
- Equal-chip-seconds scorer (scripts/autoscale_bench.py pure helpers):
  trace integration, static-equivalent sizing, per-class attainment,
  seeded arrival determinism.
"""

from __future__ import annotations

import importlib.util
import math
import os
import time

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.analysis.runtime import BlockLedger
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.autoscale import (
    ACTIONS,
    ACTUATOR_OF,
    ActuatorState,
    AutoscalePolicy,
    ClusterAutoscaler,
    SessionReaper,
    TrendPredictor,
    decide,
    validate_autoscale,
)
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.storage import KvSpillStore


# -- decide(): the table ---------------------------------------------------

POL = AutoscalePolicy(scale_to_zero=True, tp_degrees=(1, 2, 4))

#: (name, sig, expected_action) — POL unless the row carries its own
DECIDE_TABLE = [
    ("wake_on_pending",
     {"replicas": 0, "min_replicas": 0, "max_replicas": 4, "pending": 1},
     "wake"),
    ("wake_on_util",
     {"replicas": 0, "min_replicas": 0, "max_replicas": 4, "util": 0.2},
     "wake"),
    ("zero_idle_no_demand",
     {"replicas": 0, "min_replicas": 0, "max_replicas": 4},
     "none"),
    ("up_on_shed",
     {"replicas": 1, "max_replicas": 4, "util": 0.8, "shed_rate": 0.5},
     "scale_up"),
    ("up_on_queue_wait",
     {"replicas": 1, "max_replicas": 4, "util": 0.8, "queue_wait_s": 2.0},
     "scale_up"),
    ("up_on_block_famine",
     {"replicas": 1, "max_replicas": 4, "util": 0.8,
      "free_block_ratio": 0.01},
     "scale_up"),
    ("up_on_forecast",
     {"replicas": 2, "max_replicas": 4, "util": 1.0, "util_forecast": 1.5},
     "scale_up"),
    ("no_up_at_max_no_degrees",
     {"replicas": 4, "max_replicas": 4, "util": 2.0, "util_forecast": 2.0,
      "degree": 0},
     "none"),
    ("resize_up_at_max",
     {"replicas": 4, "max_replicas": 4, "util": 2.0, "util_forecast": 2.0,
      "degree": 2},
     "resize_up"),
    ("resize_up_no_bigger_degree",
     {"replicas": 4, "max_replicas": 4, "util": 2.0, "util_forecast": 2.0,
      "degree": 4},
     "none"),
    ("zero_when_idle",
     {"replicas": 1, "min_replicas": 0, "max_replicas": 4, "util": 0.0,
      "idle_s": 120.0, "live": 0.0},
     "scale_to_zero"),
    ("no_zero_with_live_sessions",
     {"replicas": 1, "min_replicas": 0, "max_replicas": 4, "util": 0.0,
      "idle_s": 120.0, "live": 2.0},
     "none"),
    ("no_zero_over_cold_budget",
     {"replicas": 1, "min_replicas": 0, "max_replicas": 4, "util": 0.0,
      "idle_s": 120.0, "live": 0.0, "cold_start_s": 99.0},
     "none"),
    ("no_zero_with_min_floor",
     {"replicas": 1, "min_replicas": 1, "max_replicas": 4, "util": 0.0,
      "idle_s": 120.0, "live": 0.0, "degree": 4},
     "resize_down"),  # floor holds; a lower degree exists -> shrink TP
    ("down_below_band",
     {"replicas": 3, "min_replicas": 1, "max_replicas": 4, "util": 0.2,
      "util_forecast": 0.2},
     "scale_down"),
    ("no_down_on_forecast_dip_alone",
     {"replicas": 3, "min_replicas": 1, "max_replicas": 4, "util": 0.8,
      "util_forecast": 0.2},
     "none"),
    ("no_down_on_current_dip_alone",
     {"replicas": 3, "min_replicas": 1, "max_replicas": 4, "util": 0.2,
      "util_forecast": 0.8},
     "none"),
    ("resize_down_at_floor",
     {"replicas": 1, "min_replicas": 1, "max_replicas": 4, "util": 0.1,
      "util_forecast": 0.1, "degree": 4},
     "resize_down"),
    ("resize_down_already_smallest",
     {"replicas": 1, "min_replicas": 1, "max_replicas": 4, "util": 0.1,
      "util_forecast": 0.1, "degree": 1},
     "none"),
    ("tier_toward_prefill",
     {"replicas": 2, "min_replicas": 2, "max_replicas": 2, "util": 1.0,
      "prefill_pressure": 6.0, "decode_pressure": 1.0,
      "prefill_replicas": 1, "decode_replicas": 3},
     "tier_rebalance"),
    ("tier_toward_decode",
     {"replicas": 2, "min_replicas": 2, "max_replicas": 2, "util": 1.0,
      "prefill_pressure": 1.0, "decode_pressure": 6.0,
      "prefill_replicas": 2, "decode_replicas": 1},
     "tier_rebalance"),
    ("tier_no_spare_engine",
     {"replicas": 2, "min_replicas": 2, "max_replicas": 2, "util": 1.0,
      "prefill_pressure": 6.0, "decode_pressure": 1.0,
      "prefill_replicas": 1, "decode_replicas": 1},
     "none"),
    ("hysteresis_hold",
     {"replicas": 2, "min_replicas": 1, "max_replicas": 4, "util": 0.9,
      "util_forecast": 1.1},
     "none"),
]


class TestDecide:
    @pytest.mark.parametrize(
        "name,sig,expected", DECIDE_TABLE,
        ids=[row[0] for row in DECIDE_TABLE])
    def test_table(self, name, sig, expected):
        dec = decide(sig, POL)
        assert dec.action == expected, (name, dec.reason)
        assert dec.action in ACTIONS
        if expected != "none":
            assert dec.actuator == ACTUATOR_OF[expected]
            assert dec.reason

    def test_one_action_per_tick_payloads(self):
        up = decide({"replicas": 2, "max_replicas": 4, "util": 3.0,
                     "util_forecast": 3.0}, POL)
        assert up.replicas == 3
        rz = decide({"replicas": 4, "max_replicas": 4, "util": 3.0,
                     "util_forecast": 3.0, "degree": 2}, POL)
        assert rz.degree == 4  # next configured step up from 2
        down = decide({"replicas": 3, "min_replicas": 1, "max_replicas": 4,
                       "util": 0.1, "util_forecast": 0.1}, POL)
        assert down.replicas == 2
        tier = decide({"replicas": 2, "min_replicas": 2, "max_replicas": 2,
                       "util": 1.0, "prefill_pressure": 6.0,
                       "decode_pressure": 1.0, "prefill_replicas": 1,
                       "decode_replicas": 3}, POL)
        assert tier.prefill == 2

    def test_slo_pressure_outranks_bands(self):
        # utilization says shrink, a shed says grow: SLO pressure wins
        dec = decide({"replicas": 2, "min_replicas": 1, "max_replicas": 4,
                      "util": 0.1, "util_forecast": 0.1,
                      "shed_rate": 1.0}, POL)
        assert dec.action == "scale_up"


# -- validator -------------------------------------------------------------

class TestValidateAutoscale:
    def test_valid_spec_normalizes(self):
        spec = {"target_concurrency": 8, "high_band": 1.5,
                "low_band": 0.4, "tp_degrees": [1, 2, 4],
                "scale_to_zero": True}
        assert validate_autoscale(spec) == spec
        pol = AutoscalePolicy.from_config(spec)
        assert pol.tp_degrees == (1, 2, 4)
        assert pol.target_concurrency == 8.0

    @pytest.mark.parametrize("spec,needle", [
        ({"bogus_knob": 1}, "unknown"),
        ({"high_band": 0.5, "low_band": 0.5}, "hysteresis"),
        ({"low_band": -0.1}, "hysteresis"),
        ({"target_concurrency": 0}, "positive"),
        ({"window_s": -1}, "positive"),
        ({"free_block_low": 1.5}, "[0, 1)"),
        ({"max_retries": 0}, ">= 1"),
        ({"tp_degrees": [4, 2]}, "increasing"),
        ({"tp_degrees": [1, 1, 2]}, "increasing"),
        ({"tp_degrees": [0, 2]}, "increasing"),
        ({"scale_to_zero": "yes"}, "bool"),
        ("not-a-dict", "mapping"),
    ])
    def test_bad_specs_raise(self, spec, needle):
        with pytest.raises(ValueError, match=None) as ei:
            validate_autoscale(spec)
        assert needle in str(ei.value)


# -- predictor -------------------------------------------------------------

class TestTrendPredictor:
    def test_constant_series(self):
        p = TrendPredictor(window_s=10.0)
        for k in range(20):
            p.observe(float(k), 4.0)
        assert p.level == pytest.approx(4.0)
        assert p.slope == pytest.approx(0.0, abs=1e-9)
        assert p.forecast(5.0) == pytest.approx(4.0)

    def test_linear_ramp_slope_and_forecast(self):
        p = TrendPredictor(window_s=100.0, alpha=1.0)  # level = last
        for k in range(11):
            p.observe(float(k), 2.0 * k)
        assert p.slope == pytest.approx(2.0)
        assert p.forecast(3.0) == pytest.approx(20.0 + 6.0)

    def test_window_retires_old_samples(self):
        p = TrendPredictor(window_s=5.0)
        p.observe(0.0, 100.0)
        for k in range(1, 12):
            p.observe(float(k), 1.0)
        assert p.n <= 6  # the t=0 spike aged out of the window
        assert all(v == 1.0 for _t, v in p._samples)

    def test_empty_predictor_neutral(self):
        p = TrendPredictor()
        assert p.level == 0.0
        assert p.slope == 0.0
        assert p.forecast(10.0) == 0.0


# -- actuator state machine ------------------------------------------------

class TestActuatorState:
    def test_cooldown_gates_refire(self):
        st = ActuatorState("x", cooldown_s=10.0)
        assert st.ready(0.0)
        st.note_fired(0.0)
        st.note_ok()
        assert not st.ready(5.0)
        assert st.ready(10.0)

    def test_backoff_doubles_to_cap_then_parks(self):
        st = ActuatorState("x", cooldown_s=0.0, max_retries=4,
                           backoff_s=1.0, backoff_cap_s=3.0)
        st.note_fired(0.0)
        st.note_failed(0.0)
        assert st.blocked_until == pytest.approx(1.0)   # 1 * 2^0
        st.note_failed(10.0)
        assert st.blocked_until == pytest.approx(12.0)  # 1 * 2^1
        st.note_failed(20.0)
        assert st.blocked_until == pytest.approx(23.0)  # capped at 3
        assert not st.parked
        st.note_failed(30.0)
        assert st.parked
        assert not st.ready(1e9)  # parked ignores time entirely
        st.reset()
        assert st.ready(1e9)
        assert st.failures == 0

    def test_success_clears_failure_streak(self):
        st = ActuatorState("x", cooldown_s=0.0, max_retries=2)
        st.note_failed(0.0)
        st.note_ok()
        st.note_failed(100.0)
        assert not st.parked  # streak restarted — not cumulative


# -- the loop: cooldowns + gating over a fake clock ------------------------

class TestClusterAutoscalerLoop:
    def _auto(self, sig, fired, **pol_kw):
        pol_kw.setdefault("up_cooldown_s", 5.0)
        policy = AutoscalePolicy(**pol_kw)
        return ClusterAutoscaler(
            policy, sensors=lambda: dict(sig),
            actuators={"replica_up": lambda d: fired.append(d.action)})

    def test_cooldown_enforced_between_fires(self):
        fired = []
        sig = {"replicas": 1, "max_replicas": 4, "util": 5.0}
        auto = self._auto(sig, fired)
        assert auto.tick(now=100.0).action == "scale_up"
        gated = auto.tick(now=101.0)
        assert gated.action == "none" and "cooldown" in gated.reason
        assert auto.tick(now=105.0).action == "scale_up"
        assert fired == ["scale_up", "scale_up"]

    def test_missing_actuator_skips_clean(self):
        sig = {"replicas": 3, "min_replicas": 1, "max_replicas": 4,
               "util": 0.0}
        auto = self._auto(sig, [])
        for k in range(30):  # let the forecast fall below the band
            dec = auto.tick(now=100.0 + k)
        assert dec.action == "none" and "no replica_down actuator" in dec.reason
        assert auto.actuator_skips_total >= 1

    def test_sensor_error_counted_not_fatal(self):
        def broken():
            raise OSError("sensor torn")
        auto = ClusterAutoscaler(AutoscalePolicy(), sensors=broken)
        dec = auto.tick(now=1.0)
        assert dec.action == "none" and "sensor error" in dec.reason
        assert auto.sensor_errors_total == 1
        assert auto.tick(now=2.0).action == "none"  # loop survives

    def test_stats_and_metrics_surface(self):
        fired = []
        sig = {"replicas": 1, "max_replicas": 4, "util": 5.0}
        auto = self._auto(sig, fired)
        auto.tick(now=100.0)
        s = auto.stats()
        assert s["autoscale_ticks_total"] == 1
        assert s["decisions"]["scale_up"] == 1
        lines = auto.metrics_lines()
        assert any(line.startswith("kft_autoscale_ticks_total")
                   for line in lines)
        assert any('action="scale_up"' in line for line in lines)


# -- idle-session reaper: reap -> thaw bit-identical -----------------------

LONG = list(range(1, 65))


def _make_engine(tiny):
    cfg, params = tiny
    eng = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                           prefix_cache=False, block_size=16)
    eng.attach_block_ledger(BlockLedger())
    return eng


@pytest.fixture(scope="module")
def tiny():
    cfg = llamalib.tiny()
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    return cfg, params


class TestSessionReaper:
    def test_rejects_nonpositive_idle_clock(self):
        with pytest.raises(ValueError, match="reap_idle_s"):
            SessionReaper(lambda: [], 0.0)

    def test_scan_skips_engines_without_spill_store(self, tiny):
        eng = _make_engine(tiny)
        try:
            reaper = SessionReaper(lambda: [eng, object()], 0.001)
            assert reaper.scan(now=time.perf_counter() + 999) == 0
        finally:
            eng.stop()

    def test_reap_then_thaw_bit_identical(self, tiny, tmp_path):
        """The satellite's parity bar: the reaper hibernates a quiet
        session mid-stream; the thawed continuation matches the
        uninterrupted greedy oracle exactly, with zero recompiles and
        a clean block ledger."""
        oracle_eng = _make_engine(tiny)
        try:
            oracle = oracle_eng.generate(LONG, max_new_tokens=24)
        finally:
            oracle_eng.stop()

        store = KvSpillStore(str(tmp_path))
        eng = _make_engine(tiny)
        try:
            eng.attach_spill_store(store)
            req = eng.submit(LONG, max_new_tokens=24, session_id="conv-r")
            deadline = time.time() + 120
            while len(req.tokens) < 8:
                assert time.time() < deadline
                time.sleep(0.01)
            delivered = list(req.tokens)
            reaper = SessionReaper(lambda: [eng], idle_s=3600.0)
            # a live stream is NEVER quiet on the real clock...
            assert reaper.scan() == 0
            # ...but is once the idle clock has genuinely elapsed
            # (probe at a future now instead of sleeping an hour)
            reaped = reaper.scan(now=time.perf_counter() + 7200.0)
            assert reaped == 1
            assert reaper.stats()["sessions_reaped_total"] == 1
            assert eng.stats()["kv_sessions_hibernated"] == 1
            assert not req.done.is_set()  # parked durable, not failed

            req2, info = eng.thaw_sequence("conv-r")
            out = req2.wait(120)
            assert out == oracle  # bit-identical continuation
            assert out[: len(delivered)] == delivered
            assert eng.stats()["jit_recompiles_total"] == 0
            assert eng.stats()["kv_blocks_leaked_total"] == 0
            assert eng.audit_blocks() == []
        finally:
            eng.stop()


# -- equal-chip-seconds scorer (the bench's pure helpers) ------------------

def _bench_mod():
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "autoscale_bench.py")
    spec = importlib.util.spec_from_file_location("autoscale_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestScorer:
    def test_chip_seconds_step_integral(self):
        b = _bench_mod()
        # 1 replica for 10s, 3 for 10s, 2 for 10s = 60 chip-seconds
        trace = [(0.0, 1), (10.0, 3), (20.0, 2)]
        assert b.chip_seconds(trace, 30.0) == pytest.approx(60.0)
        # truncation at end_s ignores the tail
        assert b.chip_seconds(trace, 15.0) == pytest.approx(25.0)

    def test_static_equivalent_rounds(self):
        b = _bench_mod()
        assert b.static_replicas_for(60.0, 30.0) == 2
        assert b.static_replicas_for(44.0, 30.0) == 1
        assert b.static_replicas_for(0.0, 30.0) == 1  # floor

    def test_slo_attainment_counts_drops_as_misses(self):
        b = _bench_mod()
        lats = {"gold": [0.5, 1.0, float("inf")],
                "silver": [3.0, 5.0], "bronze": []}
        att = b.slo_attainment(lats)
        assert att["gold"] == pytest.approx(2 / 3)
        assert att["silver"] == pytest.approx(1 / 2)
        assert att["bronze"] == 1.0  # no traffic = no misses

    def test_diurnal_arrivals_seeded_and_shaped(self):
        b = _bench_mod()
        a1 = b.diurnal_arrivals(7, 10.0, 10.0)
        a2 = b.diurnal_arrivals(7, 10.0, 10.0)
        assert a1 == a2  # deterministic
        assert a1 == sorted(a1)
        assert {cls for _t, cls in a1} <= set(b.CLASSES)
        assert all(0.0 <= t <= 10.0 for t, _ in a1)
        a3 = b.diurnal_arrivals(8, 10.0, 10.0)
        assert a3 != a1  # the seed actually matters
