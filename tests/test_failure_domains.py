"""Correlated-failure survival (ISSUE 16).

Layers, matching the tentpole:

- HEALTH CIRCUITS: per-backend closed -> open -> half-open state
  machine (consecutive + error-rate trips, jittered recovery, single
  probe, doubled backoff on a failed probe);
- RETRY BUDGET: re-routes as a capped fraction of recent successes —
  the amplification bound the outage bench pins;
- JITTERED RETRY-AFTER: the one shared load-aware hint both the shed
  path and the router's 503 ride;
- FAILURE DOMAINS: the router's url -> domain map, the one-pass
  mass-forget when a whole domain dies, the scale-down victim guard
  that never empties a domain, and conf-freeze validation of the
  ``domains`` knob;
- EMERGENCY AUTOSCALE: the decide() surge row, the tick() cooldown
  bypass (bounded, never past a parked channel), and the
  ConcurrencyGate the cold-start/thaw stampede paths share;
- CHAOS: ``FaultPlan.domain_outage`` is seeded at plan build and fires
  exactly once;
- MASS RECOVERY: hibernated sessions thaw on a survivor exactly once
  (spill entry consumed, zero recompiles, ledger clean), with the
  thaw gate serializing the herd.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.analysis.runtime import BlockLedger
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.storage import KvSpillStore, SpillCorrupt
from kubeflow_tpu.serving.traffic import (
    BackendHealth,
    ClusterPrefixPoller,
    RetryBudget,
    TrafficPlane,
    jittered_retry_after,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64 tokens = 4 blocks at block_size 16


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("block_size", 16)
    eng = ContinuousEngine(cfg, params, **kw)
    eng.attach_block_ledger(BlockLedger())
    return eng


def assert_no_leaks(*engines):
    for eng in engines:
        assert eng.audit_blocks() == []
        assert eng.stats()["kv_blocks_leaked_total"] == 0
        assert eng.block_ledger.conservation_errors == []


def post(url: str, payload: dict, headers=None, timeout: float = 60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, dict(e.headers), body


class _Stub:
    """Constant-latency JSON responder: the routing-layer tests measure
    circuits / budget / mass-forget, so the data plane is a stub — no
    jax, sub-second tests.  GET /metrics serves optional prefix rows so
    the poller tests can scrape it."""

    def __init__(self, metrics_text: str = ""):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        stub = self

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0) or 0))
                stub.requests += 1
                body = b'{"choices": [{"text": "ok"}]}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = stub.metrics_text.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.requests = 0
        self.metrics_text = metrics_text
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def _dead_url() -> str:
    """A URL nothing listens on (bind, grab the port, close)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"http://127.0.0.1:{port}"


# -- health circuits ------------------------------------------------------


class TestBackendHealth:
    def test_consecutive_failures_trip_and_success_closes(self):
        h = BackendHealth(fail_threshold=3, open_s=0.05, probe_jitter=0.0)
        b = "http://b0"
        for _ in range(2):
            h.note_failure(b)
        assert h.state(b) == "closed"
        assert h.routable([b]) == [b]
        h.note_failure(b)
        assert h.state(b) == "open"
        assert h.routable([b]) == []
        assert h.open_backends() == [b]
        # past the recovery deadline the backend is routable again;
        # a success (the probe's outcome) closes the circuit
        time.sleep(0.06)
        assert h.routable([b]) == [b]
        h.note_success(b)
        assert h.state(b) == "closed"
        st = h.stats()
        assert st["circuit_opens_total"] == 1
        assert st["circuit_closes_total"] == 1

    def test_error_rate_trips_without_consecutive(self):
        h = BackendHealth(fail_threshold=100, error_rate=0.5, window=4)
        b = "http://b0"
        # alternate failure/success: consecutive never reaches 100 but
        # the 4-wide window eventually hits 2/4 = 50% failures
        h.note_failure(b)
        h.note_success(b)
        h.note_failure(b)
        h.note_success(b)
        assert h.state(b) == "closed"
        h.note_failure(b)
        assert h.state(b) == "open"

    def test_half_open_single_probe_and_doubled_backoff(self):
        h = BackendHealth(fail_threshold=1, open_s=0.05, open_cap_s=10.0,
                          probe_jitter=0.0)
        b = "http://b0"
        h.note_failure(b)
        assert h.state(b) == "open"
        time.sleep(0.06)
        # two-phase: routable() is a pure filter (no probe armed yet),
        # on_routed() arms exactly one probe
        assert h.routable([b]) == [b]
        assert h.routable([b]) == [b]
        h.on_routed(b)
        assert h.state(b) == "half_open"
        assert h.routable([b]) == []  # one probe at a time
        # a failed probe re-opens with DOUBLED backoff: the old 0.05s
        # deadline is not enough anymore
        h.note_failure(b)
        assert h.state(b) == "open"
        time.sleep(0.06)
        assert h.routable([b]) == []
        time.sleep(0.06)
        assert h.routable([b]) == [b]
        assert h.stats()["circuit_probes_total"] == 1

    def test_trip_forces_open_and_forget_resets(self):
        h = BackendHealth()
        b = "http://b0"
        h.trip(b)
        assert h.state(b) == "open"
        h.forget(b)
        assert h.state(b) == "closed"
        assert h.routable([b]) == [b]

    def test_unknown_backend_is_closed_and_routable(self):
        h = BackendHealth()
        assert h.state("http://never-seen") == "closed"
        assert h.routable(["http://never-seen"]) == ["http://never-seen"]

    @pytest.mark.parametrize("kw", [
        {"fail_threshold": 0},
        {"error_rate": 0.0},
        {"error_rate": 1.5},
        {"open_s": 0.0},
        {"open_s": 2.0, "open_cap_s": 1.0},
    ])
    def test_bad_knobs_rejected(self, kw):
        with pytest.raises(ValueError):
            BackendHealth(**kw)


class TestRetryBudget:
    def test_burst_then_deny_and_success_refunds(self):
        # floor disabled: the success-funded bucket is the whole budget
        rb = RetryBudget(ratio=0.2, burst=3.0, floor_rate=0.0)
        assert [rb.try_retry() for _ in range(3)] == [True] * 3
        assert rb.try_retry() is False
        # 5 successes at ratio 0.2 fund exactly one more retry
        for _ in range(5):
            rb.note_success()
        assert rb.try_retry() is True
        assert rb.try_retry() is False
        st = rb.stats()
        assert st["retries_granted_total"] == 4
        assert st["retries_denied_total"] == 2

    def test_floor_keeps_single_failover_alive(self):
        rb = RetryBudget(ratio=0.0, burst=1.0, floor_rate=1000.0)
        assert rb.try_retry() is True   # the burst token
        assert rb.try_retry() is True   # the floor trickle
        assert rb.stats()["retries_denied_total"] == 0

    @pytest.mark.parametrize("kw", [{"ratio": -0.1}, {"burst": 0.5}])
    def test_bad_knobs_rejected(self, kw):
        with pytest.raises(ValueError):
            RetryBudget(**kw)


class TestJitteredRetryAfter:
    def test_bounds_and_spread(self):
        xs = [jittered_retry_after(base=1.0, spread=0.5)
              for _ in range(200)]
        assert all(0.5 <= x <= 1.5 for x in xs)
        assert max(xs) - min(xs) > 0.1  # it actually jitters

    def test_load_raises_hint_and_cap_clamps(self):
        assert jittered_retry_after(base=1.0, load=10.0, spread=0.0) \
            == pytest.approx(11.0)
        assert jittered_retry_after(base=1.0, load=100.0) <= 30.0
        assert jittered_retry_after(base=0.0, load=0.0, spread=0.0) \
            == pytest.approx(0.05)


# -- poller backoff (satellite) -------------------------------------------


class TestPollerBackoff:
    def test_unreachable_backend_skipped_with_backoff(self):
        dead = _dead_url()
        poller = ClusterPrefixPoller(lambda: [dead], interval_s=3600.0)
        try:
            poller.poll_once()   # connect refused: enters backoff
            assert poller.poll_skips_total == 0
            poller.poll_once()   # inside the (hours-long) window: skip
            assert poller.poll_skips_total == 1
            poller.poll_once()
            assert poller.poll_skips_total == 2
        finally:
            poller.stop()

    def test_reachable_backend_clears_backoff_and_keeps_heat(self):
        key = "ab" * 8
        stub = _Stub(metrics_text=(
            "# TYPE kft_kv_prefix_key gauge\n"
            f'kft_kv_prefix_key{{model="m",key="{key}"}} 2\n'))
        poller = ClusterPrefixPoller(lambda: [stub.url],
                                     interval_s=0.01, jitter=0.0)
        try:
            poller.poll_once()
            assert poller.heat() == {key: 1}
            stub.stop()
            # the scrape fails now; prior heat survives (one flaky
            # scrape must not flap the census down), url backs off
            poller.poll_once()
            assert poller.heat() == {key: 1}
            time.sleep(0.05)  # past the tiny first backoff window
            poller.poll_once()  # re-probe (fails again, doubled delay)
            assert poller.heat() == {key: 1}
        finally:
            poller.stop()


# -- failure domains on the router ----------------------------------------


class TestRouterDomains:
    def _router(self, urls, domains):
        from kubeflow_tpu.serving.controller import Router

        router = Router(activate=lambda: None)
        router.set_backends(urls)
        router.set_traffic(TrafficPlane({}))
        router.set_domains(domains)
        return router

    def test_domain_outage_mass_forget_fires_once(self):
        urls = ["http://d0-a", "http://d0-b", "http://d1-a", "http://d1-b"]
        doms = {urls[0]: "d0", urls[1]: "d0",
                urls[2]: "d1", urls[3]: "d1"}
        router = self._router(urls, doms)
        try:
            # seed affinity + session state pointing at d0
            router.traffic.affinity.observe([101, 102], urls[0])
            router.traffic.sessions.observe("conv-1", urls[1])
            router.traffic.sessions.observe("conv-2", urls[2])
            # d0-a's circuit opens: no outage yet (d0-b still closed)
            for _ in range(3):
                router._backend_down(urls[0])
            assert router.domain_outages_total == 0
            assert router.traffic.sessions.best(
                "conv-1", urls) == urls[1]
            # d0-b opens too -> the WHOLE domain is down: one-pass
            # mass-forget of its affinity/session rows
            for _ in range(3):
                router._backend_down(urls[1])
            assert router.domain_outages_total == 1
            assert router.traffic.sessions.best("conv-1", urls) is None
            assert router.traffic.affinity.best(
                [101, 102], urls) == (None, 0)
            # the survivor domain's rows are untouched
            assert router.traffic.sessions.best(
                "conv-2", urls) == urls[2]
            # fires ONCE: more failures on the dead domain do not
            # re-declare it
            router._backend_down(urls[0])
            assert router.domain_outages_total == 1
            # a successful forward into d0 is the all-clear (re-arms)
            router._backend_up(urls[0])
            assert "d0" not in router._domains_down
        finally:
            router.stop()

    def test_total_collapse_declares_only_the_first_domain(self):
        # d0 dies while d1 serves: a domain outage.  Then d1 dies too:
        # total collapse, NOT a second domain outage — there is no
        # survivor left to mass-forget toward
        urls = ["http://d0-a", "http://d1-a"]
        router = self._router(
            urls, {urls[0]: "d0", urls[1]: "d1"})
        try:
            for _ in range(3):
                router._backend_down(urls[0])
            assert router.domain_outages_total == 1
            for _ in range(3):
                router._backend_down(urls[1])
            assert router.domain_outages_total == 1
        finally:
            router.stop()

    def test_implicit_single_domain_never_declares_outage(self):
        # domains unset: every url maps to "" and the outage machinery
        # stays inert — the pre-PR behavior contract
        urls = ["http://a", "http://b"]
        router = self._router(urls, {})
        try:
            for u in urls:
                for _ in range(3):
                    router._backend_down(u)
            assert router.domain_outages_total == 0
            assert router.domain_of(urls[0]) == ""
        finally:
            router.stop()

    def test_metrics_export_circuit_and_outage_rows(self):
        urls = ["http://d0-a", "http://d0-b", "http://d1-a"]
        doms = {urls[0]: "d0", urls[1]: "d0", urls[2]: "d1"}
        router = self._router(urls, doms)
        try:
            for u in urls[:2]:
                for _ in range(3):
                    router._backend_down(u)
            text = router.metrics_text()
            assert "# TYPE kft_router_circuit_open gauge" in text
            assert ('kft_router_circuit_open{backend="http://d0-a",'
                    'domain="d0"} 1') in text
            assert ('kft_router_circuit_open{backend="http://d1-a",'
                    'domain="d1"} 0') in text
            assert "kft_router_domain_outages_total 1" in text
            assert "kft_router_circuit_opens_total" in text
            assert "kft_router_retry_budget_tokens" in text
        finally:
            router.stop()

    def test_storm_reroutes_to_survivor_and_declares_outage(self):
        """End to end over real sockets: kill one domain's only
        backend mid-traffic — every request still resolves 200 via the
        survivor (the in-request re-route), the corpse's circuit opens
        and the domain is declared down."""
        stubs = {"d0": _Stub(), "d1": _Stub()}
        urls = [stubs["d0"].url, stubs["d1"].url]
        router = self._router(
            urls, {stubs[d].url: d for d in stubs})
        t0 = time.perf_counter()
        try:
            url = router.url + "/openai/v1/completions"
            body = {"model": "m", "prompt": "x", "max_tokens": 2}
            code, _, _ = post(url, body, timeout=30)
            assert code == 200
            stubs["d0"].stop()  # the whole d0 domain dies at once
            codes = [post(url, body, timeout=30)[0] for _ in range(12)]
            # zero hung, zero failed: every arrival re-routes inside
            # its own request (budget-granted) or routes clean
            assert codes == [200] * 12, codes
            assert router.health.state(stubs["d0"].url) == "open"
            assert router.domain_outages_total == 1
            assert stubs["d1"].requests >= 12
            assert router.retry_budget.stats()[
                "retries_denied_total"] == 0
            # completion-time bound: the whole recovery storm resolved
            # promptly (no hidden timeout-and-retry stalls)
            assert time.perf_counter() - t0 < 30.0
        finally:
            router.stop()
            for s in stubs.values():
                try:
                    s.stop()
                except Exception:  # noqa: BLE001 — d0's stub is already
                    # stopped by the test body; double-shutdown is fine
                    pass


# -- scale-down domain guard ----------------------------------------------


class TestScaleDownDomainGuard:
    def _order(self, preds):
        from kubeflow_tpu.serving.controller import (
            InferenceServiceController,
        )

        dep = SimpleNamespace(router=None)
        rev = SimpleNamespace(predictors=preds)
        InferenceServiceController._order_scale_down_victim(
            None, dep, rev)
        return rev.predictors

    @staticmethod
    def _pred(url, domain):
        return SimpleNamespace(url=url, domain=domain, engines=None)

    def test_never_empties_a_domain_while_another_holds_two(self):
        preds = [self._pred("u1", "a"), self._pred("u2", "a"),
                 self._pred("u3", "b")]
        ordered = self._order(list(preds))
        # u3 is b's LAST replica while a holds 2: the victim (tail)
        # must come from a
        assert ordered[-1].domain == "a"

    def test_thin_spread_allows_emptying(self):
        # one replica per domain: the spread is as thin as it can be,
        # any victim is allowed (scale-down must still make progress)
        preds = [self._pred("u1", "a"), self._pred("u2", "b")]
        ordered = self._order(list(preds))
        assert len(ordered) == 2

    def test_unset_domains_guard_is_noop(self):
        preds = [self._pred("u1", ""), self._pred("u2", ""),
                 self._pred("u3", "")]
        ordered = self._order(list(preds))
        # all candidates allowed; zero heat everywhere -> the stable
        # min picks the first, exactly the pre-PR ordering
        assert ordered[-1].url == "u1"


# -- emergency autoscale --------------------------------------------------


class TestEmergencyDecide:
    def _policy(self, **kw):
        from kubeflow_tpu.serving.autoscale import AutoscalePolicy

        return AutoscalePolicy(**kw)

    def _sig(self, **kw):
        # util 0.8 sits inside the [0.5, 1.25) hysteresis band so the
        # only live question is the emergency rule
        base = {"replicas": 2, "min_replicas": 1, "max_replicas": 4,
                "util": 0.8}
        base.update(kw)
        return base

    def test_surge_fires_above_threshold(self):
        from kubeflow_tpu.serving.autoscale import decide

        dec = decide(self._sig(unhealthy_frac=0.6), self._policy())
        assert dec.action == "scale_up"
        assert dec.reason.startswith("emergency")
        assert dec.replicas == 3

    def test_surge_bounded_by_max_replicas(self):
        from kubeflow_tpu.serving.autoscale import decide

        dec = decide(self._sig(unhealthy_frac=1.0),
                     self._policy(emergency_surge=10))
        assert dec.action == "scale_up"
        assert dec.replicas == 4
        # already at max: nothing to surge into
        dec = decide(self._sig(replicas=4, unhealthy_frac=1.0),
                     self._policy())
        assert dec.action == "none"

    def test_below_threshold_and_absent_signal_are_inert(self):
        from kubeflow_tpu.serving.autoscale import decide

        assert decide(self._sig(unhealthy_frac=0.5),
                      self._policy()).action == "none"
        # absent signal: bit-identical to the pre-PR decision table
        assert decide(self._sig(), self._policy()).action == "none"

    @pytest.mark.parametrize("bad,needle", [
        ({"emergency_unhealthy_frac": 0.0}, "emergency_unhealthy_frac"),
        ({"emergency_unhealthy_frac": 1.5}, "emergency_unhealthy_frac"),
        ({"emergency_surge": 0}, "emergency_surge"),
        ({"emergency_surge": True}, "emergency_surge"),
        ({"emergency_window_s": -1}, "emergency_window_s"),
        ({"thaw_concurrency": -1}, "thaw_concurrency"),
        ({"thaw_concurrency": True}, "thaw_concurrency"),
    ])
    def test_bad_knobs_rejected_at_validation(self, bad, needle):
        from kubeflow_tpu.serving.autoscale import validate_autoscale

        with pytest.raises(ValueError, match=needle):
            validate_autoscale(bad)


class TestEmergencyTick:
    def _scaler(self, fired, *, fail=False, **pol):
        from kubeflow_tpu.serving.autoscale import (
            AutoscalePolicy,
            ClusterAutoscaler,
        )

        pol.setdefault("up_cooldown_s", 100.0)
        pol.setdefault("emergency_window_s", 50.0)
        sig = {"replicas": 2, "min_replicas": 1, "max_replicas": 8,
               "util": 0.8, "unhealthy_frac": 0.75}

        def act(dec):
            if fail:
                raise RuntimeError("actuator down")
            fired.append(dec)

        return ClusterAutoscaler(
            AutoscalePolicy(**pol), sensors=lambda: dict(sig),
            actuators={"replica_up": act})

    def test_bypass_jumps_cooldown_once_per_window(self):
        fired = []
        sc = self._scaler(fired)
        dec = sc.tick(now=0.0)
        assert dec.action == "scale_up"      # cold channel: no bypass
        assert sc.emergency_bypass_total == 0
        dec = sc.tick(now=1.0)               # inside the 100s cooldown
        assert dec.action == "scale_up"      # ...but the bypass fires
        assert sc.emergency_bypass_total == 1
        dec = sc.tick(now=2.0)               # inside the 50s window:
        assert dec.action == "none"          # gated, no second bypass
        assert "cooldown" in dec.reason
        assert sc.emergency_bypass_total == 1
        dec = sc.tick(now=60.0)              # window elapsed
        assert dec.action == "scale_up"
        assert sc.emergency_bypass_total == 2
        assert len(fired) == 3

    def test_bypass_never_jumps_a_parked_channel(self):
        fired = []
        sc = self._scaler(fired, fail=True, max_retries=1)
        sc.tick(now=0.0)                     # fails -> parked
        assert sc.states["replica_up"].parked
        dec = sc.tick(now=200.0)             # emergency still on
        assert dec.action == "none"
        assert "parked" in dec.reason
        assert sc.emergency_bypass_total == 0
        assert fired == []

    def test_emergency_bypass_total_in_stats(self):
        sc = self._scaler([])
        assert "autoscale_emergency_bypass_total" in sc.stats()


class TestConcurrencyGate:
    def test_limit_and_wait_counters(self):
        from kubeflow_tpu.serving.autoscale import ConcurrencyGate

        gate = ConcurrencyGate(1)
        inside = threading.Event()
        release = threading.Event()
        done = threading.Event()

        def holder():
            with gate:
                inside.set()
                release.wait(30)

        def waiter():
            with gate:
                done.set()

        t1 = threading.Thread(target=holder, daemon=True)
        t1.start()
        assert inside.wait(10)
        t2 = threading.Thread(target=waiter, daemon=True)
        t2.start()
        time.sleep(0.05)
        assert not done.is_set()             # serialized behind t1
        release.set()
        assert done.wait(10)
        t1.join(10)
        t2.join(10)
        st = gate.stats()
        assert st["gate_limit"] == 1
        assert st["gate_entries_total"] == 2
        assert st["gate_waits_total"] == 1


# -- chaos: the seeded domain-outage fault --------------------------------


class TestDomainOutageFault:
    def test_seeded_victim_and_offset_are_frozen(self):
        from kubeflow_tpu.chaos import FaultPlan

        a = FaultPlan(seed=7).domain_outage(["d0", "d1", "d2"])
        b = FaultPlan(seed=7).domain_outage(["d0", "d1", "d2"])
        assert a.faults[0].node == b.faults[0].node
        assert a.faults[0].at == b.faults[0].at
        # a different seed is free to choose differently — across a
        # small sweep at least one choice must differ (deflake guard:
        # the victim is seeded, not constant)
        picks = {FaultPlan(seed=s).domain_outage(
            ["d0", "d1", "d2"]).faults[0].node for s in range(16)}
        assert len(picks) > 1

    def test_fires_exactly_once(self):
        from kubeflow_tpu.chaos import FaultPlan

        plan = FaultPlan(seed=3).domain_outage(["d0", "d1"], at=0.0)
        plan.activate()
        first = plan.due_domain_outages()
        assert first in (["d0"], ["d1"])
        assert plan.due_domain_outages() == []

    def test_empty_domains_rejected(self):
        from kubeflow_tpu.chaos import FaultPlan

        with pytest.raises(ValueError):
            FaultPlan(seed=1).domain_outage([])


# -- conf-freeze (satellite) ----------------------------------------------


class TestConfFreezeDomains:
    def test_bad_domains_knobs_are_one_failed_status(self):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        cases = {
            "bad-domains-list": {"domains": ["zone-a"]},
            "bad-domains-empty": {"domains": {}},
            "bad-domains-weight": {"domains": {"zone-a": 0}},
            "bad-domains-bool": {"domains": {"zone-a": True}},
        }
        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            for name, cfg in cases.items():
                cluster.store.create(InferenceService(
                    metadata=ObjectMeta(name=name),
                    spec=InferenceServiceSpec(predictor=ComponentSpec(
                        model_format=ModelFormat(name="llama-continuous"),
                        config={"params_ref": "mem://never-fetched",
                                **cfg}))))
            for name in cases:
                deadline = time.time() + 20
                isvc = None
                while time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    (name, isvc.status)
                assert "domains" in (isvc.status.message or ""), \
                    (name, isvc.status.message)


# -- mass recovery: thaw on a survivor ------------------------------------


class TestMassRecoveryThaw:
    def test_survivor_thaws_exactly_once(self, tiny_llama, tmp_path):
        """The dead domain's hibernated session thaws on a survivor
        sharing the store root — exactly once: the spill entry is
        consumed, a second thaw is a hard error, zero recompiles and a
        clean ledger on the survivor."""
        store = KvSpillStore(str(tmp_path))
        a = make_engine(tiny_llama)
        a.attach_spill_store(store)
        req = a.submit(LONG, max_new_tokens=120)
        deadline = time.time() + 120
        while len(req.tokens) < 8:
            assert time.time() < deadline
            time.sleep(0.01)
        assert a.hibernate_sequence(req, "conv-dead-domain")
        a.stop()   # the whole domain dies
        del a

        b = make_engine(tiny_llama)
        try:
            b.attach_spill_store(store)
            assert store.contains("conv-dead-domain")
            t0 = time.perf_counter()
            req2, info = b.thaw_sequence("conv-dead-domain")
            out = req2.wait(120)
            assert len(out) == 120
            assert not info["degraded"]
            # exactly-once: consumed on success, a replay cannot thaw
            # the same session twice
            assert not store.contains("conv-dead-domain")
            with pytest.raises(SpillCorrupt):
                b.thaw_sequence("conv-dead-domain")
            st = b.stats()
            assert st["jit_recompiles_total"] == 0
            assert st["kv_thaws_total"] == 1
            # completion-time bound: a thaw is a resume, not a retrain
            assert time.perf_counter() - t0 < 120.0
            assert_no_leaks(b)
        finally:
            b.stop()

    def test_thaw_gate_serializes_the_herd(self, tiny_llama, tmp_path):
        """Two sessions thaw concurrently through a limit-1 gate: both
        complete, and the gate saw one wait — the herd was serialized,
        not refused."""
        from kubeflow_tpu.serving.autoscale import ConcurrencyGate

        store = KvSpillStore(str(tmp_path))
        eng = make_engine(tiny_llama)
        try:
            eng.attach_spill_store(store)
            for sid in ("h-1", "h-2"):
                req = eng.submit(LONG, max_new_tokens=120)
                deadline = time.time() + 120
                while len(req.tokens) < 6:
                    assert time.time() < deadline
                    time.sleep(0.01)
                assert eng.hibernate_sequence(req, sid)
            eng.thaw_gate = ConcurrencyGate(1)
            results = {}

            def thaw(sid):
                req2, _info = eng.thaw_sequence(sid)
                results[sid] = req2.wait(120)

            threads = [threading.Thread(target=thaw, args=(sid,),
                                        daemon=True)
                       for sid in ("h-1", "h-2")]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "a gated thaw hung"
            assert len(results["h-1"]) == 120
            assert len(results["h-2"]) == 120
            st = eng.thaw_gate.stats()
            assert st["gate_entries_total"] == 2
            assert eng.stats()["jit_recompiles_total"] == 0
            assert_no_leaks(eng)
        finally:
            eng.stop()


# -- the full storm (slow) ------------------------------------------------


@pytest.mark.slow
class TestDomainOutageMidStorm:
    def test_seeded_domain_kill_reroutes_and_recovers(self, tiny_llama):
        """Heavy variant: real model replicas in two failure domains, a
        seeded ``domain_outage`` kills one domain whole mid-storm —
        zero hung requests, successes keep completing on the survivor,
        the router declares the outage, amplification stays inside the
        budget, and the survivor never recompiles."""
        from kubeflow_tpu.chaos import FaultPlan
        from kubeflow_tpu.serving.controller import Router
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        ref = register_mem("failure-domain-storm", tiny_llama)

        def server():
            srv = ModelServer()
            srv.register(TextGenerator("m", dict(
                params_ref=ref, tokenizer="bytes", num_slots=4,
                decode_chunk=2, block_size=16, prefix_cache=False,
                max_new_tokens=8, warmup_groups=[])))
            srv.start()
            return srv

        servers = {"d0": server(), "d1": server()}
        for s in servers.values():
            code, _, _ = post(s.url + "/openai/v1/completions",
                              {"model": "m", "prompt": "warm",
                               "max_tokens": 2}, timeout=120)
            assert code == 200
        router = Router(activate=lambda: None)
        router.set_backends([s.url for s in servers.values()])
        router.set_traffic(TrafficPlane(
            {"default": {"max_concurrent": 2, "queue_depth": 8}}))
        router.set_domains({servers[d].url: d for d in servers})
        plan = FaultPlan(seed=41).domain_outage(["d0", "d1"], at=0.0)
        results = []
        lock = threading.Lock()
        killed = []
        try:
            plan.activate()
            threads = []
            kill_t = [None]

            def one(i):
                code, _, _ = post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": f"storm {i}",
                     "max_tokens": 4}, timeout=120)
                with lock:
                    results.append((i, code, time.perf_counter()))

            for i in range(16):
                if i == 6:
                    for d in plan.due_domain_outages():
                        servers[d].stop()  # the whole domain, at once
                        killed.append(d)
                    kill_t[0] = time.perf_counter()
                th = threading.Thread(target=one, args=(i,), daemon=True)
                th.start()
                threads.append(th)
                time.sleep(0.05)
            hung = 0
            for th in threads:
                th.join(timeout=120)
                hung += int(th.is_alive())
            assert hung == 0, "a request hung through the domain kill"
            assert len(killed) == 1  # the seeded victim fired once
            assert len(results) == 16
            codes = [c for _, c, _ in results]
            assert all(c in (0, 200, 429, 500, 502, 503)
                       for c in codes), results
            assert sum(1 for _, c, t in results
                       if c == 200 and t > kill_t[0]) >= 2, results
            survivor = servers[{"d0": "d1", "d1": "d0"}[killed[0]]]
            assert router.backend_stats()[survivor.url]["requests"] >= 4
            # amplification bound: forwarded attempts stay inside
            # 1 + ratio of the client storm (the budget contract)
            rb = router.retry_budget.stats()
            amp = (16 + rb["retries_granted_total"]) / 16
            assert amp <= 1 + router.retry_budget.ratio \
                + router.retry_budget.burst / 16
            # the survivor took the storm without a single recompile
            with urllib.request.urlopen(
                    survivor.url + "/metrics", timeout=30) as r:
                text = r.read().decode()
            assert ('kft_engine_jit_recompiles_total{model="m"} 0'
                    in text)
        finally:
            router.stop()
            for d, s in servers.items():
                if d not in killed:
                    s.stop()
