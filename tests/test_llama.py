"""Llama model family: correctness of forward, sharding, GQA, decode cache.

Every sharded case runs on the 8-virtual-device CPU mesh (conftest), the
same SPMD path XLA lowers on a real slice (SURVEY.md §4 implication (c)).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import sharding as shardlib


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = llama.tiny()
    model = llama.Llama(cfg)
    toks = jnp.ones((4, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, toks, params


def test_forward_shape_and_determinism(tiny_setup):
    cfg, model, toks, params = tiny_setup
    logits = model.apply(params, toks)
    assert logits.shape == (4, 32, cfg.vocab_size)
    again = model.apply(params, toks)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(again))


def test_scan_matches_unrolled(tiny_setup):
    """Same weights through scan and unrolled stacks -> same logits."""
    cfg, model, toks, params = tiny_setup
    unrolled = llama.Llama(llama.tiny(scan_layers=False))
    # unstack the scanned layer params [L, ...] into per-layer subtrees
    scanned = params["params"]
    uparams = {k: v for k, v in scanned.items() if k != "layers"}
    per_layer = scanned["layers"]["block"]
    for i in range(cfg.num_layers):
        uparams[f"layer_{i}"] = jax.tree.map(lambda a, i=i: a[i], per_layer)
    out_scan = model.apply(params, toks)
    out_unrolled = unrolled.apply({"params": uparams}, toks)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_unrolled), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize(
    "axes",
    [{"data": 8}, {"fsdp": 8}, {"data": 2, "fsdp": 2, "model": 2}, {"data": 4, "model": 2}],
)
def test_sharded_forward_matches_single_device(tiny_setup, axes):
    cfg, model, _, params = tiny_setup
    toks = jnp.ones((8, 32), jnp.int32)  # batch divisible by any batch-axis mix
    expected = np.asarray(model.apply(params, toks))
    mesh = meshlib.build_mesh(axes)
    shardings = shardlib.param_shardings(params, mesh)
    p = jax.device_put(params, shardings)
    t = jax.device_put(toks, meshlib.batch_sharding(mesh))
    with shardlib.shard_context(mesh):
        out = jax.jit(model.apply)(p, t)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-4, rtol=2e-4)


def test_activation_constraints_reach_hlo(tiny_setup):
    """shard_context must make nn.with_logical_constraint emit real HLO
    shardings — without it flax silently drops them (a caught regression)."""
    cfg, model, _, params = tiny_setup
    toks = jnp.ones((8, 32), jnp.int32)
    mesh = meshlib.build_mesh({"data": 2, "fsdp": 2, "model": 2})
    with shardlib.shard_context(mesh):
        txt = jax.jit(model.apply).lower(params, toks).as_text()
    assert txt.count("sharding") > 0


def test_ring_attention_model_matches_dense(tiny_setup):
    cfg, model, toks, params = tiny_setup
    expected = np.asarray(model.apply(params, toks))
    ring_model = llama.Llama(llama.tiny(attention_impl="ring"))
    mesh = meshlib.build_mesh({"data": 2, "seq": 4})
    shardings = shardlib.param_shardings(params, mesh)
    p = jax.device_put(params, shardings)
    with shardlib.shard_context(mesh):
        out = jax.jit(ring_model.apply)(p, toks)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-4, rtol=2e-4)


def test_param_count_formula(tiny_setup):
    cfg, model, toks, params = tiny_setup
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == llama.num_params(cfg)


def test_presets_construct():
    for name, fn in llama.PRESETS.items():
        cfg = fn()
        assert cfg.num_heads % cfg.num_kv_heads == 0, name
    assert llama.num_params(llama.llama2_7b()) == pytest.approx(6.7e9, rel=0.03)


def test_unrolled_remat_builds():
    """remat=True + scan_layers=False must compile (caught regression:
    static_argnums pointed at a keyword-only arg and crashed)."""
    model = llama.Llama(llama.tiny(remat=True, scan_layers=False))
    toks = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    out = jax.jit(model.apply)(params, toks)
    assert out.shape == (2, 16, 256)


def test_chunked_prefill_matches_full_forward(tiny_setup):
    """Multi-token decode chunks must mask per-query (caught regression:
    mask used the pre-update cursor for the whole chunk)."""
    cfg, model, toks, params = tiny_setup
    full = np.asarray(model.apply(params, toks))
    b, s = toks.shape
    chunk = 8
    cache = None
    outs = []
    for start in range(0, s, chunk):
        tok = toks[:, start : start + chunk]
        pos = jnp.arange(start, start + chunk)[None, :].repeat(b, 0)
        vars_in = {**params, **({"cache": cache} if cache else {})}
        logits, mutated = model.apply(
            vars_in, tok, pos, decode=True, mutable=["cache"])
        cache = mutated["cache"]
        outs.append(np.asarray(logits))
    decoded = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(decoded, full, atol=2e-3, rtol=2e-3)


def test_decode_cache_matches_full_forward(tiny_setup):
    cfg, model, toks, params = tiny_setup
    full = np.asarray(model.apply(params, toks))  # [b, s, v]
    # prime the cache token by token
    b, s = toks.shape
    cache = None
    outs = []
    variables = dict(params)
    for t in range(s):
        tok = toks[:, t : t + 1]
        pos = jnp.full((b, 1), t, jnp.int32)
        vars_in = {**params, **({"cache": cache} if cache else {})}
        logits, mutated = model.apply(
            vars_in, tok, pos, decode=True, mutable=["cache"])
        cache = mutated["cache"]
        outs.append(np.asarray(logits[:, 0]))
    decoded = np.stack(outs, axis=1)
    np.testing.assert_allclose(decoded, full, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("axes", [None, {"data": 4, "model": 2}])
def test_one_hot_embed_matches_gather(tiny_setup, axes):
    """embed_one_hot (the heavy-TP lookup) computes the identical forward —
    on one device and compiled under the sharded mesh it exists for.
    Varied token ids (incl. one out-of-bounds, which both paths clamp)."""
    cfg, model, _, params = tiny_setup
    rng = np.random.default_rng(0)
    toks_np = rng.integers(0, cfg.vocab_size, size=(8, 32)).astype(np.int32)
    toks_np[0, 0] = cfg.vocab_size + 7  # OOB: clamped identically by both
    toks = jnp.asarray(toks_np)
    oh_model = llama.Llama(llama.tiny(embed_one_hot=True))
    a = np.asarray(model.apply(params, toks))
    if axes is None:
        b = np.asarray(oh_model.apply(params, toks))
    else:
        mesh = meshlib.build_mesh(axes)
        p = jax.device_put(params, shardlib.param_shardings(params, mesh))
        t = jax.device_put(toks, meshlib.batch_sharding(mesh))
        with shardlib.shard_context(mesh):
            b = np.asarray(jax.jit(oh_model.apply)(p, t))
    np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)
