"""MoE serving (SURVEY §2.2 Mixtral-class backend capability, §2.5 EP row
— r4 verdict missing #4).

The engine is generic over LlamaConfig, so a MoE model serves through
the SAME slot-pool programs; these tests pin the semantics:

- decode is DROPLESS by construction (one token per step can never
  exceed expert capacity), so the exact reference is the dropless
  (ragged) full forward.  Capacity-factor dispatch is a train-time
  batching artifact: a capacity-cfg PREFILL can drop assignments under
  routing skew, which is why serving should publish/serve MoE snapshots
  with ``moe_dispatch="ragged"`` (asserted equivalent here).
- the train->publish->serve loop closes: ``save_pretrained`` keeps the
  moe fields, and ``ContinuousLlamaGenerator`` serves the snapshot.
- an EP x TP serving mesh shards expert weights on ``expert`` and
  kv/mlp dims on ``model`` with token parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]


def _moe(**kw):
    cfg = llamalib.tiny(moe_experts=4, moe_top_k=2,
                        moe_dispatch="ragged", **kw)
    params = nn.meta.unbox(llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    return cfg, params


def _full_forward_greedy(cfg, params, prompt, n):
    """Independent reference: no KV cache, no engine — re-forward the
    whole sequence per token and take the argmax."""
    model = llamalib.Llama(cfg)
    toks = list(prompt)
    for _ in range(n):
        logits = model.apply(
            {"params": params}, jnp.asarray([toks], jnp.int32))
        toks.append(int(np.argmax(np.asarray(logits[0, -1], np.float32))))
    return toks[len(prompt):]


class TestMoeDecodeParity:
    def test_engine_matches_dropless_full_forward(self):
        cfg, params = _moe()
        want = [_full_forward_greedy(cfg, params, p, 5) for p in PROMPTS]
        eng = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                               eos_id=None)
        try:
            got = [eng.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            eng.stop()
        assert got == want

    def test_capacity_decode_equals_dropless_decode(self):
        """At decode shapes nothing can exceed capacity, so the dense
        (capacity) dispatch and ragged dispatch decode identically —
        the divergence lives only in full-sequence (train) forwards."""
        cfg, params = _moe()
        import dataclasses

        dense_cfg = dataclasses.replace(cfg, moe_dispatch="dense")
        outs = []
        for c in (cfg, dense_cfg):
            eng = ContinuousEngine(c, params, num_slots=4, decode_chunk=2,
                                   eos_id=None)
            try:
                outs.append(
                    [eng.generate(p, max_new_tokens=5) for p in PROMPTS])
            finally:
                eng.stop()
        assert outs[0] == outs[1]

    def test_ep_tp_mesh_parity_and_shardings(self):
        cfg, params = _moe()
        single = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                                  eos_id=None)
        try:
            want = [single.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            single.stop()
        eng = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=2, eos_id=None,
            mesh_axes={"expert": 2, "model": 2})
        try:
            wg = eng.params["layers"]["block"]["mlp"]["w_gate"]
            # stacked [L, e, h, m]: experts split over 'expert', mlp dim
            # over 'model'
            assert wg.sharding.spec[1] == "expert"
            assert wg.sharding.spec[-1] == "model"
            assert len(wg.sharding.device_set) == 4
            got = [eng.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            eng.stop()
        assert got == want

    def test_int8_weights_rejected_for_moe(self):
        cfg, params = _moe()
        with pytest.raises(ValueError, match="MoE"):
            llamalib.quantize_for_serving(cfg, params)
        # KV-only int8 composes with MoE
        qcfg, qp = llamalib.quantize_for_serving(
            cfg, params, weights=False, kv=True)
        eng = ContinuousEngine(qcfg, qp, num_slots=2, decode_chunk=2,
                               eos_id=None)
        try:
            out = eng.generate([1, 2, 3], max_new_tokens=3)
        finally:
            eng.stop()
        assert len(out) == 3


class TestMoePublishServe:
    def test_train_publish_serve_loop(self, tmp_path):
        """The loop the r4 verdict called out as stopping at publish:
        an MoE snapshot published by save_pretrained serves through
        ContinuousLlamaGenerator with exact parity."""
        from kubeflow_tpu.serving.continuous import ContinuousLlamaGenerator

        cfg, params = _moe()
        snap = str(tmp_path / "moe_snap")
        llamalib.save_pretrained(snap, cfg, params)
        cfg2 = llamalib.load_pretrained_config(snap)
        assert cfg2.moe_experts == 4 and cfg2.moe_dispatch == "ragged"
        want = [_full_forward_greedy(cfg, params, p, 4) for p in PROMPTS]
        gen = ContinuousLlamaGenerator("moe", {
            "storage_path": snap, "num_slots": 4, "decode_chunk": 2,
            "max_new_tokens": 4, "warmup_groups": []})
        gen.start()
        try:
            got = gen.predict_batch(PROMPTS)
        finally:
            gen.stop()
        assert got == want
