"""E2E: JaxJob YAML -> gang admission -> real processes -> jax.distributed
rendezvous -> DP training -> Succeeded (SURVEY.md §7 phase 3's minimum
end-to-end slice; the kind-cluster tier of the reference test pyramid).

These spawn real subprocesses doing real multi-process JAX on the CPU
backend — the same XLA code path as a multi-host TPU slice.
"""

import time

import pytest

from kubeflow_tpu.api.common import JobConditionType, has_condition
from kubeflow_tpu.runtime.platform import LocalPlatform
from kubeflow_tpu.sdk import TrainingClient
from kubeflow_tpu.utils.net import free_port


@pytest.fixture()
def platform(tmp_path):
    p = LocalPlatform(num_hosts=4, chips_per_host=4, root_dir=str(tmp_path))
    with p:
        yield p


@pytest.mark.e2e
class TestLocalE2E:
    def test_single_worker_smoke(self, platform):
        """Baseline config 1: single-worker MNIST-class smoke run."""
        client = TrainingClient(platform)
        job = client.train(
            name="mnist-smoke",
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=1,
            env={"KFT_STEPS": "5", "KFT_BATCH": "16"},
            timeout=120,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("mnist-smoke")
        assert "loss=" in logs["mnist-smoke-worker-0"]

    def test_two_worker_distributed(self, platform):
        """Baseline config 2 analog: 2-process DDP-style data parallelism
        with a genuine jax.distributed rendezvous."""
        client = TrainingClient(platform)
        job = client.train(
            name="ddp",
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=2,
            env={"KFT_STEPS": "4", "KFT_BATCH": "16"},
            timeout=180,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        # gang-startup metric measured once every rank crossed the barrier
        assert job.status.gang_startup_seconds is not None
        assert job.status.gang_startup_seconds > 0
        logs = client.get_job_logs("ddp")
        assert len(logs) == 2

    def test_yaml_submission(self, platform):
        client = TrainingClient(platform)
        port = free_port()
        job = client.create_job(
            f"""
apiVersion: kubeflow-tpu.dev/v1
kind: JaxJob
metadata:
  name: from-yaml
spec:
  coordinatorPort: {port}
  replicaSpecs:
    worker:
      replicas: 1
      template:
        entrypoint: kubeflow_tpu.models.mnist:train_main
        env:
          KFT_STEPS: "3"
          KFT_BATCH: "8"
"""
        )
        job = client.wait_for_job_conditions("from-yaml", timeout=120)
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)

    def test_failing_entrypoint_fails_job(self, platform):
        client = TrainingClient(platform)
        with pytest.raises(RuntimeError, match="failed"):
            client.train(
                name="will-fail",
                entrypoint="kubeflow_tpu.models.mnist:not_a_function",
                num_workers=1,
                timeout=120,
            )
