"""E2E: JaxJob YAML -> gang admission -> real processes -> jax.distributed
rendezvous -> DP training -> Succeeded (SURVEY.md §7 phase 3's minimum
end-to-end slice; the kind-cluster tier of the reference test pyramid).

These spawn real subprocesses doing real multi-process JAX on the CPU
backend — the same XLA code path as a multi-host TPU slice.
"""

import os
import re
import signal
import time

import pytest

from kubeflow_tpu.api.common import JobConditionType, has_condition
from kubeflow_tpu.runtime.platform import LocalPlatform
from kubeflow_tpu.sdk import TrainingClient
from kubeflow_tpu.utils.net import free_port


@pytest.fixture()
def platform(tmp_path):
    p = LocalPlatform(num_hosts=4, chips_per_host=4, root_dir=str(tmp_path))
    with p:
        yield p


@pytest.mark.e2e
class TestLocalE2E:
    def test_single_worker_smoke(self, platform):
        """Baseline config 1: single-worker MNIST-class smoke run."""
        client = TrainingClient(platform)
        job = client.train(
            name="mnist-smoke",
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=1,
            env={"KFT_STEPS": "5", "KFT_BATCH": "16"},
            timeout=120,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("mnist-smoke")
        assert "loss=" in logs["mnist-smoke-worker-0"]

    def test_two_worker_resnet_ddp(self, platform):
        """Baseline config 2 literal: data-parallel ResNet, 2 replicas,
        real multi-process rendezvous (XLA psum standing in for NCCL)."""
        client = TrainingClient(platform)
        job = client.train(
            name="resnet-ddp",
            entrypoint="kubeflow_tpu.models.resnet:train_main",
            num_workers=2,
            env={"KFT_STEPS": "3", "KFT_BATCH": "8", "KFT_RESNET": "tiny"},
            timeout=180,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("resnet-ddp")
        # both ranks computed the same global model: identical loss lines
        lines = {
            name: [l for l in text.splitlines() if l.startswith("loss=")][-1]
            for name, text in logs.items()
        }
        assert len(set(lines.values())) == 1 and len(lines) == 2

    def test_two_worker_distributed(self, platform):
        """Baseline config 2 analog: 2-process DDP-style data parallelism
        with a genuine jax.distributed rendezvous."""
        client = TrainingClient(platform)
        job = client.train(
            name="ddp",
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=2,
            env={"KFT_STEPS": "4", "KFT_BATCH": "16"},
            timeout=180,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        # gang-startup metric measured once every rank crossed the barrier
        assert job.status.gang_startup_seconds is not None
        assert job.status.gang_startup_seconds > 0
        logs = client.get_job_logs("ddp")
        assert len(logs) == 2

    def test_yaml_submission(self, platform):
        client = TrainingClient(platform)
        port = free_port()
        job = client.create_job(
            f"""
apiVersion: kubeflow-tpu.dev/v1
kind: JaxJob
metadata:
  name: from-yaml
spec:
  coordinatorPort: {port}
  replicaSpecs:
    worker:
      replicas: 1
      template:
        entrypoint: kubeflow_tpu.models.mnist:train_main
        env:
          KFT_STEPS: "3"
          KFT_BATCH: "8"
"""
        )
        job = client.wait_for_job_conditions("from-yaml", timeout=120)
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)

    def test_failing_entrypoint_fails_job(self, platform):
        client = TrainingClient(platform)
        with pytest.raises(RuntimeError, match="failed"):
            client.train(
                name="will-fail",
                entrypoint="kubeflow_tpu.models.mnist:not_a_function",
                num_workers=1,
                timeout=120,
            )

    def test_kill_live_worker_gang_restart_resume(self, platform, tmp_path):
        """SURVEY §5 fault injection: SIGKILL a healthy worker mid-train.

        Expects the full recovery chain: kubelet reports 137 (retryable) ->
        controller gang-restarts (RESTARTING condition, ALL pods recreated,
        restart_count bumped; the surviving worker's SIGTERM triggers
        save-on-preemption) -> new gang resumes from the checkpoint
        (resume_step > 0) -> Succeeded with the full step count reached.
        """
        from kubeflow_tpu.controlplane import events_for

        ckpt_dir = str(tmp_path / "fault-ckpt")
        client = TrainingClient(platform)
        client.train(
            name="fault",
            entrypoint="kubeflow_tpu.train.llm:train_main",
            num_workers=2,
            env={
                "KFT_STEPS": "40",
                "KFT_BATCH": "8",
                "KFT_SEQ_LEN": "16",
                "KFT_CKPT_DIR": ckpt_dir,
                "KFT_SAVE_EVERY": "2",
                "KFT_LOG_EVERY": "2",
            },
            backoff_limit=2,
            wait=False,
        )
        # wait until training is genuinely under way: a checkpoint exists
        deadline = time.time() + 120
        while time.time() < deadline:
            steps = [d for d in (os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else [])
                     if d.isdigit()]
            if steps:
                break
            time.sleep(0.2)
        assert steps, "no checkpoint appeared before the kill"

        pod = platform.store.get("Pod", "fault-worker-1")
        assert pod.status.pid, pod.status
        os.kill(pod.status.pid, signal.SIGKILL)

        job = client.wait_for_job_conditions("fault", timeout=300)
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        assert job.status.restart_count >= 1
        reasons = [e.reason for e in events_for(platform.store, "JaxJob", "fault")]
        assert "Restarting" in reasons
        # step continuity: the restarted gang resumed from a checkpoint,
        # not step 0, and still reached the configured 40 steps
        log = client.get_job_logs("fault")["fault-worker-0"]
        resumes = [int(m) for m in re.findall(r"resume_step=(\d+)", log)]
        assert len(resumes) >= 2 and resumes[0] == 0 and max(resumes) > 0, resumes
        final_steps = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
        assert max(int(s) for s in final_steps) == 40

    def test_elastic_resize_resumes_from_checkpoint(self, platform, tmp_path):
        """SURVEY §2.5 elastic row: change replicas on a LIVE job.

        4 workers -> 2: controller detects the stale world size, re-gangs
        (Resizing event; deleted workers save-on-preemption), recomputes the
        default mesh for the new size, and the 2-worker gang reshape-restores
        the checkpoint and finishes all steps.  backoff_limit=0 proves the
        resize does not consume the failure budget.
        """
        from kubeflow_tpu.controlplane import events_for

        ckpt_dir = str(tmp_path / "resize-ckpt")
        client = TrainingClient(platform)
        client.train(
            name="elastic",
            entrypoint="kubeflow_tpu.train.llm:train_main",
            num_workers=4,
            env={
                "KFT_STEPS": "40",
                "KFT_BATCH": "8",
                "KFT_SEQ_LEN": "16",
                "KFT_CKPT_DIR": ckpt_dir,
                "KFT_SAVE_EVERY": "2",
                "KFT_LOG_EVERY": "2",
            },
            backoff_limit=0,
            wait=False,
        )
        deadline = time.time() + 180
        steps = []
        while time.time() < deadline:
            steps = [d for d in (os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else [])
                     if d.isdigit()]
            if steps:
                break
            time.sleep(0.2)
        assert steps, "no checkpoint appeared before the resize"

        platform.store.update_with_retry(
            "JaxJob", "elastic", "default",
            lambda o: setattr(o.spec.replica_specs["worker"], "replicas", 2),
        )

        job = client.wait_for_job_conditions("elastic", timeout=300)
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        reasons = [e.reason for e in events_for(platform.store, "JaxJob", "elastic")]
        assert "Resizing" in reasons
        # the resized gang resumed from checkpoint on the smaller mesh
        log = client.get_job_logs("elastic")["elastic-worker-0"]
        resumes = [int(m) for m in re.findall(r"resume_step=(\d+)", log)]
        assert len(resumes) >= 2 and resumes[0] == 0 and max(resumes) > 0, resumes
        final = platform.store.get("JaxJob", "elastic")
        assert final.spec.replica_specs["worker"].replicas == 2
        assert final.spec.mesh == {"data": 2}
        final_steps = [d for d in os.listdir(ckpt_dir) if d.isdigit()]
        assert max(int(s) for s in final_steps) == 40

    def test_pbt_fork_resumes_parent_checkpoint(self, platform, tmp_path):
        """PBT contract in the real trainer: a fork starts at the parent's
        step and KFT_STEPS means 'this many MORE steps'."""
        client = TrainingClient(platform)
        root = str(tmp_path / "pbt")
        common = {"KFT_PBT_ROOT": root, "KFT_BATCH": "8",
                  "KFT_SEQ_LEN": "32", "KFT_STEPS": "4",
                  "KFT_SAVE_EVERY": "2", "KFT_LOG_EVERY": "2"}
        client.train(name="pbt-a", entrypoint="kubeflow_tpu.train.llm:train_main",
                     num_workers=1, env=dict(common), timeout=240)
        client.train(name="pbt-b", entrypoint="kubeflow_tpu.train.llm:train_main",
                     num_workers=1,
                     env={**common, "KFT_RESUME_FROM": "pbt-a"}, timeout=240)
        logs = client.get_job_logs("pbt-b")["pbt-b-worker-0"]
        resume = [l for l in logs.splitlines() if l.startswith("resume_step=")]
        assert resume and float(resume[0].split("=")[1]) == 4.0
        # fork baseline marker survives and the horizon extended to 8
        import os
        assert open(os.path.join(root, "pbt-b", "pbt_base_step")).read() == "4"
        steps = [l for l in logs.splitlines() if l.startswith("loss=")]
        assert steps  # trained past the fork


@pytest.mark.e2e
class TestCompileCache:
    def test_compile_cache_populated(self, platform, tmp_path):
        """KFT_COMPILE_CACHE wires jax's persistent compilation cache into
        the pod runtime (warm gang restarts, BASELINE metric #2): after a
        job runs with it, the cache dir holds compiled entries."""
        cache = tmp_path / "xla-cache"
        client = TrainingClient(platform)
        job = client.train(
            name="cachejob",
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=1,
            env={"KFT_STEPS": "2", "KFT_BATCH": "8",
                 "KFT_COMPILE_CACHE": str(cache)},
            timeout=120,
        )
        assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
        entries = list(cache.glob("*")) if cache.exists() else []
        assert entries, "persistent compile cache stayed empty"
