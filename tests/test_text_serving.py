"""Text-in/text-out LLM serving (serving/text.py): tokenizer in the
server + OpenAI-style completions — the huggingfaceserver surface
[upstream: kserve -> python/huggingfaceserver]."""

import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.storage import register_mem
from kubeflow_tpu.serving.text import (
    ByteTokenizer,
    HfTokenizer,
    TextGenerator,
    resolve_tokenizer,
)


@pytest.fixture(scope="module")
def text_model():
    cfg = llamalib.tiny()  # vocab 256 == the byte tokenizer's range
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    ref = register_mem("text-llama", (cfg, params["params"]))
    m = TextGenerator("textgen", {
        "params_ref": ref, "max_new_tokens": 6, "decode_chunk": 2,
        "num_slots": 4, "warmup_groups": []})
    m.start()
    yield m
    m.stop()


class TestTokenizers:
    def test_byte_tokenizer_round_trips(self):
        t = ByteTokenizer()
        for s in ("hello", "héllo wörld", ""):
            assert t.decode(t.encode(s)) == s

    def test_hf_tokenizer_local(self, tmp_path):
        """AutoTokenizer from a LOCAL directory (zero-egress contract)."""
        from tokenizers import Tokenizer, models, pre_tokenizers
        from transformers import PreTrainedTokenizerFast

        vocab = {"<unk>": 0, "hello": 1, "world": 2, "tpu": 3}
        tok = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
        tok.pre_tokenizer = pre_tokenizers.Whitespace()
        PreTrainedTokenizerFast(
            tokenizer_object=tok, unk_token="<unk>"
        ).save_pretrained(str(tmp_path / "tok"))
        t = HfTokenizer(str(tmp_path / "tok"))
        ids = t.encode("hello tpu")
        assert ids == [1, 3]
        assert t.decode(ids) == "hello tpu"
        # resolve via config spec
        t2 = resolve_tokenizer({"tokenizer": {"type": "hf",
                                              "path": str(tmp_path / "tok")}})
        assert t2.encode("world") == [2]

    def test_unknown_spec_rejected(self):
        with pytest.raises(ValueError):
            resolve_tokenizer({"tokenizer": {"type": "nope"}})


class TestTextGenerator:
    def test_text_in_text_out(self, text_model):
        out = text_model.predict_batch(["hi", {"prompt": "ab", "max_tokens": 3}])
        assert len(out) == 2
        assert all(isinstance(o, str) for o in out)
        assert len(out[0].encode("utf-8", errors="replace")) >= 1
        # dict form honored its own budget (3 byte-tokens max)
        assert len(text_model.tokenizer.encode(out[1])) <= 3 or len(out[1]) <= 3

    def test_deterministic_greedy(self, text_model):
        a = text_model.predict_batch(["same prompt"])[0]
        b = text_model.predict_batch(["same prompt"])[0]
        assert a == b

    def test_openai_completions_endpoint(self, text_model):
        """The OpenAI completions contract over live HTTP."""
        from kubeflow_tpu.serving.server import ModelServer

        server = ModelServer().start()
        try:
            server.register(text_model)
            body = {"model": "textgen", "prompt": ["x", "yz"],
                    "max_tokens": 4}
            req = urllib.request.Request(
                f"{server.url}/openai/v1/completions",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())
            assert out["object"] == "text_completion"
            assert len(out["choices"]) == 2
            assert {c["index"] for c in out["choices"]} == {0, 1}
            assert all(isinstance(c["text"], str) for c in out["choices"])
            # unknown model -> 404
            bad = urllib.request.Request(
                f"{server.url}/openai/v1/completions",
                data=json.dumps({"model": "ghost", "prompt": "q"}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(bad, timeout=10)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            # the module-scoped model outlives this server: detach without
            # stopping the engine
            server._models.pop("textgen", None)
            server._specs.pop("textgen", None)
            server.stop()


class TestStreaming:
    def test_stream_completions_sse(self, text_model):
        """stream: true — SSE chunks arrive progressively and concatenate
        to exactly the non-streamed completion."""
        from kubeflow_tpu.serving.server import ModelServer

        server = ModelServer().start()
        try:
            server.register(text_model)
            ref = text_model.openai_completions(
                {"prompt": "stream me", "max_tokens": 6})
            body = {"model": "textgen", "prompt": "stream me",
                    "max_tokens": 6, "stream": True}
            req = urllib.request.Request(
                f"{server.url}/openai/v1/completions",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            chunks = []
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.headers.get("Content-Type") == "text/event-stream"
                for raw in r:
                    line = raw.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        break
                    chunks.append(json.loads(data)["choices"][0]["text"])
            assert chunks, "no streamed chunks"
            # the streaming contract: chunk concatenation == the full
            # completion (chunk COUNT is timing-dependent — a warm engine
            # can finish all decode chunks before the first poll)
            assert "".join(chunks) == ref["choices"][0]["text"]
        finally:
            server._models.pop("textgen", None)
            server._specs.pop("textgen", None)
            server.stop()


class TestTieredTextServing:
    def test_completions_over_tiered_engine(self):
        """TieredEngine must be a drop-in behind TextGenerator: the
        OpenAI completions path reads engine.eos_id/default_max_new_tokens
        (caught regression: the tiered router initially lacked them)."""
        from kubeflow_tpu.serving.continuous import TieredEngine

        cfg = llamalib.tiny()
        model = llamalib.Llama(cfg)
        params = model.init(
            jax.random.PRNGKey(1), jnp.ones((1, 8), jnp.int32))
        ref = register_mem("text-llama-tiered", (cfg, params["params"]))
        m = TextGenerator("tieredgen", {
            "params_ref": ref, "max_new_tokens": 4, "decode_chunk": 2,
            "num_slots": 4, "short_pool_len": 32, "warmup_groups": []})
        m.start()
        try:
            assert isinstance(m.engine, TieredEngine)
            out = m.openai_completions(
                {"prompt": "hi", "max_tokens": 4})
            assert out["choices"][0]["text"] is not None
            assert out["usage"]["completion_tokens"] >= 1
        finally:
            m.stop()


class TestOpenAiStopAndN:
    def _model(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        ref = register_mem("stopllama", (cfg, params))
        m = TextGenerator("t", {"params_ref": ref, "max_new_tokens": 6,
                                "warmup_groups": []})
        m.start()
        return m

    def test_stop_sequence_truncates(self):
        m = self._model()
        try:
            base = m.openai_completions({"prompt": "ab", "max_tokens": 6})
            text = base["choices"][0]["text"]
            assert len(text) >= 2
            stop_seq = text[1]  # guaranteed to occur
            out = m.openai_completions({
                "prompt": "ab", "max_tokens": 6, "stop": stop_seq})
            c = out["choices"][0]
            assert c["text"] == text.split(stop_seq)[0]
            assert c["finish_reason"] == "stop"
            # list form + no-hit stop keeps full text with length reason
            out2 = m.openai_completions({
                "prompt": "ab", "max_tokens": 6, "stop": ["\x00zz"]})
            assert out2["choices"][0]["text"] == text
            assert out2["choices"][0]["finish_reason"] == "length"
        finally:
            m.stop()

    def test_n_choices(self):
        m = self._model()
        try:
            out = m.openai_completions({
                "prompt": "ab", "max_tokens": 4, "n": 3})
            assert len(out["choices"]) == 3
            assert [c["index"] for c in out["choices"]] == [0, 1, 2]
            # greedy: all three samples identical; with temperature they
            # are independent draws
            assert len({c["text"] for c in out["choices"]}) == 1
        finally:
            m.stop()

    def test_streaming_stop(self):
        m = self._model()
        try:
            base = m.openai_completions({"prompt": "ab", "max_tokens": 6})
            text = base["choices"][0]["text"]
            stop_seq = text[2]
            chunks = list(m.openai_stream({
                "prompt": "ab", "max_tokens": 6, "stop": stop_seq}))
            import json as jsonlib

            body = "".join(
                jsonlib.loads(c[len(b"data: "):].decode())["choices"][0]
                ["text"]
                for c in chunks if c.startswith(b"data: {"))
            assert body == text.split(stop_seq)[0]
        finally:
            m.stop()


class TestOpenAiChat:
    def _model(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        ref = register_mem("chatllama", (cfg, params))
        m = TextGenerator("c", {"params_ref": ref, "max_new_tokens": 4,
                                "warmup_groups": []})
        m.start()
        return m

    def test_chat_equals_templated_completion(self):
        m = self._model()
        try:
            messages = [{"role": "system", "content": "be brief"},
                        {"role": "user", "content": "hi"}]
            chat = m.openai_chat({"messages": messages, "max_tokens": 4})
            comp = m.openai_completions({
                "prompt": m._chat_prompt(messages), "max_tokens": 4})
            c = chat["choices"][0]
            assert chat["object"] == "chat.completion"
            assert c["message"]["role"] == "assistant"
            assert c["message"]["content"] == comp["choices"][0]["text"]
            assert "finish_reason" in c
        finally:
            m.stop()

    def test_chat_stream_chunks(self):
        import json as jsonlib

        m = self._model()
        try:
            messages = [{"role": "user", "content": "hi"}]
            full = m.openai_chat({"messages": messages, "max_tokens": 4})
            chunks = list(m.openai_chat_stream(
                {"messages": messages, "max_tokens": 4}))
            body = "".join(
                jsonlib.loads(c[len(b"data: "):].decode())["choices"][0]
                ["delta"]["content"]
                for c in chunks if c.startswith(b"data: {"))
            assert body == full["choices"][0]["message"]["content"]
            assert chunks[-1] == b"data: [DONE]\n\n"
        finally:
            m.stop()

    def test_chat_route_over_http(self):
        import json as jsonlib
        import urllib.request

        from kubeflow_tpu.serving.server import ModelServer

        m = self._model()
        srv = ModelServer()
        srv.register(m)
        srv.start()
        try:
            req = urllib.request.Request(
                srv.url + "/openai/v1/chat/completions",
                data=jsonlib.dumps({
                    "model": "c",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = jsonlib.loads(resp.read())
            assert out["object"] == "chat.completion"
            assert out["choices"][0]["message"]["content"]
        finally:
            srv.stop()


class TestStreamN:
    def test_streaming_n_choices(self):
        import jax
        import jax.numpy as jnp
        import json as jsonlib

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        ref = register_mem("streamn", (cfg, params))
        m = TextGenerator("s", {"params_ref": ref, "max_new_tokens": 4,
                                "warmup_groups": []})
        m.start()
        try:
            chunks = list(m.openai_stream(
                {"prompt": "ab", "max_tokens": 4, "n": 3}))
            idx = {
                jsonlib.loads(c[len(b"data: "):].decode())["choices"][0]
                ["index"]
                for c in chunks if c.startswith(b"data: {")}
            assert idx == {0, 1, 2}
        finally:
            m.stop()
