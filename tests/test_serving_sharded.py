"""Sharded (multi-chip) serving parity on the 8-device CPU mesh.

The reference's serving tier spans accelerators natively (TP vLLM/Triton
instances; SURVEY.md §2.2).  These tests prove the TP serving path —
weights and KV pool sharded over a ``{"model": N}`` mesh — produces the
SAME tokens as the single-device path, on the same virtual-device SPMD
backend the trainer parity tests use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving import sharded as shardedlib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.runtimes import LlamaGenerator
from kubeflow_tpu.serving.storage import register_mem


def _tiny():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    return cfg, params


PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]


class TestShardedGenerator:
    def test_tp_parity_with_single_device(self):
        cfg, params = _tiny()
        ref = register_mem("shardllama", (cfg, params))
        single = LlamaGenerator("g1", {"params_ref": ref, "max_new_tokens": 4})
        single.start()
        want = single.predict_batch(PROMPTS)

        tp = LlamaGenerator(
            "g2", {"params_ref": ref, "max_new_tokens": 4,
                   "mesh_axes": {"model": 2}})
        tp.start()
        got = tp.predict_batch(PROMPTS)
        assert got == want

    def test_params_and_cache_actually_sharded(self):
        cfg, params = _tiny()
        ref = register_mem("shardllama2", (cfg, params))
        g = LlamaGenerator(
            "g", {"params_ref": ref, "max_new_tokens": 2,
                  "mesh_axes": {"model": 2}})
        g.start()
        # weights: the mlp kernel's hidden dim must be split over 2 devices
        wg = g.params["layers"]["block"]["mlp"]["w_gate"]["kernel"]
        assert len(wg.sharding.device_set) == 2
        shard_shapes = {s.data.shape for s in wg.addressable_shards}
        full = wg.shape
        assert all(sh[-1] == full[-1] // 2 for sh in shard_shapes)
        # KV cache: kv_heads axis split
        cache = g._init_cache(2)
        leaf = jax.tree.leaves(
            {k: v for k, v in cache.items()})  # any collection layout
        big = [x for x in leaf if x.ndim >= 4]
        assert big, "expected tensor cache leaves"
        for x in big:
            assert len(x.sharding.device_set) == 2
            assert {s.data.shape[-2] for s in x.addressable_shards} == {
                x.shape[-2] // 2}

    def test_tp4_parity(self):
        """model axis 4 needs q_per_kv grouping to still work: tiny has 2
        kv heads, so TP=4 would split heads below kv groups — use a config
        with 4 kv heads instead."""
        cfg = llamalib.tiny(num_heads=4, num_kv_heads=4)
        model = llamalib.Llama(cfg)
        params = model.init(
            jax.random.PRNGKey(1), jnp.ones((1, 8), jnp.int32))["params"]
        ref = register_mem("shardllama4", (cfg, params))
        single = LlamaGenerator("s", {"params_ref": ref, "max_new_tokens": 3})
        single.start()
        want = single.predict_batch(PROMPTS)
        tp = LlamaGenerator(
            "t", {"params_ref": ref, "max_new_tokens": 3,
                  "mesh_axes": {"model": 4}})
        tp.start()
        assert tp.predict_batch(PROMPTS) == want


class TestShardedContinuousEngine:
    def test_tp_engine_parity(self):
        cfg, params = _tiny()
        single = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=2, eos_id=None)
        try:
            want = [single.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            single.stop()

        tp = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=2, eos_id=None,
            mesh_axes={"model": 2})
        try:
            # pool buffers must be sharded over the mesh
            big = [x for x in jax.tree.leaves(tp._pool_cache) if x.ndim >= 4]
            assert big and all(len(x.sharding.device_set) == 2 for x in big)
            got = [tp.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            tp.stop()
        assert got == want

    def test_tp_engine_concurrent_burst(self):
        cfg, params = _tiny()
        eng = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=2, eos_id=None,
            mesh_axes={"model": 2})
        try:
            eng.warmup()
            reqs = [eng.submit(p, max_new_tokens=4) for p in PROMPTS * 2]
            outs = [r.wait(timeout=120) for r in reqs]
        finally:
            eng.stop()
        assert all(len(o) == 4 for o in outs)
        # same prompt -> same greedy continuation regardless of slot
        assert outs[0] == outs[3] and outs[1] == outs[4] and outs[2] == outs[5]

    def test_warmup_after_traffic_rejected(self):
        cfg, params = _tiny()
        eng = ContinuousEngine(cfg, params, num_slots=2, decode_chunk=1)
        try:
            eng.generate([1, 2], max_new_tokens=1)
            with pytest.raises(RuntimeError, match="warmup"):
                eng.warmup()
        finally:
            eng.stop()


class TestServingMeshHelpers:
    def test_build_mesh_uses_subset_of_devices(self):
        mesh = shardedlib.build_serving_mesh({"model": 2})
        assert mesh.devices.size == 2

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="needs"):
            shardedlib.build_serving_mesh({"model": 64})

    def test_cache_sharding_replicates_scalars(self):
        mesh = shardedlib.build_serving_mesh({"model": 2})
        s = shardedlib.cache_leaf_sharding(mesh, 1)
        assert s.is_fully_replicated
        s5 = shardedlib.cache_leaf_sharding(mesh, 5)
        assert not s5.is_fully_replicated
