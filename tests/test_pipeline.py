"""Pipeline parallelism: GPipe executor + trainer integration.

Covers the SURVEY §2.5 PP row ("stage meshes over DCN between slices;
collective-permute microbatch pipeline") the round-1 verdict flagged as
missing: stage partitioning of the scanned Llama stack, microbatch
scheduling via shard_map/ppermute, loss-trajectory equivalence against the
single-mesh run, and the num_slices=2 hybrid (DCN) mesh path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import pipeline as pipelib
from kubeflow_tpu.parallel import sharding as shardlib
from kubeflow_tpu.train import trainer as trainlib


def _losses(axes, *, num_slices=1, steps=4, num_microbatches=None, model=None,
            **kw):
    cfg = trainlib.TrainConfig(
        model=model or llamalib.tiny(num_layers=4, remat=True),
        mesh_axes=axes,
        num_slices=num_slices,
        global_batch=8,
        seq_len=32,
        steps=steps,
        log_every=1,
        learning_rate=1e-3,
        num_microbatches=num_microbatches,
        **kw,
    )
    t = trainlib.Trainer(cfg, devices=jax.devices())
    out = []
    t.train(on_metrics=lambda m: out.append(m.loss))
    return out


def test_gpipe_matches_sequential_scan():
    """Pure-executor check: pipelined apply == plain scan over layers."""
    mesh = meshlib.build_mesh({"pipeline": 4, "data": 2})
    rng = jax.random.PRNGKey(0)
    n_layers, width, batch = 8, 16, 8
    ws = jax.random.normal(rng, (n_layers, width, width)) * 0.1

    def block_apply(w, x):
        return jnp.tanh(x @ w)

    x = jax.random.normal(jax.random.PRNGKey(1), (batch, width))

    def seq_ref(ws, x):
        for i in range(n_layers):
            x = block_apply(ws[i], x)
        return x

    with shardlib.shard_context(mesh):
        ref = jax.jit(seq_ref)(ws, x)
        out = jax.jit(
            lambda ws, x: pipelib.gpipe(
                block_apply, ws, x, mesh=mesh, num_microbatches=4)
        )(ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_gpipe_grads_match():
    """Backward pipeline (reverse ppermute ring) gives the same grads."""
    mesh = meshlib.build_mesh({"pipeline": 2, "data": 4})
    n_layers, width, batch = 4, 8, 4
    ws = jax.random.normal(jax.random.PRNGKey(0), (n_layers, width, width)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, width))

    def block_apply(w, h):
        return jnp.tanh(h @ w)

    def loss_seq(ws):
        h = x
        for i in range(n_layers):
            h = block_apply(ws[i], h)
        return (h ** 2).mean()

    def loss_pp(ws):
        h = pipelib.gpipe(block_apply, ws, x, mesh=mesh, num_microbatches=2)
        return (h ** 2).mean()

    with shardlib.shard_context(mesh):
        g_ref = jax.jit(jax.grad(loss_seq))(ws)
        g_pp = jax.jit(jax.grad(loss_pp))(ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref), atol=1e-5)


def test_pipeline_matches_single_mesh_loss_trajectory():
    """{pipeline:2, data:4} training == {data:8} training, step for step."""
    ref = _losses({"data": 8})
    pp = _losses({"pipeline": 2, "data": 4})
    assert len(ref) == len(pp) == 4
    np.testing.assert_allclose(pp, ref, atol=1e-4)


def test_pipeline_more_microbatches():
    """More microbatches than stages (smaller bubble) stays equivalent."""
    ref = _losses({"data": 8}, steps=2)
    pp = _losses({"pipeline": 2, "data": 4}, steps=2, num_microbatches=4)
    np.testing.assert_allclose(pp, ref, atol=1e-4)


def test_pipeline_over_dcn_hybrid_mesh():
    """num_slices=2: the planner puts pipeline on DCN and training runs."""
    axes = {"pipeline": 2, "seq": 2, "model": 2}
    plan = meshlib.plan_mesh(axes, num_devices=8, num_slices=2)
    assert plan.dcn_axes == {"pipeline": 2}
    assert plan.ici_axes == {"seq": 2, "model": 2}
    losses = _losses(axes, num_slices=2, steps=2)
    assert len(losses) == 2 and all(np.isfinite(l) for l in losses)


def test_dcn_planner_rejects_model_axis_over_slices():
    """Bandwidth-hungry axes crossing slice boundaries must not compile."""
    with pytest.raises(meshlib.MeshPlanError):
        meshlib.plan_mesh({"model": 8}, num_devices=8, num_slices=2)


def test_pipeline_indivisible_batch_rejected():
    mesh = meshlib.build_mesh({"pipeline": 2, "data": 4})
    ws = jnp.zeros((4, 8, 8))
    x = jnp.zeros((5, 8))
    with pytest.raises(ValueError, match="not divisible"):
        with shardlib.shard_context(mesh):
            pipelib.gpipe(
                lambda w, h: h @ w, ws, x, mesh=mesh, num_microbatches=2)


# -- 1F1B -------------------------------------------------------------------


def _mlp_problem(n_layers=8, width=16, batch=8, seed=0):
    k = jax.random.PRNGKey(seed)
    kw, kh, kx, kt = jax.random.split(k, 4)
    ws = jax.random.normal(kw, (n_layers, width, width)) * 0.1
    head = jax.random.normal(kh, (width, 4)) * 0.1
    x = jax.random.normal(kx, (batch, width))
    tgt = jax.random.normal(kt, (batch, 4))

    def block_apply(w, h):
        return jnp.tanh(h @ w)

    def loss_fn(hp, y, t):
        return ((y @ hp - t) ** 2).mean()

    def seq_ref(ws, hp, x):
        h = x
        for i in range(n_layers):
            h = block_apply(ws[i], h)
        return loss_fn(hp, h, tgt)

    return block_apply, loss_fn, ws, head, x, tgt, seq_ref


@pytest.mark.parametrize("p,m", [(2, 2), (2, 4), (4, 4), (4, 8)])
def test_1f1b_loss_and_grads_match_sequential(p, m):
    """The fused 1F1B value-and-grad equals sequential autodiff exactly —
    loss, layer grads, head grads, and input grads."""
    block_apply, loss_fn, ws, head, x, tgt, seq_ref = _mlp_problem()
    mesh = meshlib.build_mesh({"pipeline": p, "data": 8 // p})

    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(seq_ref, argnums=(0, 1, 2)))(ws, head, x)

    with shardlib.shard_context(mesh):
        loss, (dws, dhead, dx) = jax.jit(
            lambda ws, hp, x, tgt: pipelib.one_f_one_b(
                block_apply, loss_fn, ws, hp, x, tgt,
                mesh=mesh, num_microbatches=m)
        )(ws, head, x, tgt)

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dws), np.asarray(ref_grads[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dhead), np.asarray(ref_grads[1]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_grads[2]), atol=1e-5)


def test_1f1b_no_pipeline_axis_falls_back():
    block_apply, loss_fn, ws, head, x, tgt, seq_ref = _mlp_problem()
    mesh = meshlib.build_mesh({"data": 8})
    ref_loss, _ = jax.jit(
        jax.value_and_grad(seq_ref, argnums=(0, 1, 2)))(ws, head, x)
    with shardlib.shard_context(mesh):
        loss, grads = pipelib.one_f_one_b(
            block_apply, loss_fn, ws, head, x, tgt, mesh=mesh)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-6)


def test_1f1b_schedule_properties():
    """Schedule invariants: every (stage, microbatch) runs fwd and bwd
    exactly once, in order, and the stash bound stays ~P, not M."""
    for p, m in [(2, 8), (4, 16), (8, 8)]:
        s = pipelib.schedule_1f1b(p, m)
        for st in range(p):
            fs = [s.fwd[t, st] for t in range(s.ticks) if s.fwd[t, st] >= 0]
            bs = [s.bwd[t, st] for t in range(s.ticks) if s.bwd[t, st] >= 0]
            assert fs == list(range(m))
            assert bs == list(range(m))
        # the 1F1B memory bound: in-flight activations ~P regardless of M
        assert s.act_slots <= p + 2
        assert s.grad_slots <= 2
        # schedule length reaches the latency-adjusted ideal M + 2(P-1)
        # (within the few extra warmup ticks deep pipelines need)
        assert s.ticks <= m + 2 * (p - 1) + p // 2


def test_1f1b_trainer_matches_single_mesh_loss_trajectory():
    """{pipeline:2, data:4} 1F1B training == {data:8} training, step for
    step — the same bar the GPipe schedule passes."""
    ref = _losses({"data": 8}, steps=3)
    pp = _losses({"pipeline": 2, "data": 4}, steps=3,
                 num_microbatches=4, pipeline_schedule="1f1b")
    assert len(ref) == len(pp) == 3
    np.testing.assert_allclose(pp, ref, atol=1e-4)


class TestMoeAuxUnderPipeline:
    """r3 verdict item 3: the Switch balancing loss must train through the
    pipeline executors — a 7B-class MoE over two DCN slices is the natural
    composition of 1F1B and dropless MoE."""

    MOE = dict(num_layers=4, remat=True, moe_experts=2, moe_top_k=1)

    def test_gpipe_collects_aux(self):
        """Pipelined MoE loss includes the aux term: turning the coef off
        lowers the objective by exactly a positive aux contribution."""
        with_aux = _losses({"pipeline": 2, "data": 4}, steps=2,
                           model=llamalib.tiny(**self.MOE),
                           aux_loss_coef=0.01)
        without = _losses({"pipeline": 2, "data": 4}, steps=2,
                          model=llamalib.tiny(**self.MOE),
                          aux_loss_coef=0.0)
        assert all(np.isfinite(l) for l in with_aux + without)
        # aux >= 1.0 by construction (Switch: balanced routing gives 1)
        assert with_aux[0] > without[0] + 0.005

    def test_1f1b_loss_matches_gpipe(self):
        """Same microbatching, same aux normalization: the two schedules
        compute the same objective."""
        g = _losses({"pipeline": 2, "data": 4}, steps=2, num_microbatches=4,
                    model=llamalib.tiny(**self.MOE), aux_loss_coef=0.01)
        f = _losses({"pipeline": 2, "data": 4}, steps=2, num_microbatches=4,
                    model=llamalib.tiny(**self.MOE), aux_loss_coef=0.01,
                    pipeline_schedule="1f1b")
        np.testing.assert_allclose(f, g, atol=1e-4)

    def test_1f1b_aux_matches_single_mesh_trajectory(self):
        """MoE + aux under 1F1B descends in lockstep with the plain-mesh
        run (same per-microbatch aux: single-mesh computes aux on the full
        batch, so allow a loose tolerance on the regularizer term)."""
        ref = _losses({"data": 8}, steps=3,
                      model=llamalib.tiny(**self.MOE), aux_loss_coef=0.01)
        pp = _losses({"pipeline": 2, "data": 4}, steps=3, num_microbatches=4,
                     model=llamalib.tiny(**self.MOE), aux_loss_coef=0.01,
                     pipeline_schedule="1f1b")
        np.testing.assert_allclose(pp, ref, atol=0.02)


class TestAccumUnder1F1B:
    def test_accum_1f1b_matches_unaccumulated(self):
        """accum x 1F1B: each accum chunk runs a full 1F1B round; the
        averaged step must match the single-shot step."""
        ref = _losses({"pipeline": 2, "data": 4}, steps=2,
                      num_microbatches=2, pipeline_schedule="1f1b")
        acc = _losses({"pipeline": 2, "data": 4}, steps=2,
                      num_microbatches=2, pipeline_schedule="1f1b",
                      accum_steps=2)
        np.testing.assert_allclose(acc, ref, atol=1e-4)

    def test_tie_embeddings_1f1b_matches_single_mesh(self):
        """tie_embeddings x 1F1B (the r3 verdict's last trainer guard,
        now closed): the tied table rides the head bundle to the last
        stage; its unembedding gradient folds back into the embedder —
        trajectory must match the single-mesh run exactly."""
        tie = dict(num_layers=4, remat=True, tie_embeddings=True)
        ref = _losses({"data": 8}, steps=3, model=llamalib.tiny(**tie))
        pp = _losses({"pipeline": 2, "data": 4}, steps=3,
                     num_microbatches=4, pipeline_schedule="1f1b",
                     model=llamalib.tiny(**tie))
        np.testing.assert_allclose(pp, ref, atol=1e-4)


class TestInterleaved1F1B:
    """Megatron virtual-stage interleaving (r3 verdict item 4): each
    device owns V non-contiguous chunks; wall ticks hit the model's exact
    lower bound T = MV+P+PV-2 chunk-ticks (= fewer stage-times than
    non-interleaved's (M+2P-2) x V as V grows)."""

    @pytest.mark.parametrize("p,m,v", [(2, 4, 2), (4, 8, 2), (2, 4, 4)])
    def test_interleaved_matches_sequential(self, p, m, v):
        """Interleaved fused value-and-grad == sequential autodiff, given
        the documented layer permutation."""
        block_apply, loss_fn, ws, head, x, tgt, seq_ref = _mlp_problem()
        mesh = meshlib.build_mesh({"pipeline": p, "data": 8 // p})
        ref_loss, ref_grads = jax.jit(
            jax.value_and_grad(seq_ref, argnums=(0, 1, 2)))(ws, head, x)

        perm = pipelib.interleave_permutation(ws.shape[0], p, v)
        inv = np.argsort(perm)
        with shardlib.shard_context(mesh):
            loss, (dws, dhead, dx) = jax.jit(
                lambda ws, hp, x, tgt: pipelib.one_f_one_b(
                    block_apply, loss_fn, ws, hp, x, tgt,
                    mesh=mesh, num_microbatches=m, interleave=v)
            )(ws[perm], head, x, tgt)
        dws = np.asarray(dws)[inv]

        np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-6)
        np.testing.assert_allclose(dws, np.asarray(ref_grads[0]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(dhead), np.asarray(ref_grads[1]), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(dx), np.asarray(ref_grads[2]), atol=1e-5)

    def test_schedule_hits_lower_bound(self):
        for p, m, v in [(2, 4, 2), (4, 8, 2), (4, 8, 4), (4, 16, 4)]:
            s = pipelib.schedule_1f1b(p, m, v)
            assert s.ticks == m * v + p + p * v - 2
            # per-chunk order: every chunk forwards/backwards its
            # microbatches in order, exactly once
            assert (s.fwd >= 0).sum() == m * v * p
            assert (s.bwd >= 0).sum() == m * v * p
            # stash bound: ~P*V chunk inputs, never M*V
            assert s.act_slots <= p * v + 2

    def test_interleaved_wall_ticks_beat_non_interleaved(self):
        """In equal work units (chunk-ticks / V), interleaving shortens
        the step: T(V)/V = M + P + (P-2)/V, strictly below T(1) for P>2
        (at P=2 the (P-2)/V term vanishes and it ties)."""
        for p, m in [(2, 8), (4, 8), (4, 16)]:
            t1 = pipelib.schedule_1f1b(p, m, 1).ticks
            for v in (2, 4):
                tv = pipelib.schedule_1f1b(p, m, v).ticks
                if p > 2:
                    assert tv / v < t1, (p, m, v, tv, t1)
                else:
                    assert tv / v <= t1, (p, m, v, tv, t1)

    def test_trainer_interleaved_matches_single_mesh(self):
        ref = _losses({"data": 8}, steps=2)
        il = _losses({"pipeline": 2, "data": 4}, steps=2,
                     num_microbatches=4, pipeline_schedule="1f1b",
                     pipeline_interleave=2)
        np.testing.assert_allclose(il, ref, atol=1e-4)
