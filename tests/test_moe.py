"""MoE + expert parallelism (SURVEY §2.5 EP row).

Pins down: exact dense equivalence (identical experts, renormalized top-k),
capacity-based token dropping, sharded-vs-single-device numerical parity on
a mesh with an ``expert`` axis, and the presence of all-to-all collectives
in the compiled expert-parallel HLO.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.models.moe import MoeMlp
from kubeflow_tpu.models.llama import Mlp
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import sharding as shardlib
from flax import linen as nn


def _cfg(**kw):
    base = dict(moe_experts=4, moe_top_k=2, moe_capacity_factor=2.0)
    base.update(kw)
    return llamalib.tiny(**base)


def _tie_experts(moe_params, mlp_params):
    """Give every expert the dense MLP's weights (for equivalence tests)."""
    e = moe_params["w_gate"].shape[0]
    out = dict(moe_params)
    for name, src in (("w_gate", "w_gate"), ("w_up", "w_up"), ("w_down", "w_down")):
        w = mlp_params[src]["kernel"]
        if name == "w_down":
            out[name] = jnp.broadcast_to(w[None], (e, *w.shape))
        else:
            out[name] = jnp.broadcast_to(w[None], (e, *w.shape))
    return out


class TestDenseEquivalence:
    def test_identical_experts_match_dense_mlp(self):
        """top-k renormalized + identical experts + ample capacity == Mlp."""
        cfg = _cfg()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, cfg.hidden_size),
                              jnp.float32)
        mlp = Mlp(cfg)
        mlp_params = nn.meta.unbox(mlp.init(jax.random.PRNGKey(1), x)["params"])
        moe = MoeMlp(cfg)
        moe_params = nn.meta.unbox(moe.init(jax.random.PRNGKey(2), x)["params"])
        tied = _tie_experts(moe_params, mlp_params)
        ref = mlp.apply({"params": mlp_params}, x)
        out = moe.apply({"params": tied}, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_top1_identical_experts_match_dense(self):
        cfg = _cfg(moe_top_k=1)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, cfg.hidden_size),
                              jnp.float32)
        mlp = Mlp(cfg)
        mlp_params = nn.meta.unbox(mlp.init(jax.random.PRNGKey(1), x)["params"])
        moe = MoeMlp(cfg)
        moe_params = nn.meta.unbox(moe.init(jax.random.PRNGKey(2), x)["params"])
        tied = _tie_experts(moe_params, mlp_params)
        ref = mlp.apply({"params": mlp_params}, x)
        out = moe.apply({"params": tied}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


class TestRoutingMechanics:
    def test_capacity_drops_tokens(self):
        """capacity_factor ~0 forces dropping: output magnitude shrinks."""
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 64), jnp.float32)
        big = MoeMlp(_cfg(moe_capacity_factor=8.0))
        small = MoeMlp(_cfg(moe_capacity_factor=0.01))
        p = nn.meta.unbox(big.init(jax.random.PRNGKey(1), x)["params"])
        out_big = big.apply({"params": p}, x)
        out_small = small.apply({"params": p}, x)
        # capacity 0.01 -> capacity=1 slot per expert: most tokens dropped
        assert float(jnp.abs(out_small).mean()) < float(jnp.abs(out_big).mean())

    def test_aux_loss_sown(self):
        cfg = _cfg()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, cfg.hidden_size))
        moe = MoeMlp(cfg)
        p = nn.meta.unbox(moe.init(jax.random.PRNGKey(1), x)["params"])
        _, inter = moe.apply(
            {"params": p}, x, mutable=["intermediates"])
        (aux,) = inter["intermediates"]["moe_aux_loss"]
        # balanced routing gives aux ~1.0; any finite positive value is sane
        assert 0.0 < float(aux) < 16.0


class TestExpertParallel:
    def test_sharded_matches_single_device(self):
        """MoE Llama forward on {expert,data,model} mesh == single device."""
        cfg = _cfg(num_layers=2)
        model = llamalib.Llama(cfg)
        tokens = jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16) % cfg.vocab_size
        params = model.init(jax.random.PRNGKey(0), tokens)
        ref = model.apply(params, tokens)

        mesh = meshlib.build_mesh({"expert": 2, "data": 2, "model": 2})
        with shardlib.shard_context(mesh):
            sharded = jax.jit(model.apply)(params, tokens)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(ref), atol=3e-2, rtol=3e-2)

    def test_all_to_all_in_expert_parallel_hlo(self):
        """GSPMD lowers the batch->expert resharding to all-to-all."""
        cfg = _cfg(num_layers=1)
        model = llamalib.Llama(cfg)
        tokens = jnp.ones((8, 16), jnp.int32)
        mesh = meshlib.build_mesh({"expert": 4, "data": 2})
        with shardlib.shard_context(mesh):
            params = model.init(jax.random.PRNGKey(0), tokens)
            compiled = (
                jax.jit(model.apply)
                .lower(params, tokens)
                .compile()
            )
        hlo = compiled.as_text()
        assert "all-to-all" in hlo, "expert dispatch did not lower to all-to-all"

    def test_moe_trains_on_expert_mesh(self):
        """One optimization step of the MoE Llama on an expert-axis mesh."""
        from kubeflow_tpu.train import trainer as trainlib

        cfg = trainlib.TrainConfig(
            model=_cfg(num_layers=2),
            mesh_axes={"expert": 2, "data": 2, "model": 2},
            global_batch=8,
            seq_len=16,
            steps=2,
            log_every=1,
        )
        t = trainlib.Trainer(cfg, devices=jax.devices())
        m = t.train()
        assert m is not None and m.step == 2
        assert np.isfinite(m.loss)


class TestRaggedDispatch:
    """Dropless dispatch via ragged_all_to_all (SURVEY §2.5 EP row)."""

    def test_ragged_matches_dense_at_ample_capacity(self):
        """With capacity high enough that dense drops nothing, the two
        dispatch impls are the same function (fwd)."""
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 64), jnp.float32)
        dense = MoeMlp(_cfg(moe_capacity_factor=8.0))
        ragged = MoeMlp(_cfg(moe_dispatch="ragged"))
        p = nn.meta.unbox(dense.init(jax.random.PRNGKey(1), x)["params"])
        out_d = dense.apply({"params": p}, x)
        out_r = ragged.apply({"params": p}, x)
        np.testing.assert_allclose(
            np.asarray(out_r), np.asarray(out_d), atol=2e-5)

    def test_ragged_grads_match_dense(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 64), jnp.float32)
        dense = MoeMlp(_cfg(moe_capacity_factor=8.0))
        ragged = MoeMlp(_cfg(moe_dispatch="ragged"))
        p = nn.meta.unbox(dense.init(jax.random.PRNGKey(1), x)["params"])

        def loss(mod):
            return lambda pp: (mod.apply({"params": pp}, x) ** 2).mean()

        g_d = jax.grad(loss(dense))(p)
        g_r = jax.grad(loss(ragged))(p)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=3e-5),
            g_d, g_r)

    def test_ragged_never_drops(self):
        """The config that forces heavy dropping in dense mode (capacity
        ~1 slot) changes nothing in ragged mode: dropless means the
        capacity factor is out of the picture."""
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 64), jnp.float32)
        r_small = MoeMlp(_cfg(moe_dispatch="ragged", moe_capacity_factor=0.01))
        r_big = MoeMlp(_cfg(moe_dispatch="ragged", moe_capacity_factor=8.0))
        p = nn.meta.unbox(r_big.init(jax.random.PRNGKey(1), x)["params"])
        out_small = r_small.apply({"params": p}, x)
        out_big = r_big.apply({"params": p}, x)
        np.testing.assert_allclose(
            np.asarray(out_small), np.asarray(out_big), atol=0, rtol=0)

    def test_ragged_skewed_routing_beats_dense_drops(self):
        """A router collapsed onto one expert: dense at capacity_factor=1
        drops most assignments; ragged honors all of them (outputs match a
        drop-free reference)."""
        cfg = _cfg(moe_dispatch="ragged", moe_top_k=1)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 64), jnp.float32)
        moe = MoeMlp(cfg)
        p = nn.meta.unbox(moe.init(jax.random.PRNGKey(1), x)["params"])
        # collapse the router: all tokens to expert 0
        p = dict(p)
        p["router"] = jnp.zeros_like(p["router"]).at[:, 0].set(10.0)
        out_r = moe.apply({"params": p}, x)
        ample = MoeMlp(_cfg(moe_top_k=1, moe_capacity_factor=64.0))
        out_ref = ample.apply({"params": p}, x)
        np.testing.assert_allclose(
            np.asarray(out_r), np.asarray(out_ref), atol=2e-5)
        dropped = MoeMlp(_cfg(moe_top_k=1, moe_capacity_factor=1.0))
        out_drop = dropped.apply({"params": p}, x)
        # sanity that the comparison means something: dense DID drop
        assert float(jnp.abs(out_drop - out_ref).max()) > 1e-3

    def test_ragged_sharded_matches_single_device(self):
        """Ragged MoE Llama forward on an {expert,data} mesh (real
        ragged_all_to_all transport between expert shards) == single
        device."""
        cfg = _cfg(num_layers=2, moe_dispatch="ragged")
        model = llamalib.Llama(cfg)
        tokens = jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16) % cfg.vocab_size
        params = model.init(jax.random.PRNGKey(0), tokens)
        ref = model.apply(params, tokens)

        mesh = meshlib.build_mesh({"expert": 4, "data": 2})
        with shardlib.shard_context(mesh):
            sharded = jax.jit(model.apply)(params, tokens)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(ref), atol=3e-2, rtol=3e-2)

    def test_ragged_trains_on_expert_mesh(self):
        from kubeflow_tpu.train import trainer as trainlib

        cfg = trainlib.TrainConfig(
            model=_cfg(num_layers=2, moe_dispatch="ragged"),
            mesh_axes={"expert": 2, "data": 4},
            global_batch=8,
            seq_len=16,
            steps=2,
            log_every=1,
        )
        t = trainlib.Trainer(cfg, devices=jax.devices())
        m = t.train()
        assert m is not None and m.step == 2
        assert np.isfinite(m.loss)


class TestGroupedCompute:
    """moe_ragged_compute="grouped": the Pallas grouped-GEMM path equals
    the masked-scan fallback bit-for-bit (same math, fewer FLOPs)."""

    def test_grouped_matches_masked_single_shard(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 64), jnp.float32)
        masked = MoeMlp(_cfg(moe_dispatch="ragged", moe_ragged_compute="masked"))
        grouped = MoeMlp(_cfg(moe_dispatch="ragged", moe_ragged_compute="grouped"))
        p = nn.meta.unbox(masked.init(jax.random.PRNGKey(1), x)["params"])
        out_m = masked.apply({"params": p}, x)
        out_g = grouped.apply({"params": p}, x)
        np.testing.assert_allclose(
            np.asarray(out_g), np.asarray(out_m), atol=2e-5)

    def test_grouped_grads_match_masked(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 64), jnp.float32)
        masked = MoeMlp(_cfg(moe_dispatch="ragged", moe_ragged_compute="masked"))
        grouped = MoeMlp(_cfg(moe_dispatch="ragged", moe_ragged_compute="grouped"))
        p = nn.meta.unbox(masked.init(jax.random.PRNGKey(1), x)["params"])

        def loss(mod):
            return lambda pp: (mod.apply({"params": pp}, x) ** 2).mean()

        g_m = jax.grad(loss(masked))(p)
        g_g = jax.grad(loss(grouped))(p)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), atol=3e-5),
            g_m, g_g)

    def test_grouped_sharded_matches_single_device(self):
        """Grouped compute downstream of the real ragged transport on an
        {expert, data} mesh == single device."""
        cfg = _cfg(num_layers=2, moe_dispatch="ragged",
                   moe_ragged_compute="grouped")
        model = llamalib.Llama(cfg)
        tokens = jnp.arange(4 * 16, dtype=jnp.int32).reshape(4, 16) % cfg.vocab_size
        params = model.init(jax.random.PRNGKey(0), tokens)
        ref = model.apply(params, tokens)
        mesh = meshlib.build_mesh({"expert": 2, "data": 4})
        with shardlib.shard_context(mesh):
            sharded = jax.jit(model.apply)(params, tokens)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(ref), atol=3e-2, rtol=3e-2)
