"""PBT e2e trial entrypoint: file-state 'training' that accumulates across
checkpoint forks.

theta lives in <KFT_PBT_ROOT>/<trial>/theta; a forked trial (KFT_RESUME_FROM)
starts from its parent's theta — exactly the exploit step's contract.  Each
generation adds 1 - (lr - 0.03)^2 * 100 (maximized at lr=0.03, max 1.0), so
any score > 1.0 proves a fork actually carried state forward.
"""

import os

from kubeflow_tpu.runtime import bootstrap


def objective_main(ctx) -> None:
    root = os.environ["KFT_PBT_ROOT"]
    own = os.path.join(root, ctx.job_name)
    parent = os.environ.get("KFT_RESUME_FROM", "").strip()
    theta = 0.0
    if parent:
        try:
            with open(os.path.join(root, parent, "theta")) as f:
                theta = float(f.read())
        except OSError:
            pass
    lr = float(os.environ.get("KFT_LR", "0.1"))
    theta += 1.0 - (lr - 0.03) ** 2 * 100.0
    os.makedirs(own, exist_ok=True)
    with open(os.path.join(own, "theta"), "w") as f:
        f.write(str(theta))
    bootstrap.emit_metric(ctx, "score", theta)
