"""Pretrained-snapshot fine-tune UX — the literal north-star example.

SURVEY.md §3.5: ``TrainingClient.train()`` fine-tuning a published model is
the reference SDK's v1.9 LLM path.  Here: ``llama.save_pretrained`` writes
the snapshot, ``KFT_INIT_FROM=hf://org/name[@rev]`` (resolved through the
storage initializer) initializes a JaxJob's trainer from it, and
``TrainingClient.train(model=...)`` is the one-call UX.
"""

import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.train import trainer as trainlib


def _trees_equal(a, b):
    ok = True
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        ok = ok and np.allclose(np.asarray(la), np.asarray(lb))
    return ok


class TestSnapshotRoundtrip:
    def test_save_load_roundtrip(self, tmp_path):
        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        path = str(tmp_path / "snap")
        llamalib.save_pretrained(path, cfg, params)
        cfg2, params2 = llamalib.load_pretrained(path)
        assert cfg2 == cfg
        from flax import linen as nn

        assert _trees_equal(nn.meta.unbox(params), params2)

    def test_load_config_only(self, tmp_path):
        cfg = llamalib.tiny(num_layers=3)
        path = str(tmp_path / "snap")
        llamalib.save_pretrained(
            path, cfg,
            llamalib.Llama(cfg).init(
                jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        got = llamalib.load_pretrained_config(path)
        assert got.num_layers == 3 and got == cfg


class TestTrainerInitFrom:
    def _snapshot(self, tmp_path, cfg, seed=0):
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(seed), jnp.ones((1, 8), jnp.int32))["params"]
        path = str(tmp_path / "snap")
        llamalib.save_pretrained(path, cfg, params)
        from flax import linen as nn

        return path, nn.meta.unbox(params)

    def test_params_load_and_optimizer_fresh(self, tmp_path):
        cfg = llamalib.tiny()
        path, want = self._snapshot(tmp_path, cfg, seed=7)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=cfg, steps=1, global_batch=8, seq_len=16, init_from=path))
        state = t.init_state()
        assert _trees_equal(state["params"], want)
        assert int(state["step"]) == 0

    def test_init_from_on_sharded_mesh(self, tmp_path):
        """Weights must land correctly when params shard over fsdp+model —
        the 7B-over-v5e-16 layout in miniature."""
        cfg = llamalib.tiny()
        path, want = self._snapshot(tmp_path, cfg, seed=3)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=cfg, steps=1, global_batch=8, seq_len=16, init_from=path,
            mesh_axes={"fsdp": 2, "model": 2, "data": 2}))
        state = t.init_state()
        wg = state["params"]["layers"]["block"]["mlp"]["w_gate"]["kernel"]
        assert not wg.sharding.is_fully_replicated  # actually sharded
        assert _trees_equal(state["params"], want)  # and still the snapshot

    def test_arch_mismatch_raises(self, tmp_path):
        path, _ = self._snapshot(tmp_path, llamalib.tiny(num_layers=2))
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=llamalib.tiny(num_layers=3), steps=1, global_batch=8,
            seq_len=16, init_from=path))
        with pytest.raises(ValueError, match="num_layers"):
            t.init_state()

    def test_resume_wins_over_init(self, tmp_path, tmp_ckpt_dir):
        """A newer checkpoint beats the pretrained snapshot: a gang restart
        mid-fine-tune must resume, not re-load the base model."""
        cfg = llamalib.tiny()
        path, want = self._snapshot(tmp_path, cfg)
        base = trainlib.TrainConfig(
            model=cfg, steps=3, global_batch=8, seq_len=16,
            checkpoint_dir=tmp_ckpt_dir, save_interval_steps=1)
        t1 = trainlib.Trainer(base)
        t1.train()
        import dataclasses

        t2 = trainlib.Trainer(dataclasses.replace(base, init_from=path))
        state = t2.restore_or_init()
        assert int(jax.device_get(state["step"])) == 3
        assert not _trees_equal(state["params"], want)


@pytest.mark.e2e
class TestFinetuneE2E:
    def test_hf_snapshot_finetune_two_workers(self, tmp_path):
        """The full north-star loop: pretrain -> publish as an hf:// hub
        snapshot -> TrainingClient.train(model="hf://...") fine-tunes it as
        a 2-process JaxJob whose first logged loss is FAR below the scratch
        start (ln 256 ~ 5.55) — proof the weights actually loaded."""
        from kubeflow_tpu.api.common import JobConditionType, has_condition
        from kubeflow_tpu.runtime.platform import LocalPlatform
        from kubeflow_tpu.sdk import TrainingClient

        # -- pretrain in-process and capture the trained params
        cfg = llamalib.tiny()
        ck = str(tmp_path / "pre-ckpt")
        pre = trainlib.Trainer(trainlib.TrainConfig(
            model=cfg, steps=80, learning_rate=1e-2, global_batch=8,
            seq_len=32, warmup_steps=5, log_every=20, checkpoint_dir=ck,
            save_interval_steps=80))
        final = pre.train()
        assert final.loss < 3.0, f"pretrain did not converge: {final.loss}"
        state = pre.ckpt.restore(pre.abstract_state())

        # -- publish as a hub-layout snapshot with a pinned revision
        hub = tmp_path / "hub"
        repo = hub / "models--acme--tiny-llama"
        snap = repo / "snapshots" / "c0ffee12"
        llamalib.save_pretrained(str(snap), cfg, state["params"])
        (repo / "refs").mkdir(parents=True)
        (repo / "refs" / "main").write_text("c0ffee12")

        # -- fine-tune as a 2-worker gang via the one-call SDK UX
        with LocalPlatform(num_hosts=2, chips_per_host=4,
                           root_dir=str(tmp_path / "cluster")) as platform:
            client = TrainingClient(platform)
            job = client.train(
                name="finetune",
                entrypoint="kubeflow_tpu.train.llm:train_main",
                num_workers=2,
                model="hf://acme/tiny-llama@main",
                env={
                    "KFT_HF_HOME": str(hub),
                    "KFT_STEPS": "4",
                    "KFT_BATCH": "8",
                    "KFT_SEQ_LEN": "32",
                    "KFT_LOG_EVERY": "1",
                    "KFT_LR": "1e-4",
                },
                timeout=240,
            )
            assert has_condition(
                job.status.conditions, JobConditionType.SUCCEEDED)
            log = client.get_job_logs("finetune")["finetune-worker-0"]
        losses = [float(m) for m in re.findall(r"loss=([0-9.]+)", log)]
        assert losses, log
        # scratch would start at ~ln(256)=5.55; the snapshot left off ~2.1
        assert losses[0] < 3.5, losses
        assert abs(losses[0] - final.loss) < 1.0, (losses[0], final.loss)


class TestServePublishedSnapshot:
    """The loop closes: train -> save_pretrained -> SERVE the snapshot
    (storage_path, what an hf:///file:// storage_uri resolves to)."""

    def _snapshot(self, tmp_path):
        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(2), jnp.ones((1, 8), jnp.int32))["params"]
        path = str(tmp_path / "snap")
        llamalib.save_pretrained(path, cfg, params)
        return cfg, params, path

    def test_llama_generator_from_snapshot(self, tmp_path):
        from kubeflow_tpu.serving.runtimes import LlamaGenerator
        from kubeflow_tpu.serving.storage import register_mem

        cfg, params, path = self._snapshot(tmp_path)
        ref = register_mem("serve-snap", (cfg, params))
        via_mem = LlamaGenerator("a", {"params_ref": ref,
                                       "max_new_tokens": 3})
        via_mem.start()
        want = via_mem.predict_batch([[1, 2, 3]])
        via_snap = LlamaGenerator("b", {"storage_path": path,
                                        "max_new_tokens": 3})
        via_snap.start()
        assert via_snap.predict_batch([[1, 2, 3]]) == want

    def test_continuous_from_snapshot(self, tmp_path):
        from kubeflow_tpu.serving.continuous import ContinuousLlamaGenerator

        _, _, path = self._snapshot(tmp_path)
        m = ContinuousLlamaGenerator(
            "c", {"storage_path": path, "max_new_tokens": 3,
                  "num_slots": 2, "warmup_groups": []})
        m.start()
        try:
            out = m.predict_batch([[1, 2, 3]])
            assert len(out[0]) == 3
        finally:
            m.stop()

    def test_missing_source_raises(self):
        from kubeflow_tpu.serving.runtimes import LlamaGenerator

        g = LlamaGenerator("d", {"max_new_tokens": 3})
        with pytest.raises(RuntimeError, match="params_ref or storage_uri"):
            g.load()
