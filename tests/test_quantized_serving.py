"""Int8 serving: weight-only + KV-cache quantization (SURVEY §2.2 — the
vLLM/Triton quantization family; r4 verdict missing #3).

Decode is HBM-bound, so int8 storage is the TPU-first lever: v5e reads
half the bytes per token and holds twice the KV slots per GiB.  Parity
bar (per the verdict): logits within a tolerance, plus a pinned
greedy-token fixture through the real engine.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import (
    ContinuousEngine,
    apply_serving_quant,
    build_engine,
)

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]


def _tiny_with_params(**kw):
    cfg = llamalib.tiny(**kw)
    params = nn.meta.unbox(llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    return cfg, params


class TestQuantizeForServing:
    def test_weight_tree_is_int8_with_scales(self):
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params)
        assert qcfg.quant_weights and qcfg.quant_kv
        wq = qp["layers"]["block"]["attn"]["wq"]
        assert wq["kernel"].dtype == np.int8
        # per-output-channel: scale covers (heads, head_dim), stacked [L]
        assert wq["scale"].shape == (
            cfg.num_layers, cfg.num_heads, cfg.head_dim)
        assert qp["head"]["unembedding"].dtype == np.int8
        assert qp["head"]["unembedding_scale"].shape == (cfg.vocab_size,)
        # embedding + norms stay full precision
        assert qp["embedder"]["embedding"].dtype == np.float32
        assert qp["layers"]["block"]["attn_norm"]["scale"].dtype == np.float32

    def test_logits_close(self):
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params, kv=False)
        toks = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
        want = np.asarray(
            llamalib.Llama(cfg).apply({"params": params}, toks), np.float32)
        got = np.asarray(
            llamalib.Llama(qcfg).apply({"params": qp}, toks), np.float32)
        # per-channel symmetric int8: ~1% relative error at these scales
        assert np.abs(want - got).max() <= 0.05 * np.abs(want).max()

    def test_dequantization_algebra_exact(self):
        """y = (x @ w_q) * s must equal x @ (w_q * s): the factored form
        the Einsum computes is exact algebra, not an approximation."""
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params, kv=False)
        w8 = np.asarray(qp["layers"]["block"]["mlp"]["w_gate"]["kernel"][0],
                        np.float32)
        s = np.asarray(qp["layers"]["block"]["mlp"]["w_gate"]["scale"][0])
        x = np.random.default_rng(0).normal(
            size=(3, cfg.hidden_size)).astype(np.float32)
        left = (x @ w8) * s[None, :]
        right = x @ (w8 * s[None, :])
        assert np.allclose(left, right, rtol=1e-5, atol=1e-4)

    def test_unquantized_kv_only(self):
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params, weights=False)
        assert not qcfg.quant_weights and qcfg.quant_kv
        assert qp["layers"]["block"]["attn"]["wq"]["kernel"].dtype != np.int8


class TestInt8Engine:
    def test_greedy_token_fixture(self):
        """Pinned fixture: int8 weights+KV through the real engine emit
        the SAME greedy tokens as bf16 for these prompts/weights — the
        verdict's greedy-token-match bar."""
        cfg, params = _tiny_with_params()
        ref = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                               eos_id=None)
        try:
            want = [ref.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            ref.stop()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params)
        eng = ContinuousEngine(qcfg, qp, num_slots=4, decode_chunk=2,
                               eos_id=None)
        try:
            # pool KV really is int8 (+ f32 scales)
            dtypes = {str(x.dtype) for x in jax.tree.leaves(eng._pool_cache)}
            assert "int8" in dtypes and "float32" in dtypes
            got = [eng.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            eng.stop()
        assert got == want

    def test_tp2_int8_parity_and_shardings(self):
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params)
        single = ContinuousEngine(qcfg, qp, num_slots=4, decode_chunk=2,
                                  eos_id=None)
        try:
            want = [single.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            single.stop()
        tp = ContinuousEngine(qcfg, qp, num_slots=4, decode_chunk=2,
                              eos_id=None, mesh_axes={"model": 2})
        try:
            wq = tp.params["layers"]["block"]["attn"]["wq"]
            assert wq["kernel"].dtype == jnp.int8
            assert len(wq["kernel"].sharding.device_set) == 2
            # int8-KV scale leaves shard their (LAST) kv_heads dim
            import jax.tree_util as jtu

            for path, leaf in jtu.tree_leaves_with_path(tp._pool_cache):
                if "scale" in str(path[-1]):
                    assert leaf.sharding.spec[-1] == "model"
            got = [tp.generate(p, max_new_tokens=5) for p in PROMPTS]
        finally:
            tp.stop()
        assert got == want

    def test_build_engine_quant_knobs(self):
        cfg, params = _tiny_with_params()
        eng = build_engine(cfg, params, {
            "num_slots": 2, "decode_chunk": 1, "warmup_groups": [],
            "quant_weights": True, "quant_kv": True})
        try:
            assert eng.cfg.quant_weights and eng.cfg.quant_kv
            out = eng.generate([1, 2, 3], max_new_tokens=3)
            assert len(out) == 3
        finally:
            eng.stop()

    def test_prefix_cache_still_works_int8(self):
        """The prefix-admit copy path must handle the int8+scale cache
        leaves (slot-axis copy over every leaf kind)."""
        cfg, params = _tiny_with_params()
        qcfg, qp = llamalib.quantize_for_serving(cfg, params)
        eng = ContinuousEngine(qcfg, qp, num_slots=2, decode_chunk=1,
                               eos_id=None, prefix_cache=True, min_prefix=4)
        try:
            base = [7, 3, 5, 2, 9, 4, 8, 6]
            first = eng.generate(base, max_new_tokens=3)
            again = eng.generate(base, max_new_tokens=3)
            assert eng.prefix_hits >= 1
            assert first == again
        finally:
            eng.stop()


class TestQuantHbmEconomy:
    def test_cache_bytes_halve(self):
        """The capacity claim, on the actual pool tree: int8 pool tensor
        bytes are half the bf16 pool's (scales add <7% back)."""
        from kubeflow_tpu.serving.continuous import cache_shapes

        # real head_dim: the per-(pos, head) f32 scale adds only
        # 4/(2*128) = 1.6% of the bf16 bill back
        cfg = llamalib.tiny(dtype=jnp.bfloat16, head_dim=128)
        qcfg = dataclasses.replace(cfg, quant_kv=True)

        def nbytes(c):
            return sum(
                int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
                for s in jax.tree.leaves(cache_shapes(c, 8)))

        dense, quant = nbytes(cfg), nbytes(qcfg)
        assert quant < 0.53 * dense, (quant, dense)
