"""InferenceGraph: sequence/switch DAG routing over InferenceServices
(SURVEY §2.2 InferenceGraph row — r1 verdict missing #7)."""

import json
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.api.inference import (
    ComponentSpec,
    GraphNode,
    GraphStep,
    InferenceGraph,
    InferenceGraphSpec,
    InferenceService,
    InferenceServicePhase,
    InferenceServiceSpec,
    ModelFormat,
    ServingRuntime,
    ServingRuntimeSpec,
    SupportedModelFormat,
)
from kubeflow_tpu.serving.graph import eval_condition
from kubeflow_tpu.serving.model import Model


class AddOneModel(Model):
    def predict_batch(self, instances):
        return [x + 1 for x in instances]


class DoubleModel(Model):
    def predict_batch(self, instances):
        return [x * 2 for x in instances]


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _isvc(name, fmt):
    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(
            predictor=ComponentSpec(model_format=ModelFormat(name=fmt))),
    )


@pytest.fixture()
def graph_cluster():
    from kubeflow_tpu.controlplane.cluster import Cluster

    cluster = Cluster()
    cluster.add_tpu_slice("slice-0", 1, 4)
    cluster.enable_serving()
    for fmt, cls in (("addone", "AddOneModel"), ("double", "DoubleModel")):
        cluster.store.create(
            ServingRuntime(
                metadata=ObjectMeta(name=f"rt-{fmt}"),
                spec=ServingRuntimeSpec(
                    supported_model_formats=[SupportedModelFormat(name=fmt)],
                    server_class=f"tests.test_inference_graph:{cls}",
                ),
            )
        )
    with cluster:
        cluster.store.create(_isvc("inc", "addone"))
        cluster.store.create(_isvc("dbl", "double"))
        yield cluster


def _wait_phase(cluster, kind, name, phase=InferenceServicePhase.READY, timeout=30):
    deadline = time.time() + timeout
    obj = None
    while time.time() < deadline:
        obj = cluster.store.try_get(kind, name)
        if obj is not None and obj.status.phase == phase:
            return obj
        time.sleep(0.05)
    raise AssertionError(f"{kind} {name} never {phase}: {obj.status if obj else None}")


class TestConditions:
    def test_eval_condition_forms(self):
        assert eval_condition("model == a", {"model": "a"})
        assert not eval_condition("model == a", {"model": "b"})
        assert eval_condition("x > 3", {"x": 5})
        assert eval_condition("x != 3", {"x": 5})
        assert not eval_condition("missing == 1", {})


class TestGraphE2E:
    def test_sequence_chains_two_services(self, graph_cluster):
        """Two-stage transformer->predictor graph through the router:
        (x + 1) * 2."""
        graph_cluster.store.create(
            InferenceGraph(
                metadata=ObjectMeta(name="chain"),
                spec=InferenceGraphSpec(nodes={
                    "root": GraphNode(router_type="Sequence", steps=[
                        GraphStep(service_name="inc"),
                        GraphStep(service_name="dbl"),
                    ]),
                }),
            )
        )
        g = _wait_phase(graph_cluster, "InferenceGraph", "chain")
        code, out = _post(g.status.url, {"instances": [1, 2, 3]})
        assert code == 200 and out["predictions"] == [4, 6, 8]

    def test_switch_routes_by_condition(self, graph_cluster):
        graph_cluster.store.create(
            InferenceGraph(
                metadata=ObjectMeta(name="switch"),
                spec=InferenceGraphSpec(nodes={
                    "root": GraphNode(router_type="Switch", steps=[
                        GraphStep(service_name="inc", condition="op == inc"),
                        GraphStep(service_name="dbl", condition="op == dbl"),
                    ]),
                }),
            )
        )
        g = _wait_phase(graph_cluster, "InferenceGraph", "switch")
        code, out = _post(g.status.url, {"op": "inc", "instances": [10]})
        assert code == 200 and out["predictions"] == [11]
        code, out = _post(g.status.url, {"op": "dbl", "instances": [10]})
        assert code == 200 and out["predictions"] == [20]
        code, out = _post(g.status.url, {"op": "nope", "instances": [10]})
        assert code == 404

    def test_nested_node_and_request_data(self, graph_cluster):
        """A sequence step can target another node; $request resets input."""
        graph_cluster.store.create(
            InferenceGraph(
                metadata=ObjectMeta(name="nested"),
                spec=InferenceGraphSpec(nodes={
                    "root": GraphNode(router_type="Sequence", steps=[
                        GraphStep(node_name="double-twice"),
                        # ignores the previous output, re-feeds the original
                        GraphStep(service_name="inc", data="$request"),
                    ]),
                    "double-twice": GraphNode(router_type="Sequence", steps=[
                        GraphStep(service_name="dbl"),
                        GraphStep(service_name="dbl"),
                    ]),
                }),
            )
        )
        g = _wait_phase(graph_cluster, "InferenceGraph", "nested")
        code, out = _post(g.status.url, {"instances": [5]})
        # root: double-twice(5)=20 discarded; inc($request 5) = 6
        assert code == 200 and out["predictions"] == [6]

    def test_missing_root_fails(self, graph_cluster):
        graph_cluster.store.create(
            InferenceGraph(
                metadata=ObjectMeta(name="broken"),
                spec=InferenceGraphSpec(nodes={
                    "notroot": GraphNode(steps=[GraphStep(service_name="inc")]),
                }),
            )
        )
        g = _wait_phase(
            graph_cluster, "InferenceGraph", "broken",
            phase=InferenceServicePhase.FAILED)
        assert "root" in g.status.message

    def test_waits_for_missing_service(self, graph_cluster):
        graph_cluster.store.create(
            InferenceGraph(
                metadata=ObjectMeta(name="waiting"),
                spec=InferenceGraphSpec(nodes={
                    "root": GraphNode(steps=[GraphStep(service_name="ghost")]),
                }),
            )
        )
        g = _wait_phase(
            graph_cluster, "InferenceGraph", "waiting",
            phase=InferenceServicePhase.LOADING)
        assert "ghost" in g.status.message
        # request through the router while not ready -> 503
        code, out = _post(g.status.url, {"instances": [1]})
        assert code == 503


class TestEnsembleAndSplitter:
    def test_ensemble_merges_parallel_outputs(self, graph_cluster):
        graph_cluster.store.create(InferenceGraph(
            metadata=ObjectMeta(name="ens"),
            spec=InferenceGraphSpec(nodes={
                "root": GraphNode(router_type="Ensemble", steps=[
                    GraphStep(service_name="inc"),
                    GraphStep(service_name="dbl"),
                ])})))
        g = _wait_phase(graph_cluster, "InferenceGraph", "ens")
        code, out = _post(g.status.url, {"instances": [3, 4]})
        assert code == 200
        assert out["inc"]["predictions"] == [4, 5]
        assert out["dbl"]["predictions"] == [6, 8]

    def test_splitter_routes_by_weight(self, graph_cluster):
        # all weight on "dbl": deterministic despite the random draw
        graph_cluster.store.create(InferenceGraph(
            metadata=ObjectMeta(name="split"),
            spec=InferenceGraphSpec(nodes={
                "root": GraphNode(router_type="Splitter", steps=[
                    GraphStep(service_name="inc", weight=0),
                    GraphStep(service_name="dbl", weight=100),
                ])})))
        g = _wait_phase(graph_cluster, "InferenceGraph", "split")
        for _ in range(5):
            code, out = _post(g.status.url, {"instances": [3]})
            assert code == 200 and out["predictions"] == [6]

    def test_unknown_router_type_500(self, graph_cluster):
        graph_cluster.store.create(InferenceGraph(
            metadata=ObjectMeta(name="bad"),
            spec=InferenceGraphSpec(nodes={
                "root": GraphNode(router_type="Mystery", steps=[
                    GraphStep(service_name="inc")])})))
        g = _wait_phase(graph_cluster, "InferenceGraph", "bad")
        code, out = _post(g.status.url, {"instances": [1]})
        assert code == 500 and "Mystery" in out["error"]
