"""Trainer: sharded init, loss descent, checkpoint round-trip, reshape-restore.

The checkpoint/resume tier the reference lacks (SURVEY.md §5): resume must
work across topology changes, because TPU elasticity = checkpoint-restart
reshape (Tenplex pattern).
"""

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama
from kubeflow_tpu.train import data as datalib
from kubeflow_tpu.train import trainer as trainlib


def _cfg(tmp=None, **kw):
    base = dict(
        model=llama.tiny(),
        mesh_axes={"data": 2, "fsdp": 2, "model": 2},
        global_batch=8,
        seq_len=32,
        steps=6,
        warmup_steps=2,
        log_every=2,
        checkpoint_dir=tmp,
    )
    base.update(kw)
    return trainlib.TrainConfig(**base)


def test_loss_decreases():
    t = trainlib.Trainer(_cfg(steps=30, learning_rate=1e-2))
    seen = []
    t.train(on_metrics=lambda m: seen.append(m))
    assert seen[-1].step == 30
    assert seen[-1].loss < seen[0].loss
    assert seen[-1].tokens_per_sec > 0


def test_state_is_sharded():
    t = trainlib.Trainer(_cfg())
    state = t.init_state()
    wq = state["params"]["layers"]["block"]["attn"]["wq"]["kernel"]
    # fsdp shards embed dim, model shards heads dim
    assert not wq.sharding.is_fully_replicated


def test_data_independent_of_world_size():
    a = datalib.SyntheticLm(8, 16, 256, process_index=0, process_count=1)
    full = a.local_batch(3)["tokens"]
    parts = [
        datalib.SyntheticLm(8, 16, 256, process_index=p, process_count=4).local_batch(3)["tokens"]
        for p in range(4)
    ]
    np.testing.assert_array_equal(full, np.concatenate(parts, axis=0))


def test_checkpoint_resume_same_mesh(tmp_ckpt_dir):
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    t.train()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    state = t2.restore_or_init()
    assert int(jax.device_get(state["step"])) == 4


def test_final_save_when_interval_divides_steps(tmp_ckpt_dir):
    """Caught regression: orbax refuses to overwrite an existing step, so
    the forced final save must skip when the loop already wrote it — and a
    re-run of a completed job must not crash either."""
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4, save_interval_steps=2))
    t.train()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4, save_interval_steps=2))
    t2.train()  # resumes at step 4 == steps: zero-step run, no crash
    assert t2.ckpt.latest_step() == 4


def test_resume_continues_data_stream(tmp_ckpt_dir):
    """Caught regression: a resumed run must consume batches for steps
    [start, steps), not replay [0, steps-start)."""
    seen = []

    class Spy(datalib.SyntheticLm):
        def local_batch(self, step):
            seen.append(step)
            return super().local_batch(step)

    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=2))
    t.train(source=Spy(8, 32, 256, process_index=0, process_count=1))
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    seen.clear()
    t2.train(source=Spy(8, 32, 256, process_index=0, process_count=1))
    assert seen == [2, 3]


def test_reshape_restore_across_meshes(tmp_ckpt_dir):
    """Save on a 2x2x2 dp/fsdp/model mesh, restore onto 8-way pure DP and
    continue training — the elasticity contract."""
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=3))
    t.train()
    saved = t.restore_or_init()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=5, mesh_axes={"data": 8}))
    restored = t2.restore_or_init()
    np.testing.assert_allclose(
        np.asarray(jax.device_get(saved["params"]["head"]["final_norm"]["scale"])),
        np.asarray(jax.device_get(restored["params"]["head"]["final_norm"]["scale"])),
    )
    out = t2.train()
    assert out.step == 5
