"""Trainer: sharded init, loss descent, checkpoint round-trip, reshape-restore.

The checkpoint/resume tier the reference lacks (SURVEY.md §5): resume must
work across topology changes, because TPU elasticity = checkpoint-restart
reshape (Tenplex pattern).
"""

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama
from kubeflow_tpu.train import data as datalib
from kubeflow_tpu.train import trainer as trainlib


def _cfg(tmp=None, **kw):
    base = dict(
        model=llama.tiny(),
        mesh_axes={"data": 2, "fsdp": 2, "model": 2},
        global_batch=8,
        seq_len=32,
        steps=6,
        warmup_steps=2,
        log_every=2,
        checkpoint_dir=tmp,
    )
    base.update(kw)
    return trainlib.TrainConfig(**base)


def test_loss_decreases():
    t = trainlib.Trainer(_cfg(steps=30, learning_rate=1e-2))
    seen = []
    t.train(on_metrics=lambda m: seen.append(m))
    assert seen[-1].step == 30
    assert seen[-1].loss < seen[0].loss
    assert seen[-1].tokens_per_sec > 0


def test_state_is_sharded():
    t = trainlib.Trainer(_cfg())
    state = t.init_state()
    wq = state["params"]["layers"]["block"]["attn"]["wq"]["kernel"]
    # fsdp shards embed dim, model shards heads dim
    assert not wq.sharding.is_fully_replicated


def test_data_independent_of_world_size():
    a = datalib.SyntheticLm(8, 16, 256, process_index=0, process_count=1)
    full = a.local_batch(3)["tokens"]
    parts = [
        datalib.SyntheticLm(8, 16, 256, process_index=p, process_count=4).local_batch(3)["tokens"]
        for p in range(4)
    ]
    np.testing.assert_array_equal(full, np.concatenate(parts, axis=0))


def test_checkpoint_resume_same_mesh(tmp_ckpt_dir):
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    t.train()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    state = t2.restore_or_init()
    assert int(jax.device_get(state["step"])) == 4


def test_final_save_when_interval_divides_steps(tmp_ckpt_dir):
    """Caught regression: orbax refuses to overwrite an existing step, so
    the forced final save must skip when the loop already wrote it — and a
    re-run of a completed job must not crash either."""
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4, save_interval_steps=2))
    t.train()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4, save_interval_steps=2))
    t2.train()  # resumes at step 4 == steps: zero-step run, no crash
    assert t2.ckpt.latest_step() == 4


def test_resume_continues_data_stream(tmp_ckpt_dir):
    """Caught regression: a resumed run must consume batches for steps
    [start, steps), not replay [0, steps-start)."""
    seen = []

    class Spy(datalib.SyntheticLm):
        def local_batch(self, step):
            seen.append(step)
            return super().local_batch(step)

    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=2))
    t.train(source=Spy(8, 32, 256, process_index=0, process_count=1))
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=4))
    seen.clear()
    t2.train(source=Spy(8, 32, 256, process_index=0, process_count=1))
    assert seen == [2, 3]


def test_reshape_restore_across_meshes(tmp_ckpt_dir):
    """Save on a 2x2x2 dp/fsdp/model mesh, restore onto 8-way pure DP and
    continue training — the elasticity contract."""
    t = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=3))
    t.train()
    saved = t.restore_or_init()
    t2 = trainlib.Trainer(_cfg(tmp_ckpt_dir, steps=5, mesh_axes={"data": 8}))
    restored = t2.restore_or_init()
    np.testing.assert_allclose(
        np.asarray(jax.device_get(saved["params"]["head"]["final_norm"]["scale"])),
        np.asarray(jax.device_get(restored["params"]["head"]["final_norm"]["scale"])),
    )
    out = t2.train()
    assert out.step == 5


class TestGradAccumulation:
    """accum_steps splits the batch into scanned microbatches; grads and
    loss must match the unaccumulated step at equal effective batch."""

    def test_loss_and_grads_match_unaccumulated(self):
        t1 = trainlib.Trainer(_cfg(accum_steps=1, global_batch=16))
        t4 = trainlib.Trainer(_cfg(accum_steps=4, global_batch=16))
        state = t1.init_state(seed=0)
        batch = datalib.SyntheticLm(16, 32, 256).local_batch(0)
        tokens = jax.device_put(batch["tokens"], t1.batch_sharding)
        loss1, g1 = jax.jit(t1._grads_fn)(state["params"], tokens)
        loss4, g4 = jax.jit(t4._grads_fn)(state["params"], tokens)
        np.testing.assert_allclose(float(loss1), float(loss4), rtol=1e-5)
        flat1 = jax.tree.leaves(g1)
        flat4 = jax.tree.leaves(g4)
        for a, b in zip(flat1, flat4):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)

    def test_indivisible_accum_rejected(self):
        t = trainlib.Trainer(_cfg(accum_steps=3))
        state = t.init_state(seed=0)
        batch = datalib.SyntheticLm(8, 32, 256).local_batch(0)
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(t._grads_fn)(state["params"], batch["tokens"])

    def test_microbatch_must_tile_batch_shards(self):
        # batch 8 over 4 batch shards: accum 4 -> 2-row microbatches, which
        # cannot tile the shards; must be rejected, not silently mis-sharded
        t = trainlib.Trainer(_cfg(accum_steps=4))
        state = t.init_state(seed=0)
        batch = datalib.SyntheticLm(8, 32, 256).local_batch(0)
        with pytest.raises(ValueError, match="batch shards"):
            jax.jit(t._grads_fn)(state["params"], batch["tokens"])

    def test_training_descends_with_accumulation(self):
        t = trainlib.Trainer(_cfg(steps=20, learning_rate=1e-2, accum_steps=2))
        seen = []
        t.train(on_metrics=lambda m: seen.append(m))
        assert seen[-1].loss < seen[0].loss


class TestMoeAuxLoss:
    """The Switch load-balancing loss must reach the objective (round-2
    verdict weak #1: sown but never consumed = balancing no-op)."""

    def _moe_trainer(self, coef):
        return trainlib.Trainer(_cfg(
            model=llama.tiny(moe_experts=4, moe_top_k=1,
                             moe_capacity_factor=2.0),
            mesh_axes={"data": 8},
            steps=40, learning_rate=5e-3, aux_loss_coef=coef))

    def _eval_aux(self, t, state, tokens):
        _, mut = t.model.apply(
            {"params": state["params"]}, tokens, mutable=["intermediates"])
        total, count = trainlib._sum_aux_losses(mut["intermediates"])
        return float(total) / count

    def test_aux_loss_added_to_objective(self):
        t = self._moe_trainer(coef=1.0)
        t0 = self._moe_trainer(coef=0.0)
        state = t.init_state(seed=0)
        tokens = datalib.SyntheticLm(8, 32, 256).local_batch(0)["tokens"]
        with_aux = float(jax.jit(t._loss_fn)(state["params"], tokens))
        without = float(jax.jit(t0._loss_fn)(state["params"], tokens))
        aux = self._eval_aux(t, state, tokens[:, :-1])
        np.testing.assert_allclose(with_aux - without, aux, rtol=1e-3)

    def test_training_moves_expert_balance(self):
        """On a narrow-vocab corpus (8 distinct tokens -> 8 fixed embedding
        vectors) routing is structurally imbalanced at init; training with
        aux_loss_coef>0 drives the Switch aux metric to ~1 (balance), while
        coef=0 leaves the imbalance in place."""
        def batch(i):
            r = np.random.RandomState(1000 + i)
            return jax.numpy.asarray(r.randint(0, 8, size=(8, 33)), "int32")

        eval_tokens = batch(999)[:, :32]

        def train(coef):
            t = self._moe_trainer(coef)
            state = t.init_state(seed=0)
            step_fn = t.compiled_step()
            for i in range(t.cfg.steps):
                state, _ = step_fn(state, {"tokens": batch(i)})
            return self._eval_aux(t, state, eval_tokens)

        aux_balanced = train(coef=1.0)
        aux_free = train(coef=0.0)
        assert aux_balanced < 1.08          # ~1.0 == uniform routing
        assert aux_free > aux_balanced + 0.1


class TestTrainerKnobs:
    """Optimizer choice + remat policy (the levers behind the 1B single-chip
    and 7B AOT configs; PERF.md / BASELINE.md)."""

    def test_adafactor_trains(self):
        t = trainlib.Trainer(_cfg(steps=20, learning_rate=1e-2,
                                  optimizer="adafactor"))
        seen = []
        t.train(on_metrics=lambda m: seen.append(m))
        assert seen[-1].loss < seen[0].loss

    def test_unknown_optimizer_rejected(self):
        with pytest.raises(ValueError, match="unknown optimizer"):
            trainlib.Trainer(_cfg(optimizer="sgd"))

    def test_remat_policy_nothing_matches_dots(self):
        """Remat policy changes memory, never math: losses identical."""
        model_a = llama.tiny(remat=True, remat_policy="dots")
        model_b = llama.tiny(remat=True, remat_policy="nothing")
        ta = trainlib.Trainer(_cfg(model=model_a))
        tb = trainlib.Trainer(_cfg(model=model_b))
        state = ta.init_state(seed=0)
        tokens = datalib.SyntheticLm(8, 32, 256).local_batch(0)["tokens"]
        la, ga = jax.jit(ta._grads_fn)(state["params"], tokens)
        lb, gb = jax.jit(tb._grads_fn)(state["params"], tokens)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4)

    def test_llama_1b_preset_shape(self):
        cfg = llama.llama_1b()
        n = llama.num_params(cfg)
        assert 1.15e9 < n < 1.25e9
        assert cfg.remat_policy == "nothing"
        assert cfg.attention_impl == "flash"
