"""Serving plane: protocols, batching, runtimes, controller, autoscale.

Mirrors KServe's python test approach (SURVEY.md §4: HTTP client against an
in-process server) plus controller tests on the fake cluster.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.api.inference import (
    ComponentSpec,
    InferenceService,
    InferenceServicePhase,
    InferenceServiceSpec,
    ModelFormat,
)
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving import (
    EchoModel,
    MicroBatcher,
    Model,
    ModelServer,
    register_mem,
)
from kubeflow_tpu.serving.storage import StorageError, download


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


class Doubler(Model):
    def predict_batch(self, instances):
        return [2 * float(x) for x in instances]


class BatchSpy(Model):
    def __init__(self, name, config=None):
        super().__init__(name, config)
        self.batch_sizes = []

    def predict_batch(self, instances):
        self.batch_sizes.append(len(instances))
        time.sleep(0.01)
        return list(instances)


class TestModelServer:
    @pytest.fixture()
    def server(self):
        s = ModelServer()
        s.register(Doubler("double"))
        s.start()
        yield s
        s.stop()

    def test_v1_predict(self, server):
        code, out = _post(f"{server.url}/v1/models/double:predict",
                          {"instances": [1, 2, 3]})
        assert code == 200 and out == {"predictions": [2.0, 4.0, 6.0]}

    def test_v1_model_status_and_health(self, server):
        code, body = _get(f"{server.url}/v1/models/double")
        assert code == 200 and json.loads(body)["ready"] is True
        code, _ = _get(f"{server.url}/v2/health/ready")
        assert code == 200

    def test_v2_infer(self, server):
        code, out = _post(
            f"{server.url}/v2/models/double/infer",
            {"inputs": [{"name": "x", "shape": [3], "datatype": "FP32",
                         "data": [1, 2, 3]}]})
        assert code == 200
        assert out["outputs"][0]["data"] == [2.0, 4.0, 6.0]

    def test_v2_metadata(self, server):
        code, body = _get(f"{server.url}/v2/models/double")
        meta = json.loads(body)
        assert code == 200 and meta["platform"] == "kubeflow-tpu-jax"

    def test_unknown_model_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{server.url}/v1/models/nope:predict", {"instances": [1]})
        assert e.value.code == 404

    def test_model_error_500(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{server.url}/v1/models/double:predict",
                  {"instances": ["not-a-number"]})
        assert e.value.code == 500

    def test_metrics_endpoint(self, server):
        _post(f"{server.url}/v1/models/double:predict", {"instances": [1]})
        code, body = _get(f"{server.url}/metrics")
        assert code == 200 and 'kft_request_count{model="double"} ' in body

    def test_dynamic_unload(self, server):
        server.unregister("double")
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(f"{server.url}/v1/models/double:predict", {"instances": [1]})
        assert e.value.code == 404


class TestMicroBatcher:
    def test_concurrent_requests_coalesce(self):
        spy = BatchSpy("spy")
        spy.start()
        b = MicroBatcher(spy, max_size=8, timeout_ms=50.0)
        results = [None] * 8
        threads = [
            threading.Thread(target=lambda i=i: results.__setitem__(
                i, b.submit([i])))
            for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        b.stop()
        assert sorted(r[0] for r in results) == list(range(8))
        # at least one multi-request batch formed
        assert max(spy.batch_sizes) > 1


class TestJaxRuntime:
    def test_jax_function_model_buckets(self):
        w = jnp.asarray([[2.0]])

        def fn(params, x):
            return x @ params

        ref = register_mem("linmodel", (fn, w))
        from kubeflow_tpu.serving.runtimes import JaxFunctionModel

        m = JaxFunctionModel("lin", {"fn_ref": ref, "buckets": (2, 4)})
        m.start()
        out = m.predict_batch([[1.0], [2.0], [3.0]])  # pads 3 -> bucket 4
        assert np.allclose(np.asarray(out).ravel(), [2.0, 4.0, 6.0])

    def test_llama_generator_mixed_lengths(self):
        """Caught regression: mixed-length prompts must not be padded into a
        shared cache; each prompt's continuation must equal its solo run."""
        cfg = llamalib.tiny()
        model = llamalib.Llama(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
        ref = register_mem("tinyllama-mixed", (cfg, params["params"]))
        from kubeflow_tpu.serving.runtimes import LlamaGenerator

        g = LlamaGenerator("gen", {"params_ref": ref, "max_new_tokens": 3})
        g.start()
        mixed = g.predict_batch([[1, 2, 3], [4, 5, 6, 7, 8]])
        solo_a = g.predict_batch([[1, 2, 3]])[0]
        solo_b = g.predict_batch([[4, 5, 6, 7, 8]])[0]
        assert mixed[0] == solo_a and mixed[1] == solo_b

    def test_llama_generator_greedy(self):
        cfg = llamalib.tiny()
        model = llamalib.Llama(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
        ref = register_mem("tinyllama", (cfg, params["params"]))
        from kubeflow_tpu.serving.runtimes import LlamaGenerator

        g = LlamaGenerator("gen", {"params_ref": ref, "max_new_tokens": 4})
        g.start()
        out = g.predict_batch([[1, 2, 3], [4, 5, 6]])
        assert len(out) == 2 and all(len(o) == 4 for o in out)
        assert all(0 <= t < cfg.vocab_size for o in out for t in o)
        # greedy decode must agree with argmax over the full forward
        logits = model.apply(params, jnp.asarray([[1, 2, 3]], jnp.int32))
        expected_first = int(jnp.argmax(logits[0, -1]))
        assert out[0][0] == expected_first


class TestStorage:
    def test_file_scheme(self, tmp_path):
        p = tmp_path / "weights.bin"
        p.write_bytes(b"x")
        assert download(f"file://{p}") == str(p)

    def test_remote_schemes_gated(self):
        with pytest.raises(StorageError, match="egress"):
            download("gs://bucket/model")

    def test_unknown_scheme(self):
        with pytest.raises(StorageError):
            download("ftp://nope")

    def test_cache_stage_and_hit(self, tmp_path):
        from kubeflow_tpu.serving.storage import list_cache, verify_manifest

        src = tmp_path / "model"
        src.mkdir()
        (src / "weights.bin").write_bytes(b"W" * 1024)
        (src / "config.json").write_text('{"d": 1}')
        cache = tmp_path / "cache"
        uri = f"file://{src}"

        staged = download(uri, cache_dir=str(cache))
        assert staged != str(src) and (
            (tmp_path / "cache") in __import__("pathlib").Path(staged).parents)
        assert (set(os.listdir(staged)) == {"weights.bin", "config.json"})
        # second download: manifest-verified hit, same path, no re-stage
        mtime = os.path.getmtime(os.path.join(staged, "weights.bin"))
        assert download(uri, cache_dir=str(cache)) == staged
        assert os.path.getmtime(os.path.join(staged, "weights.bin")) == mtime
        entries = list_cache(str(cache))
        assert len(entries) == 1 and entries[0]["valid"]
        assert {f["path"] for f in entries[0]["files"]} == {
            "weights.bin", "config.json"}

    def test_cache_corruption_restaged(self, tmp_path):
        src = tmp_path / "w.bin"
        src.write_bytes(b"GOOD")
        cache = tmp_path / "cache"
        uri = f"file://{src}"
        staged = download(uri, cache_dir=str(cache))
        staged_file = staged if os.path.isfile(staged) else os.path.join(staged, "w.bin")
        with open(staged_file, "wb") as f:
            f.write(b"EVIL")  # same size, wrong sha256
        restaged = download(uri, cache_dir=str(cache))
        refile = restaged if os.path.isfile(restaged) else os.path.join(restaged, "w.bin")
        assert open(refile, "rb").read() == b"GOOD"


def _isvc(name="svc", **pred):
    defaults = dict(model_format=ModelFormat(name="echo"), min_replicas=1,
                    max_replicas=2)
    defaults.update(pred)
    return InferenceService(
        metadata=ObjectMeta(name=name),
        spec=InferenceServiceSpec(predictor=ComponentSpec(**defaults)),
    )


@pytest.fixture()
def serving_cluster():
    from kubeflow_tpu.controlplane.cluster import Cluster

    cluster = Cluster()
    cluster.add_tpu_slice("slice-0", 1, 4)
    cluster.enable_serving()
    with cluster:
        yield cluster


def _wait_ready(cluster, name, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        isvc = cluster.store.try_get("InferenceService", name)
        if isvc is not None and isvc.status.phase == InferenceServicePhase.READY:
            return isvc
        time.sleep(0.05)
    raise AssertionError(f"{name} never became Ready: {isvc.status if isvc else None}")


class TestInferenceServiceController:
    def test_isvc_to_first_prediction(self, serving_cluster):
        """SURVEY.md §3.3: apply InferenceService -> runtime auto-selected ->
        Ready -> prediction through the routed URL."""
        serving_cluster.store.create(_isvc())
        isvc = _wait_ready(serving_cluster, "svc")
        code, out = _post(f"{isvc.status.url}/v1/models/svc:predict",
                          {"instances": [1, 2]})
        assert code == 200 and out["predictions"] == [1, 2]

    def test_unknown_format_fails(self, serving_cluster):
        serving_cluster.store.create(
            _isvc(name="bad", model_format=ModelFormat(name="mystery")))
        deadline = time.time() + 10
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "bad")
            if isvc is not None and isvc.status.phase == InferenceServicePhase.FAILED:
                assert "mystery" in isvc.status.message
                return
            time.sleep(0.05)
        raise AssertionError("never reached Failed")

    def test_scale_to_zero_and_activate(self, serving_cluster):
        serving_cluster.store.create(_isvc(name="zero", min_replicas=0))
        isvc = _wait_ready(serving_cluster, "zero")
        # idle window passes -> scaled to zero
        deadline = time.time() + 15
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "zero")
            if isvc.status.active_replicas == 0:
                break
            time.sleep(0.1)
        assert isvc.status.active_replicas == 0
        # activator path: request wakes a replica
        code, out = _post(f"{isvc.status.url}/v1/models/zero:predict",
                          {"instances": [7]}, timeout=30)
        assert code == 200 and out["predictions"] == [7]

    def test_delete_tears_down(self, serving_cluster):
        serving_cluster.store.create(_isvc(name="gone"))
        isvc = _wait_ready(serving_cluster, "gone")
        url = isvc.status.url
        serving_cluster.store.try_delete("InferenceService", "gone")
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                _post(f"{url}/v1/models/gone:predict", {"instances": [1]},
                      timeout=2)
            except (urllib.error.URLError, ConnectionError, OSError):
                return
            time.sleep(0.1)
        raise AssertionError("router still serving after delete")


class FirstTwoSum(Model):
    """Score = x[0] + x[1]; features 2+ are irrelevant (explainer ground
    truth: occluding segment 0/1 drops the score by exactly that feature)."""

    def predict_batch(self, instances):
        return [float(x[0]) + float(x[1]) for x in instances]


class TestExplainer:
    def test_explain_verb_and_attributions(self, serving_cluster):
        """KServe explainer parity: the ``:explain`` verb routes to the
        explainer component, which scores occlusions through the predictor."""
        serving_cluster.store.create(InferenceService(
            metadata=ObjectMeta(name="exp"),
            spec=InferenceServiceSpec(
                predictor=ComponentSpec(handler="tests.test_serving:FirstTwoSum"),
                explainer=ComponentSpec(
                    handler="kubeflow_tpu.serving.explainer:OcclusionExplainer",
                    config={"num_segments": 4}),
            )))
        isvc = _wait_ready(serving_cluster, "exp")
        code, out = _post(f"{isvc.status.url}/v1/models/exp:explain",
                          {"instances": [[3.0, 5.0, 1.0, 2.0]]})
        assert code == 200
        e = out["explanations"][0]
        assert e["base_score"] == 8.0
        # informative features carry exactly their contribution; dead ones zero
        assert e["attributions"] == [3.0, 5.0, 0.0, 0.0]
        # ``:predict`` still reaches the predictor tier through the same URL
        code, out = _post(f"{isvc.status.url}/v1/models/exp:predict",
                          {"instances": [[1.0, 2.0, 9.0, 9.0]]})
        assert code == 200 and out["predictions"] == [3.0]


class TestGrpcV2:
    def test_v2_grpc_round_trip(self):
        """The V2 protocol's second wire format: gRPC ModelInfer through the
        same model repository + micro-batcher as HTTP."""
        import grpc

        from kubeflow_tpu.serving.grpc_server import GrpcInferenceClient

        server = ModelServer()
        server.register(Doubler("double"))
        server.start()
        addr = server.enable_grpc()  # kserve's grpc_port analog
        try:
            client = GrpcInferenceClient(addr)
            assert client.server_live()
            assert client.model_ready("double")
            assert client.model_metadata("double")["platform"] == "kubeflow-tpu-jax"
            assert client.infer("double", [1, 2, 3]) == [2.0, 4.0, 6.0]
            with pytest.raises(grpc.RpcError):
                client.infer("nope", [1])
            client.close()
        finally:
            server.stop()  # stops the gRPC front too


class TestLlamaGeneratorRagged:
    def _gen(self, **cfg_kw):
        cfg = llamalib.tiny()
        model = llamalib.Llama(cfg)
        params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
        ref = register_mem(f"tinyllama-ragged-{len(cfg_kw)}", (cfg, params["params"]))
        from kubeflow_tpu.serving.runtimes import LlamaGenerator

        g = LlamaGenerator("gen", {"params_ref": ref, "max_new_tokens": 3, **cfg_kw})
        g.start()
        return g, cfg

    def test_overlong_prompt_truncates_not_raises(self):
        """One client's oversize prompt must not 500 the co-batched
        requests: left-truncation keeps the conditioning tail."""
        g, cfg = self._gen()
        cap = g.seq_buckets[-1]
        long_prompt = list(range(1, cap + 40))
        out = g.predict_batch([long_prompt, [5, 6, 7]])
        assert len(out) == 2 and all(len(o) == 3 for o in out)
        # truncated prompt behaves exactly like its tail
        solo = g.predict_batch([long_prompt[-cap:]])[0]
        assert out[0] == solo

    def test_temperature_varies_across_requests(self):
        g, _ = self._gen(temperature=1.5)
        a = g.predict_batch([[1, 2, 3]])[0]
        outs = {tuple(g.predict_batch([[1, 2, 3]])[0]) for _ in range(6)}
        assert len(outs) > 1  # a fixed key made every continuation identical

    def test_bad_bucket_config_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError, match="no usable seq bucket"):
            self._gen(seq_buckets=(100000,))

    def test_weights_dtype_serving_cast(self):
        """Opt-in bf16 serving weights (decode is HBM-bound on weight
        reads); outputs stay valid token ids of the right shape."""
        g, cfg = self._gen(weights_dtype="bfloat16")
        leaf = jax.tree_util.tree_leaves(g.params)[0]
        assert leaf.dtype == jnp.bfloat16
        out = g.predict_batch([[1, 2, 3], [4, 5]])
        assert all(len(o) == 3 for o in out)
        assert all(0 <= t < cfg.vocab_size for o in out for t in o)

    def test_empty_prompt_isolated_and_empty_output(self):
        """An empty prompt neither fails the co-batched requests nor
        fabricates a continuation: it returns []."""
        g, _ = self._gen()
        out = g.predict_batch([[], [5, 6, 7]])
        assert out[0] == []
        assert len(out[1]) == 3
        solo = g.predict_batch([[5, 6, 7]])[0]
        assert out[1] == solo
        # all-empty batch short-circuits without any device dispatch
        assert g.predict_batch([[], []]) == [[], []]


class VersionTagModel(Model):
    """Replies with its configured tag — lets canary tests count which
    revision served each request."""

    def predict_batch(self, instances):
        return [self.config["tag"]] * len(instances)


class TestCanaryRollout:
    """KServe canaryTrafficPercent parity (VERDICT r2 missing #3): roll a
    spec change out to p% of traffic, observe the split, promote, old
    revision drains; or roll back."""

    def _tag_isvc(self, name, tag):
        return InferenceService(
            metadata=ObjectMeta(name=name),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler="tests.test_serving:VersionTagModel",
                config={"tag": tag}, min_replicas=1, max_replicas=2)),
        )

    def _counts(self, url, name, n=50):
        got = {}
        for _ in range(n):
            code, out = _post(f"{url}/v1/models/{name}:predict",
                              {"instances": [0]})
            assert code == 200
            tag = out["predictions"][0]
            got[tag] = got.get(tag, 0) + 1
        return got

    def test_canary_split_promote(self, serving_cluster):
        from kubeflow_tpu.sdk.kserve import KServeClient

        client = KServeClient(serving_cluster)
        serving_cluster.store.create(self._tag_isvc("roll", "v1"))
        isvc = _wait_ready(serving_cluster, "roll")
        assert self._counts(isvc.status.url, "roll", 10) == {"v1": 10}
        assert isvc.status.stable_revision == 1

        # roll v2 at 20%
        client.rollout(
            "roll",
            {"predictor": {"handler": "tests.test_serving:VersionTagModel",
                           "config": {"tag": "v2"}, "min_replicas": 1,
                           "max_replicas": 2}},
            traffic_percent=20)
        deadline = time.time() + 15
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "roll")
            if (isvc.status.canary_revision is not None
                    and isvc.status.phase == InferenceServicePhase.READY):
                break
            time.sleep(0.05)
        assert isvc.status.canary_revision == 2
        assert isvc.status.canary_traffic == 20
        counts = self._counts(isvc.status.url, "roll", 50)
        # deterministic weighted router: exactly 20% +- rounding phase
        assert counts["v2"] == 10 and counts["v1"] == 40, counts

        # promote: canary becomes stable, old revision drains
        client.promote("roll")
        deadline = time.time() + 15
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "roll")
            if (isvc.status.canary_revision is None
                    and isvc.status.stable_revision == 2
                    and isvc.status.active_replicas == 1):
                break
            time.sleep(0.05)
        assert isvc.status.stable_revision == 2
        assert isvc.status.canary_revision is None
        assert isvc.status.active_replicas == 1  # old replicas gone
        assert self._counts(isvc.status.url, "roll", 10) == {"v2": 10}
        from kubeflow_tpu.controlplane.controller import events_for

        events = [e.reason for e in events_for(
            serving_cluster.store, "InferenceService", "roll")]
        assert "CanaryDeployed" in events and "CanaryPromoted" in events

    def test_canary_rollback(self, serving_cluster):
        from kubeflow_tpu.sdk.kserve import KServeClient

        client = KServeClient(serving_cluster)
        serving_cluster.store.create(self._tag_isvc("back", "v1"))
        isvc = _wait_ready(serving_cluster, "back")
        client.rollout(
            "back",
            {"predictor": {"handler": "tests.test_serving:VersionTagModel",
                           "config": {"tag": "v2"}, "min_replicas": 1,
                           "max_replicas": 2}},
            traffic_percent=50)
        deadline = time.time() + 15
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "back")
            if isvc.status.canary_revision is not None:
                break
            time.sleep(0.05)
        client.rollback("back")
        deadline = time.time() + 15
        while time.time() < deadline:
            isvc = serving_cluster.store.try_get("InferenceService", "back")
            if (isvc.status.canary_revision is None
                    and isvc.status.active_replicas == 1):
                break
            time.sleep(0.05)
        assert isvc.status.canary_revision is None
        # all traffic back on v1
        assert self._counts(isvc.status.url, "back", 10) == {"v1": 10}
        from kubeflow_tpu.controlplane.controller import events_for

        events = [e.reason for e in events_for(
            serving_cluster.store, "InferenceService", "back")]
        assert "CanaryRolledBack" in events


class TestHfScheme:
    """hf:// local-snapshot resolution with revision pinning (VERDICT r2
    missing #8 / SURVEY §2.2 storage initializer row)."""

    def _hub(self, tmp_path, commits=("aabb1122", "ccdd3344")):
        """Fake HF_HOME/hub layout with two snapshots of org/tiny-bert;
        refs/main points at the LAST commit."""
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import bert as bertlib

        repo = tmp_path / "hub" / "models--org--tiny-bert"
        (repo / "refs").mkdir(parents=True)
        cfg = bertlib.tiny(num_classes=2)
        model = bertlib.BertClassifier(cfg)
        for i, commit in enumerate(commits):
            params = model.init(
                jax.random.PRNGKey(i), jnp.ones((1, 8), jnp.int32))
            snap = repo / "snapshots" / commit
            bertlib.save_pretrained(str(snap), cfg, params)
        (repo / "refs" / "main").write_text(commits[-1])
        return str(tmp_path / "hub"), cfg

    def test_revision_pinning(self, tmp_path):
        from kubeflow_tpu.serving.storage import resolve_hf

        root, _ = self._hub(tmp_path)
        assert resolve_hf("hf://org/tiny-bert", hf_root=root).endswith("ccdd3344")
        assert resolve_hf("hf://org/tiny-bert@main", hf_root=root).endswith("ccdd3344")
        # pin by commit and by unique prefix
        assert resolve_hf("hf://org/tiny-bert@aabb1122", hf_root=root).endswith("aabb1122")
        assert resolve_hf("hf://org/tiny-bert@aabb", hf_root=root).endswith("aabb1122")
        with pytest.raises(StorageError, match="unknown revision"):
            resolve_hf("hf://org/tiny-bert@nope", hf_root=root)
        with pytest.raises(StorageError, match="not present"):
            resolve_hf("hf://org/other", hf_root=root)

    def test_hf_feeds_manifest_cache(self, tmp_path):
        root, _ = self._hub(tmp_path)
        staged = download("hf://org/tiny-bert@aabb1122",
                          cache_dir=str(tmp_path / "cache"), hf_root=root)
        assert (tmp_path / "cache") in __import__("pathlib").Path(staged).parents
        assert os.path.exists(os.path.join(staged, "weights.msgpack"))

    def test_bert_served_from_hf(self, tmp_path, serving_cluster):
        """The BERT fixture of baseline config 3 served end-to-end from an
        hf:// storage_uri."""
        root, cfg = self._hub(tmp_path)
        serving_cluster.store.create(InferenceService(
            metadata=ObjectMeta(name="hfbert"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                model_format=ModelFormat(name="bert"),
                storage_uri="hf://org/tiny-bert@main",
                config={"hf_root": root},
                min_replicas=1, max_replicas=1)),
        ))
        isvc = _wait_ready(serving_cluster, "hfbert")
        code, out = _post(f"{isvc.status.url}/v1/models/hfbert:predict",
                          {"instances": [[1, 2, 3, 4]]})
        assert code == 200
        probs = out["predictions"][0]
        assert len(probs) == cfg.num_classes
        assert abs(sum(probs) - 1.0) < 1e-3


class TestRepositoryApi:
    """V2 repository API (SURVEY §2.2 model server library: 'model
    repository with dynamic load/unload')."""

    def test_index_unload_load_cycle(self):
        from kubeflow_tpu.serving.runtimes import EchoModel
        from kubeflow_tpu.serving.server import ModelServer

        server = ModelServer().start()
        try:
            server.register(EchoModel("m1"))
            code, idx = _post_like_get(f"{server.url}/v2/repository/index")
            assert code == 200
            assert idx == [{"name": "m1", "state": "READY", "reason": ""}]

            code, out = _post(
                f"{server.url}/v2/repository/models/m1/unload", {})
            assert code == 200 and out["ok"]
            # unloaded: indexed but unavailable; infer now 404s
            _, idx = _post_like_get(f"{server.url}/v2/repository/index")
            assert idx[0]["state"] == "UNAVAILABLE"
            try:
                _post(f"{server.url}/v1/models/m1:predict", {"instances": [1]})
                raise AssertionError("expected 404 for unloaded model")
            except urllib.error.HTTPError as e:
                assert e.code == 404

            code, out = _post(
                f"{server.url}/v2/repository/models/m1/load", {})
            assert code == 200 and out["ok"]
            code, out = _post(f"{server.url}/v1/models/m1:predict",
                              {"instances": [1, 2]})
            assert code == 200 and out["predictions"] == [1, 2]

            try:
                _post(f"{server.url}/v2/repository/models/ghost/load", {})
                raise AssertionError("expected 404 for unknown model")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()


def _post_like_get(url):
    code, out = _post(url, {})
    return code, out


class TestInferenceLogger:
    """kserve agent/logger parity: the ISvc ``logger`` field POSTs
    CloudEvents-framed request/response copies to a collector sink
    without blocking the predict path."""

    def _sink(self):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from kubeflow_tpu.utils.net import allocate_port

        events = []

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                events.append({
                    "type": self.headers.get("ce-type"),
                    "id": self.headers.get("ce-id"),
                    "source": self.headers.get("ce-source"),
                    "body": json.loads(self.rfile.read(n)),
                })
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        port = allocate_port()
        httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return f"http://127.0.0.1:{port}", events, httpd

    def test_request_and_response_logged(self):
        import time as timelib

        from kubeflow_tpu.serving.runtimes import EchoModel

        url, events, httpd = self._sink()
        srv = ModelServer()
        srv.register(EchoModel("echo"))
        srv.set_logger(url, "all", service="my-isvc")
        srv.start()
        try:
            code, out = _post(srv.url + "/v1/models/echo:predict",
                              {"instances": [1, 2]})
            assert code == 200 and out["predictions"] == [1, 2]
            deadline = timelib.monotonic() + 10
            while len(events) < 2 and timelib.monotonic() < deadline:
                timelib.sleep(0.05)
            kinds = sorted(e["type"] for e in events)
            assert kinds == [
                "org.kubeflow.serving.inference.request",
                "org.kubeflow.serving.inference.response"]
            req = next(e for e in events if e["type"].endswith("request"))
            resp = next(e for e in events if e["type"].endswith("response"))
            assert req["body"] == {"instances": [1, 2]}
            assert resp["body"] == {"predictions": [1, 2]}
            assert req["id"] == resp["id"]  # correlated
            assert req["source"] == "my-isvc"
        finally:
            srv.stop()
            httpd.shutdown()

    def test_mode_request_only_and_dead_sink(self):
        import time as timelib

        from kubeflow_tpu.serving.runtimes import EchoModel

        url, events, httpd = self._sink()
        srv = ModelServer()
        srv.register(EchoModel("echo"))
        srv.set_logger(url, "request")
        srv.start()
        try:
            _post(srv.url + "/v1/models/echo:predict", {"instances": [3]})
            deadline = timelib.monotonic() + 10
            while not events and timelib.monotonic() < deadline:
                timelib.sleep(0.05)
            timelib.sleep(0.2)  # a response event would have landed too
            assert [e["type"].rsplit(".", 1)[-1] for e in events] == [
                "request"]
            # dead sink: predicts keep working, drops are counted
            httpd.shutdown()
            code, out = _post(srv.url + "/v1/models/echo:predict",
                              {"instances": [4]})
            assert code == 200 and out["predictions"] == [4]
        finally:
            srv.stop()

    def test_isvc_logger_field(self, serving_cluster):
        import time as timelib

        from kubeflow_tpu.api.inference import LoggerSpec

        url, events, httpd = self._sink()
        serving_cluster.store.create(InferenceService(
            metadata=ObjectMeta(name="logged"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler="kubeflow_tpu.serving.runtimes:EchoModel",
                logger=LoggerSpec(url=url),
            ))))
        isvc = _wait_ready(serving_cluster, "logged")
        code, out = _post(isvc.status.url + "/v1/models/logged:predict",
                          {"instances": [7]})
        assert code == 200 and out["predictions"] == [7]
        deadline = timelib.monotonic() + 10
        while len(events) < 2 and timelib.monotonic() < deadline:
            timelib.sleep(0.05)
        assert len(events) >= 2
        assert any(e["source"] == "logged" for e in events)
        httpd.shutdown()
