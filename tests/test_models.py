"""BERT + ResNet model families (BASELINE configs 2 and 3).

Same tiers as test_llama.py: numerics on one device, sharded-equals-single
on the 8-device mesh, and the serving/e2e integration the baseline configs
name.
"""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeflow_tpu.models import bert as bertlib
from kubeflow_tpu.models import resnet as resnetlib
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import sharding as shardlib


class TestBert:
    @pytest.fixture(scope="class")
    def tiny_setup(self):
        cfg = bertlib.tiny()
        model = bertlib.BertClassifier(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, cfg.vocab_size)
        params = model.init(jax.random.PRNGKey(1), ids)
        return cfg, model, ids, params

    def test_forward_shape_and_determinism(self, tiny_setup):
        cfg, model, ids, params = tiny_setup
        logits = model.apply(params, ids)
        assert logits.shape == (4, cfg.num_classes)
        assert jnp.allclose(logits, model.apply(params, ids))

    def test_padding_mask_invariance(self, tiny_setup):
        """Padded positions must not change a row's logits — the property
        the serving runtime's pad-to-bucket batching depends on."""
        cfg, model, ids, params = tiny_setup
        short = ids[:, :8]
        mask = jnp.concatenate(
            [jnp.ones((4, 8), bool), jnp.zeros((4, 8), bool)], axis=1)
        padded = jnp.concatenate(
            [short, jnp.zeros((4, 8), short.dtype)], axis=1)
        out_short = model.apply(params, short)
        out_padded = model.apply(params, padded, mask)
        np.testing.assert_allclose(
            np.asarray(out_short), np.asarray(out_padded), atol=1e-4)

    def test_gradients_flow(self, tiny_setup):
        cfg, model, ids, params = tiny_setup
        y = jnp.array([0, 1, 0, 1])

        def loss(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, ids), y).mean()

        grads = jax.grad(loss)(params)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(bool(jnp.any(g != 0)) for g in flat)

    def test_sharded_matches_single_device(self, tiny_setup):
        """TP/DP over the 8-device mesh computes the same logits as one
        device (the test_llama.py:54 pattern)."""
        cfg, model, ids, params = tiny_setup
        want = np.asarray(model.apply(params, ids))
        mesh = meshlib.build_mesh({"data": 2, "model": 4})
        with shardlib.shard_context(mesh):
            sharded_params = jax.device_put(params, meshlib.replicated(mesh))
            x = jax.device_put(ids, meshlib.batch_sharding(mesh))
            got = np.asarray(jax.jit(model.apply)(sharded_params, x))
        np.testing.assert_allclose(want, got, atol=2e-4)


class TestResNet:
    @pytest.fixture(scope="class")
    def tiny_setup(self):
        cfg = resnetlib.tiny()
        model = resnetlib.ResNet(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 32, 3))
        params = model.init(jax.random.PRNGKey(1), x)
        return cfg, model, x, params

    def test_forward_shape(self, tiny_setup):
        cfg, model, x, params = tiny_setup
        logits = model.apply(params, x)
        assert logits.shape == (4, cfg.num_classes)

    def test_resnet50_block_count(self):
        """The preset matches the reference model the benchmark names:
        50 = 1 stem + 3*(3+4+6+3) bottleneck convs + 1 head."""
        cfg = resnetlib.resnet50()
        assert cfg.bottleneck and sum(cfg.stage_sizes) == 16
        assert 1 + 3 * sum(cfg.stage_sizes) + 1 == 50

    def test_training_reduces_loss(self, tiny_setup):
        cfg, model, x, params = tiny_setup
        y = jnp.array([0, 1, 2, 3])
        tx = optax.sgd(0.1, momentum=0.9)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            def loss_fn(p):
                return optax.softmax_cross_entropy_with_integer_labels(
                    model.apply(p, x), y).mean()
            loss, grads = jax.value_and_grad(loss_fn)(p)
            updates, o = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        first = None
        for _ in range(10):
            params, opt, loss = step(params, opt)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_dp_sharded_matches_single_device(self, tiny_setup):
        cfg, model, x, params = tiny_setup
        x = jnp.concatenate([x, x], axis=0)  # batch 8 = mesh size
        want = np.asarray(model.apply(params, x))
        mesh = meshlib.build_mesh({"data": 8})
        xs = jax.device_put(x, meshlib.batch_sharding(mesh))
        ps = jax.device_put(params, meshlib.replicated(mesh))
        got = np.asarray(jax.jit(model.apply)(ps, xs))
        np.testing.assert_allclose(want, got, atol=2e-4)


class TestBertServing:
    def test_isvc_bert_runtime_autoselected(self):
        """Baseline config 3 end-to-end: bert modelFormat -> kft-bert
        runtime -> ragged token batches -> class probabilities."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec, InferenceService, InferenceServicePhase,
            InferenceServiceSpec, ModelFormat)
        from kubeflow_tpu.controlplane.cluster import Cluster
        from kubeflow_tpu.serving import register_mem

        cfg = bertlib.tiny()
        model = bertlib.BertClassifier(cfg)
        params = model.init(
            jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))
        ref = register_mem("bert-tiny", (cfg, params))

        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        cluster.enable_serving()
        with cluster:
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="bert"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="bert"),
                    config={"params_ref": ref}))))
            deadline = time.time() + 60
            isvc = None
            while time.time() < deadline:
                isvc = cluster.store.try_get("InferenceService", "bert")
                if isvc and isvc.status.phase == InferenceServicePhase.READY:
                    break
                time.sleep(0.1)
            assert isvc.status.phase == InferenceServicePhase.READY, isvc.status
            body = json.dumps(
                {"instances": [[5, 9, 2], [7, 1, 3, 4, 8, 11, 2]]}).encode()
            req = urllib.request.Request(
                f"{isvc.status.url}/v1/models/bert:predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            preds = out["predictions"]
            assert len(preds) == 2
            for p in preds:
                assert len(p) == cfg.num_classes
                assert abs(sum(p) - 1.0) < 1e-3
            # padded-batch scores equal solo scores (mask correctness e2e)
            req1 = urllib.request.Request(
                f"{isvc.status.url}/v1/models/bert:predict",
                data=json.dumps({"instances": [[5, 9, 2]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req1, timeout=60) as resp:
                solo = json.loads(resp.read())["predictions"][0]
            np.testing.assert_allclose(preds[0], solo, atol=1e-4)
