"""Multi-HOST serving gang e2e: the predictor as N cooperating processes.

SURVEY.md §3.3 / §2.6 — a TP=8 predictor spanning 2 host processes (each
4 virtual CPU devices, the honest multi-host stand-in) must return
token-identical output to the single-process TP=8 path: same programs,
same mesh, different process placement (serving/gang.py design note).
The gang is placed by the InferenceService controller as a JaxJob, so
restarts ride the training gang machinery.
"""

import json
import os
import signal
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.api.inference import (
    ComponentSpec,
    GangSpec,
    InferenceService,
    InferenceServicePhase,
    InferenceServiceSpec,
    KIND_INFERENCE_SERVICE,
)
from kubeflow_tpu.controlplane.objects import KIND_POD
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.runtime.platform import LocalPlatform
from kubeflow_tpu.serving.continuous import ContinuousEngine

PROMPTS = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]
ENGINE_CONF = {
    "num_slots": 4,
    "decode_chunk": 2,
    "temperature": 0.0,
    "max_new_tokens": 5,
    "seq_buckets": [32],
    "prefix_cache": False,
    "warmup_groups": [[1, 32]],
}


@pytest.fixture()
def platform(tmp_path):
    p = LocalPlatform(num_hosts=4, chips_per_host=4, root_dir=str(tmp_path))
    with p:
        yield p


def _snapshot(tmp_path) -> str:
    # TP=8 shards kv_heads/mlp/vocab over 8 devices: all must divide by 8
    cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    path = str(tmp_path / "snap")
    llamalib.save_pretrained(path, cfg, params)
    return path


def _reference_tokens(snap: str) -> list[list[int]]:
    """Single-process TP=8 engine on the same checkpoint (this test
    process has 8 virtual devices via conftest)."""
    cfg, params = llamalib.load_pretrained(snap)
    eng = ContinuousEngine(
        cfg, params, num_slots=4, decode_chunk=2, temperature=0.0,
        eos_id=None, seq_buckets=[32], prefix_cache=False,
        mesh_axes={"model": 8})
    try:
        return [eng.generate(p, max_new_tokens=5, timeout=300)
                for p in PROMPTS]
    finally:
        eng.stop()


def _predict(url: str, name: str, instances, timeout=300.0):
    req = urllib.request.Request(
        f"{url}/v1/models/{name}:predict",
        data=json.dumps({"instances": instances}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())["predictions"]


def _wait_phase(store, name, phase, timeout=300.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        isvc = store.get(KIND_INFERENCE_SERVICE, name)
        if isvc.status.phase == phase:
            return isvc
        time.sleep(0.25)
    raise AssertionError(
        f"isvc {name} never reached {phase}: "
        f"{store.get(KIND_INFERENCE_SERVICE, name).status}")


@pytest.mark.e2e
class TestServingGang:
    def test_gang_tp8_token_parity_and_restart(self, platform, tmp_path):
        snap = _snapshot(tmp_path)
        want = _reference_tokens(snap)

        isvc = InferenceService(
            metadata=ObjectMeta(name="gangllama"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler=(
                    "kubeflow_tpu.serving.continuous:"
                    "ContinuousLlamaGenerator"),
                storage_uri=f"file://{snap}",
                gang=GangSpec(
                    hosts=2, mesh_axes={"model": 8}, chips_per_host=4),
                config=dict(ENGINE_CONF),
            )))
        platform.store.create(isvc)
        isvc = _wait_phase(platform.store, "gangllama",
                           InferenceServicePhase.READY)

        # (a) token parity: 2-process TP=8 == single-process TP=8
        got = [_predict(isvc.status.url, "gangllama", [p])[0]
               for p in PROMPTS]
        assert got == want

        # (b) restart like a JaxJob: SIGKILL rank 0 -> gang restart ->
        # same URL serves the same tokens again
        pod = platform.store.get(KIND_POD, "gangllama-gang-r1-g0-worker-0")
        assert pod.status.pid
        os.kill(pod.status.pid, signal.SIGKILL)
        deadline = time.time() + 300
        restarted = False
        while time.time() < deadline:
            try:
                again = _predict(isvc.status.url, "gangllama",
                                 [PROMPTS[0]], timeout=10)
                if again[0] == want[0]:
                    restarted = True
                    break
            except (urllib.error.URLError, urllib.error.HTTPError, OSError):
                pass
            time.sleep(1.0)
        assert restarted, "gang did not come back after rank-0 SIGKILL"

    def test_gang_shared_segments_parity(self, platform, tmp_path):
        """Shared-prefix segments over the gang control stream: the
        segment ops (creation prefill/merge, batched suffix admit,
        prefix decode) replay on followers, token-identical to the
        single-process segment engine — suffix-sized slots and all."""
        snap = _snapshot(tmp_path)
        rng = __import__("numpy").random.default_rng(0)
        system = rng.integers(1, 200, size=24).tolist()
        prompts = [system + rng.integers(1, 200, size=3).tolist()
                   for _ in range(3)]
        conf = {
            "num_slots": 3, "decode_chunk": 2, "temperature": 0.0,
            "max_new_tokens": 4, "seq_buckets": [16], "max_seq_len": 32,
            "prefix_cache": False, "prefix_segments": 2,
            "segment_len": 64, "min_prefix": 8, "warmup_groups": [],
        }
        # single-process TP=8 reference with the same knobs
        import dataclasses

        cfg, params = llamalib.load_pretrained(snap)
        scfg = dataclasses.replace(cfg, max_seq_len=32)
        ref = ContinuousEngine(
            scfg, params, num_slots=3, decode_chunk=2, temperature=0.0,
            eos_id=None, seq_buckets=[16], prefix_cache=False,
            prefix_segments=2, segment_len=64, min_prefix=8,
            mesh_axes={"model": 8})
        try:
            want = [ref.generate(p, max_new_tokens=4, timeout=300)
                    for p in prompts]
            assert ref.stats()["segments_live"] >= 1
        finally:
            ref.stop()

        isvc = InferenceService(
            metadata=ObjectMeta(name="seggang"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler=(
                    "kubeflow_tpu.serving.continuous:"
                    "ContinuousLlamaGenerator"),
                storage_uri=f"file://{snap}",
                gang=GangSpec(
                    hosts=2, mesh_axes={"model": 8}, chips_per_host=4),
                config=conf,
            )))
        platform.store.create(isvc)
        isvc = _wait_phase(platform.store, "seggang",
                           InferenceServicePhase.READY)
        got = [_predict(isvc.status.url, "seggang", [p])[0]
               for p in prompts]
        assert got == want

    def test_gang_replicas_scale(self, platform, tmp_path):
        """Gang REPLICAS scale like in-process ones: min_replicas=2
        places two ordinal-named JaxJob gangs behind the router; both
        serve; teardown deletes both."""
        from kubeflow_tpu.api.jaxjob import KIND_JAXJOB

        snap = _snapshot(tmp_path)
        isvc = InferenceService(
            metadata=ObjectMeta(name="multigang"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler=(
                    "kubeflow_tpu.serving.continuous:"
                    "ContinuousLlamaGenerator"),
                storage_uri=f"file://{snap}",
                min_replicas=2, max_replicas=2,
                gang=GangSpec(
                    hosts=2, mesh_axes={"model": 8}, chips_per_host=4),
                config=dict(ENGINE_CONF),
            )))
        platform.store.create(isvc)
        isvc = _wait_phase(platform.store, "multigang",
                           InferenceServicePhase.READY)
        deadline = time.time() + 300
        while time.time() < deadline:
            jobs = sorted(
                j.metadata.name
                for j in platform.store.list(KIND_JAXJOB)
                if j.metadata.name.startswith("multigang-gang-"))
            if len(jobs) == 2:
                break
            time.sleep(0.5)
        assert jobs == ["multigang-gang-r1-g0", "multigang-gang-r1-g1"]
        # both gangs take traffic through the router
        outs = [_predict(isvc.status.url, "multigang", [[1, 2, 3]])[0]
                for _ in range(4)]
        assert all(o == outs[0] for o in outs)
        platform.store.delete(KIND_INFERENCE_SERVICE, "multigang",
                              "default")
        deadline = time.time() + 120
        while time.time() < deadline:
            left = [j for j in platform.store.list(KIND_JAXJOB)
                    if j.metadata.name.startswith("multigang-gang-")]
            if not left:
                break
            time.sleep(0.5)
        assert not left, [j.metadata.name for j in left]

    def test_gang_channel_roundtrip(self):
        """Framing unit test: big numpy payloads survive the stream."""
        import threading

        import numpy as np

        from kubeflow_tpu.serving.gang import GangChannel

        from kubeflow_tpu.utils.net import allocate_port

        port = allocate_port()
        out = {}

        def follower():
            ch = GangChannel.connect("127.0.0.1", port, rank=1)
            out["msgs"] = [ch.next(), ch.next()]
            ch.close()

        t = threading.Thread(target=follower)
        t.start()
        ch = GangChannel.listen(port, 1)
        big = np.arange(100_000, dtype=np.int32)
        ch.publish(("decode", 128, big))
        ch.publish(("stop",))
        t.join(timeout=30)
        ch.close()
        assert out["msgs"][1] == ("stop",)
        op, needed, arr = out["msgs"][0]
        assert (op, needed) == ("decode", 128)
        assert np.array_equal(arr, big)


class TestGangChunkedPrefill:
    """ISSUE 2: the chunked-admission schedule (``chunk_prefill`` /
    ``fused`` ops) crosses the control stream and a follower replays it
    BIT-IDENTICALLY — same pool cache, same pool logits — with token
    parity against the single-process chunked engine.  Single process,
    loopback channel: the real GangEngine publish wrappers and the real
    ``follow()`` executor, no JaxJob machinery."""

    @pytest.mark.slow
    def test_follower_replays_chunked_schedule_bit_identically(self):
        import threading

        import numpy as np
        from flax import linen as nn

        from kubeflow_tpu.serving.gang import GangChannel, GangEngine, follow
        from kubeflow_tpu.utils.net import allocate_port

        cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
        params = nn.meta.unbox(llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        kw = dict(num_slots=3, decode_chunk=2, temperature=0.0,
                  eos_id=None, seq_buckets=[32], prefix_cache=False,
                  prefill_budget=8, mesh_axes={"model": 8})
        prompt = list(range(1, 25))  # 3 chunks at budget 8

        ref = ContinuousEngine(cfg, params, **kw)
        try:
            r1 = ref.submit([7, 8, 9], max_new_tokens=12)
            r2 = ref.submit(prompt, max_new_tokens=5)
            want = [r1.wait(300), r2.wait(300)]
        finally:
            ref.stop()

        port = allocate_port()
        follower_engine = ContinuousEngine(cfg, params, **kw)
        ops: list[str] = []

        def run_follower():
            ch = GangChannel.connect("127.0.0.1", port, rank=1, token="t")
            orig_next = ch.next

            def tap():
                m = orig_next()
                ops.append(m[0])
                return m

            ch.next = tap
            try:
                follow(follower_engine, ch)
            finally:
                ch.close()

        t = threading.Thread(target=run_follower, daemon=True)
        t.start()
        chan = GangChannel.listen(port, 1, token="t")
        leader = GangEngine(cfg, params, channel=chan, **kw)
        try:
            victim = leader.submit([7, 8, 9], max_new_tokens=12)
            time.sleep(0.2)  # let the victim enter decode: chunks fuse
            late = leader.submit(prompt, max_new_tokens=5)
            got = [victim.wait(300), late.wait(300)]
        finally:
            # stop() publishes the terminal op; follow() then drains the
            # full stream before returning, so after join both pools are
            # final — no sleep-based synchronization (generous timeout:
            # the follower compiles its own program set on first replay)
            leader.stop()
            t.join(timeout=300)
        assert not t.is_alive(), "follower did not drain the stream"
        assert got == want  # chunked gang == chunked single-process
        assert "chunk_prefill" in ops or "fused" in ops
        # the replayed pool state is the leader's, bit for bit
        ll = np.asarray(jax.device_get(leader._pool_logits))
        fl = np.asarray(jax.device_get(follower_engine._pool_logits))
        assert np.array_equal(ll, fl)
        for a, b in zip(jax.tree.leaves(jax.device_get(leader._pool_cache)),
                        jax.tree.leaves(
                            jax.device_get(follower_engine._pool_cache))):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestGangSpeculative:
    """ISSUE 4: the speculative schedule (``verify`` ops carrying
    drafts + residual bans) crosses the control stream and a follower
    replays it BIT-IDENTICALLY — acceptance is recomputed on-device by
    the same deterministic program, so leader and follower pool state
    match without accept lengths ever crossing the wire.  Single
    process, loopback channel, like TestGangChunkedPrefill."""

    @pytest.mark.slow
    def test_follower_replays_verify_stream_bit_identically(self):
        import threading

        import numpy as np
        from flax import linen as nn

        from kubeflow_tpu.serving.gang import GangChannel, GangEngine, follow
        from kubeflow_tpu.utils.net import allocate_port

        cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
        params = nn.meta.unbox(llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        kw = dict(num_slots=3, decode_chunk=1, temperature=0.0,
                  eos_id=None, seq_buckets=[32], prefix_cache=False,
                  spec_k=4, mesh_axes={"model": 8})
        prompt = np.random.default_rng(7).integers(1, 200, size=5).tolist()

        ref = ContinuousEngine(cfg, params, **kw)
        try:
            want = ref.generate(prompt, max_new_tokens=40, timeout=300)
            assert ref.spec_dispatches_total > 0  # the run speculated
        finally:
            ref.stop()

        port = allocate_port()
        follower_engine = ContinuousEngine(cfg, params, **kw)
        ops: list[str] = []

        def run_follower():
            ch = GangChannel.connect("127.0.0.1", port, rank=1, token="t")
            orig_next = ch.next

            def tap():
                m = orig_next()
                ops.append(m[0])
                return m

            ch.next = tap
            try:
                follow(follower_engine, ch)
            finally:
                ch.close()

        t = threading.Thread(target=run_follower, daemon=True)
        t.start()
        chan = GangChannel.listen(port, 1, token="t")
        leader = GangEngine(cfg, params, channel=chan, **kw)
        try:
            got = leader.generate(prompt, max_new_tokens=40, timeout=300)
        finally:
            leader.stop()
            t.join(timeout=300)
        assert not t.is_alive(), "follower did not drain the stream"
        assert got == want  # speculative gang == speculative single-proc
        assert "verify" in ops
        ll = np.asarray(jax.device_get(leader._pool_logits))
        fl = np.asarray(jax.device_get(follower_engine._pool_logits))
        assert np.array_equal(ll, fl)
        for a, b in zip(jax.tree.leaves(jax.device_get(leader._pool_cache)),
                        jax.tree.leaves(
                            jax.device_get(follower_engine._pool_cache))):
            assert np.array_equal(np.asarray(a), np.asarray(b))


class TestGangChannelRecovery:
    """Control-stream self-healing (ISSUE 1), no processes: the dispatch
    replay a follower needs after a socket drop is exactly the replay an
    engine follower would apply, op tuples and numpy payloads included."""

    def test_dispatch_stream_survives_follower_socket_drop(self):
        import threading

        import numpy as np

        from kubeflow_tpu.chaos import FaultPlan
        from kubeflow_tpu.serving.gang import GangChannel
        from kubeflow_tpu.utils.net import allocate_port

        port = allocate_port()
        plan = FaultPlan(seed=0).socket_drop(role="follower", after_calls=20)
        chan = dict(hb_interval=0.05, dead_peer_timeout=0.5,
                    reattach_timeout=5.0, reconnect_timeout=5.0)
        out = {}

        def follower():
            ch = GangChannel.connect(
                "127.0.0.1", port, rank=1, token="t",
                sock_wrap=plan.socket_wrapper("follower"), **chan)
            msgs = []
            while True:
                m = ch.next()
                if m == ("stop",):
                    break
                msgs.append(m)
            out["msgs"] = msgs
            ch.close()

        t = threading.Thread(target=follower)
        t.start()
        leader = GangChannel.listen(port, 1, token="t", **chan)
        sent = []
        for step in range(12):
            msg = ("decode", step, np.arange(
                200, dtype=np.int32) + step)
            leader.publish(msg)
            sent.append(msg)
            time.sleep(0.01)
        leader.publish(("stop",))
        t.join(timeout=20)
        leader.close()
        assert not t.is_alive(), "follower hung after socket drop"
        got = out["msgs"]
        assert len(got) == len(sent)
        for g, s in zip(got, sent):
            assert g[:2] == s[:2]
            assert __import__("numpy").array_equal(g[2], s[2])


@pytest.mark.e2e
class TestGangOpenAI:
    def test_openai_completions_on_gang(self, platform, tmp_path):
        """The OpenAI surface on a multi-host predictor: rank 0 serves
        /openai/v1/completions with the byte tokenizer over the gang
        engine; text equals the single-process TP=8 text path."""
        snap = _snapshot(tmp_path)
        conf = {**ENGINE_CONF, "runtime": "text", "tokenizer": "bytes"}

        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg, params = llamalib.load_pretrained(snap)
        ref_key = register_mem("gangtext", (cfg, params))
        single = TextGenerator("s", {
            "params_ref": ref_key, "tokenizer": "bytes",
            "mesh_axes": {"model": 8}, **{
                k: v for k, v in ENGINE_CONF.items()}})
        single.start()
        try:
            want = single.openai_completions(
                {"prompt": "hi", "max_tokens": 4})["choices"][0]["text"]
        finally:
            single.stop()

        isvc = InferenceService(
            metadata=ObjectMeta(name="oaigang"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler="kubeflow_tpu.serving.text:TextGenerator",
                storage_uri=f"file://{snap}",
                gang=GangSpec(
                    hosts=2, mesh_axes={"model": 8}, chips_per_host=4),
                config=conf,
            )))
        platform.store.create(isvc)
        isvc = _wait_phase(platform.store, "oaigang",
                           InferenceServicePhase.READY)
        req = urllib.request.Request(
            f"{isvc.status.url}/openai/v1/completions",
            data=json.dumps({"model": "oaigang", "prompt": "hi",
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            body = json.loads(resp.read())
        assert body["choices"][0]["text"] == want
