"""Continuous batching engine (serving/continuous.py).

The reference's LLM serving capability is vLLM-backed continuous batching
[upstream: kserve -> python/huggingfaceserver]; these tests pin the TPU
slot-pool equivalent: correctness vs the decode-to-completion generator,
token-boundary admission of mid-decode arrivals, slot reuse, EOS stop.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine, ContinuousLlamaGenerator
from kubeflow_tpu.serving.runtimes import LlamaGenerator
from kubeflow_tpu.serving.storage import register_mem


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


@pytest.fixture(scope="module")
def reference_generator(tiny_llama):
    """The decode-to-completion generator as the correctness oracle."""
    cfg, params = tiny_llama
    ref = register_mem("cb-oracle", (cfg, params))
    g = LlamaGenerator("oracle", {"params_ref": ref, "max_new_tokens": 6})
    g.start()
    return g


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 1)
    return ContinuousEngine(cfg, params, **kw)


class TestContinuousEngine:
    def test_greedy_matches_batch_generator(self, tiny_llama, reference_generator):
        eng = make_engine(tiny_llama)
        try:
            prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            got = [r.wait(300) for r in reqs]
            expected = reference_generator.predict_batch(prompts)
            assert got == expected
        finally:
            eng.stop()

    def test_chunked_decode_matches(self, tiny_llama, reference_generator):
        eng = make_engine(tiny_llama, decode_chunk=4)
        try:
            got = eng.generate([1, 2, 3], max_new_tokens=6)
            assert got == reference_generator.predict_batch([[1, 2, 3]])[0]
        finally:
            eng.stop()

    def test_mid_decode_admission_within_one_step(self, tiny_llama,
                                                  reference_generator):
        """A request arriving while another decodes must be admitted at the
        next token boundary (the capability continuous batching exists for:
        LlamaGenerator would make it wait for the whole running batch)."""
        eng = make_engine(tiny_llama, decode_chunk=1)
        try:
            long_req = eng.submit([1, 2, 3], max_new_tokens=40)
            while eng.step_counter < 5:  # let the long request get going
                time.sleep(0.01)
            assert not long_req.done.is_set()
            late = eng.submit([4, 5, 6, 7, 8], max_new_tokens=3)
            got = late.wait(300)
            # admitted at the first token boundary after submission
            assert late.admitted_step - late.submitted_step <= 1
            # finished while the long request was still decoding
            assert not long_req.done.is_set()
            assert got == reference_generator.predict_batch([[4, 5, 6, 7, 8]])[0][:3]
            long_req.wait(300)
        finally:
            eng.stop()

    def test_slot_reuse_more_requests_than_slots(self, tiny_llama,
                                                 reference_generator):
        """5 requests through 2 slots: retired slots are reused and stale
        KV from prior occupants never leaks into later generations."""
        eng = make_engine(tiny_llama, num_slots=2)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
            reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
            got = [r.wait(300) for r in reqs]
            expected = [
                reference_generator.predict_batch([p])[0][:4] for p in prompts
            ]
            assert got == expected
        finally:
            eng.stop()

    def test_eos_stops_generation(self, tiny_llama, reference_generator):
        first = reference_generator.predict_batch([[1, 2, 3]])[0][0]
        eng = make_engine(tiny_llama, eos_id=first)
        try:
            got = eng.generate([1, 2, 3], max_new_tokens=8)
            assert got == [first]  # stopped at EOS, not at max_new_tokens
        finally:
            eng.stop()

    def test_empty_prompt_empty_continuation(self, tiny_llama):
        eng = make_engine(tiny_llama)
        try:
            assert eng.generate([], max_new_tokens=4) == []
        finally:
            eng.stop()


class TestContinuousRuntime:
    def test_concurrent_requests_coalesce(self, tiny_llama, reference_generator):
        """The Model wrapper is self-batching: concurrent request threads
        all make progress through one slot pool."""
        cfg, params = tiny_llama
        ref = register_mem("cb-runtime", (cfg, params))
        m = ContinuousLlamaGenerator(
            "cb", {"params_ref": ref, "num_slots": 4, "decode_chunk": 1,
                   "max_new_tokens": 4})
        m.start()
        try:
            prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6]]
            results: dict[int, list] = {}

            def call(i):
                results[i] = m.predict_batch([prompts[i]])[0]

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            expected = [
                reference_generator.predict_batch([p])[0][:4] for p in prompts
            ]
            assert [results[i] for i in range(len(prompts))] == expected
        finally:
            m.stop()
