"""Continuous batching engine (serving/continuous.py).

The reference's LLM serving capability is vLLM-backed continuous batching
[upstream: kserve -> python/huggingfaceserver]; these tests pin the TPU
slot-pool equivalent: correctness vs the decode-to-completion generator,
token-boundary admission of mid-decode arrivals, slot reuse, EOS stop.
"""

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine, ContinuousLlamaGenerator
from kubeflow_tpu.serving.runtimes import LlamaGenerator
from kubeflow_tpu.serving.storage import register_mem


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


@pytest.fixture(scope="module")
def reference_generator(tiny_llama):
    """The decode-to-completion generator as the correctness oracle."""
    cfg, params = tiny_llama
    ref = register_mem("cb-oracle", (cfg, params))
    g = LlamaGenerator("oracle", {"params_ref": ref, "max_new_tokens": 6})
    g.start()
    return g


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 1)
    return ContinuousEngine(cfg, params, **kw)


class TestContinuousEngine:
    def test_greedy_matches_batch_generator(self, tiny_llama, reference_generator):
        eng = make_engine(tiny_llama)
        try:
            prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9]]
            reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
            got = [r.wait(300) for r in reqs]
            expected = reference_generator.predict_batch(prompts)
            assert got == expected
        finally:
            eng.stop()

    def test_chunked_decode_matches(self, tiny_llama, reference_generator):
        eng = make_engine(tiny_llama, decode_chunk=4)
        try:
            got = eng.generate([1, 2, 3], max_new_tokens=6)
            assert got == reference_generator.predict_batch([[1, 2, 3]])[0]
        finally:
            eng.stop()

    def test_mid_decode_admission_within_one_step(self, tiny_llama,
                                                  reference_generator):
        """A request arriving while another decodes must be admitted at the
        next token boundary (the capability continuous batching exists for:
        LlamaGenerator would make it wait for the whole running batch)."""
        eng = make_engine(tiny_llama, decode_chunk=1)
        try:
            long_req = eng.submit([1, 2, 3], max_new_tokens=40)
            while eng.step_counter < 5:  # let the long request get going
                time.sleep(0.01)
            assert not long_req.done.is_set()
            late = eng.submit([4, 5, 6, 7, 8], max_new_tokens=3)
            got = late.wait(300)
            # admitted at the first token boundary after submission
            assert late.admitted_step - late.submitted_step <= 1
            # finished while the long request was still decoding
            assert not long_req.done.is_set()
            assert got == reference_generator.predict_batch([[4, 5, 6, 7, 8]])[0][:3]
            long_req.wait(300)
        finally:
            eng.stop()

    def test_slot_reuse_more_requests_than_slots(self, tiny_llama,
                                                 reference_generator):
        """5 requests through 2 slots: retired slots are reused and stale
        KV from prior occupants never leaks into later generations."""
        eng = make_engine(tiny_llama, num_slots=2)
        try:
            prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
            reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
            got = [r.wait(300) for r in reqs]
            expected = [
                reference_generator.predict_batch([p])[0][:4] for p in prompts
            ]
            assert got == expected
        finally:
            eng.stop()

    def test_eos_stops_generation(self, tiny_llama, reference_generator):
        first = reference_generator.predict_batch([[1, 2, 3]])[0][0]
        eng = make_engine(tiny_llama, eos_id=first)
        try:
            got = eng.generate([1, 2, 3], max_new_tokens=8)
            assert got == [first]  # stopped at EOS, not at max_new_tokens
            # the dispatch-ahead lag's waste is MEASURED, not hidden:
            # tokens decoded past the EOS cut land in tokens_discarded
            import time as _time
            _time.sleep(0.3)  # let in-flight chunks drain
            assert eng.tokens_discarded >= 1
            assert eng.stats()["tokens_discarded"] == eng.tokens_discarded
        finally:
            eng.stop()

    def test_empty_prompt_empty_continuation(self, tiny_llama):
        eng = make_engine(tiny_llama)
        try:
            assert eng.generate([], max_new_tokens=4) == []
        finally:
            eng.stop()


class TestContinuousRuntime:
    def test_concurrent_requests_coalesce(self, tiny_llama, reference_generator):
        """The Model wrapper is self-batching: concurrent request threads
        all make progress through one slot pool."""
        cfg, params = tiny_llama
        ref = register_mem("cb-runtime", (cfg, params))
        m = ContinuousLlamaGenerator(
            "cb", {"params_ref": ref, "num_slots": 4, "decode_chunk": 1,
                   "max_new_tokens": 4})
        m.start()
        try:
            prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [2, 4, 6]]
            results: dict[int, list] = {}

            def call(i):
                results[i] = m.predict_batch([prompts[i]])[0]

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            expected = [
                reference_generator.predict_batch([p])[0][:4] for p in prompts
            ]
            assert [results[i] for i in range(len(prompts))] == expected
        finally:
            m.stop()


class TestPrefixCache:
    """r3 verdict item 7: KV prefix reuse at admission — repeated prompts
    skip the shared prefill via an on-device slot-to-slot copy."""

    LONG = list(range(1, 49))  # 48-token shared prefix

    def test_prefix_hit_output_parity(self, tiny_llama):
        """A prefix-cached admission must produce EXACTLY the tokens a
        cold admission produces (greedy)."""
        cold = make_engine(tiny_llama, prefix_cache=False)
        try:
            first = cold.generate(self.LONG, max_new_tokens=5)
            again = cold.generate(self.LONG, max_new_tokens=5)
        finally:
            cold.stop()
        assert first == again

        warm = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            a = warm.generate(self.LONG, max_new_tokens=5)
            assert warm.prefix_hits == 0  # nothing to match yet
            b = warm.generate(self.LONG, max_new_tokens=5)
            assert warm.prefix_hits == 1
            assert warm.prefix_tokens_saved >= len(self.LONG) - 1
        finally:
            warm.stop()
        assert a == first and b == first

    def test_conversation_continuation_prefix(self, tiny_llama):
        """prompt + generated-turn resent (the chat pattern): the whole
        previous conversation matches as prefix, only the new turn
        prefills."""
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            turn1 = eng.generate(self.LONG, max_new_tokens=4)
            followup = self.LONG + turn1 + [7, 8, 9]
            cold = make_engine(tiny_llama, prefix_cache=False)
            try:
                want = cold.generate(followup, max_new_tokens=4)
            finally:
                cold.stop()
            got = eng.generate(followup, max_new_tokens=4)
            assert eng.prefix_hits == 1
            # the saved prefix covers at least the original prompt
            assert eng.prefix_tokens_saved >= len(self.LONG)
        finally:
            eng.stop()
        assert got == want

    def test_short_common_prefix_not_matched(self, tiny_llama):
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=32)
        try:
            eng.generate(self.LONG[:8] + [100, 101], max_new_tokens=3)
            eng.generate(self.LONG[:8] + [102, 103], max_new_tokens=3)
            assert eng.prefix_hits == 0  # 8 < min_prefix
        finally:
            eng.stop()

    def test_divergent_suffix_correct(self, tiny_llama):
        """Shared prefix, different suffix: outputs must match cold runs
        for BOTH suffixes."""
        p1 = self.LONG + [60, 61, 62]
        p2 = self.LONG + [70, 71]
        cold = make_engine(tiny_llama, prefix_cache=False)
        try:
            w1 = cold.generate(p1, max_new_tokens=4)
            w2 = cold.generate(p2, max_new_tokens=4)
        finally:
            cold.stop()
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=8)
        try:
            g1 = eng.generate(p1, max_new_tokens=4)
            g2 = eng.generate(p2, max_new_tokens=4)
            assert eng.prefix_hits == 1
        finally:
            eng.stop()
        assert g1 == w1 and g2 == w2

    def test_prefix_cache_on_sharded_mesh(self, tiny_llama):
        """Prefix copy + suffix prefill compose with the TP pool."""
        eng = make_engine(tiny_llama, prefix_cache=True, min_prefix=8,
                          mesh_axes={"model": 2})
        try:
            a = eng.generate(self.LONG, max_new_tokens=4)
            b = eng.generate(self.LONG, max_new_tokens=4)
            assert eng.prefix_hits == 1
        finally:
            eng.stop()
        assert a == b


class TestTieredEngine:
    """Two-tier pool (r3 weak #4): a long conversation must not drag
    every short request's decode window up to its own."""

    def test_routing_and_parity(self, tiny_llama):
        from kubeflow_tpu.serving.continuous import TieredEngine

        cfg, params = tiny_llama
        cold = make_engine(tiny_llama, prefix_cache=False)
        try:
            want_short = cold.generate([1, 2, 3], max_new_tokens=4)
            long_prompt = list(range(1, 70))
            want_long = cold.generate(long_prompt, max_new_tokens=4)
        finally:
            cold.stop()

        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=2, prefix_cache=False)
        try:
            got_short = eng.generate([1, 2, 3], max_new_tokens=4)
            got_long = eng.generate(long_prompt, max_new_tokens=4)
            # routing actually split: each pool emitted its own tokens
            assert eng.short.tokens_emitted >= 4
            assert eng.long.tokens_emitted >= 4
        finally:
            eng.stop()
        assert got_short == want_short and got_long == want_long

    def test_one_paged_pool_no_per_tier_kv(self, tiny_llama):
        """ISSUE 6: the ladder is an admission POLICY over ONE paged
        pool — no per-tier KV pools remain.  The single pool's cache is
        block-granular (rows = blocks, seq = block_size), and the class
        quotas are enforced by the engine's admission_policy hook."""
        from kubeflow_tpu.serving.continuous import TieredEngine

        cfg, params = tiny_llama
        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=1)
        try:
            assert len(eng.pools) == 1
            assert eng.short is eng.long is eng.engine
            assert eng.engine.paged and eng.engine.block_size > 0
            bs = eng.engine.block_size
            big = [x for x in jax.tree.leaves(eng.engine._pool_cache)
                   if x.ndim >= 4]
            # every big leaf stores BLOCKS: seq dim == block_size, row
            # dim == num_blocks — max_seq_len appears nowhere resident
            assert big and all(x.shape[-3] == bs for x in big)
            assert all(x.shape[-4] == eng.engine.num_blocks for x in big)
            assert (eng.engine.admission_policy.__func__
                    is TieredEngine._admit_quota)
            st = eng.stats()
            assert [c["quota"] for c in st["classes"]] == eng.quotas
        finally:
            eng.stop()

    def test_concurrent_mixed_lengths(self, tiny_llama):
        from kubeflow_tpu.serving.continuous import TieredEngine

        cfg, params = tiny_llama
        cold = make_engine(tiny_llama, prefix_cache=False)
        try:
            wants = [cold.generate(p, max_new_tokens=3) for p in
                     ([5, 6], list(range(1, 60)), [9, 8, 7])]
        finally:
            cold.stop()
        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=2)
        try:
            reqs = [eng.submit(p, max_new_tokens=3) for p in
                    ([5, 6], list(range(1, 60)), [9, 8, 7])]
            gots = [r.wait(120) for r in reqs]
        finally:
            eng.stop()
        assert gots == wants

    def test_build_engine_tiered_config(self, tiny_llama):
        from kubeflow_tpu.serving.continuous import TieredEngine, build_engine

        cfg, params = tiny_llama
        eng = build_engine(cfg, params, {
            "num_slots": 4, "short_pool_len": 32, "warmup_groups": []})
        try:
            assert isinstance(eng, TieredEngine)
            out = eng.generate([1, 2, 3], max_new_tokens=2)
            assert len(out) == 2
        finally:
            eng.stop()


class TestCancellationAndStats:
    def test_cancel_queued_request(self, tiny_llama):
        eng = make_engine(tiny_llama, num_slots=1, decode_chunk=1)
        try:
            # occupy the only slot, queue a second request, cancel it
            first = eng.submit(list(range(1, 20)), max_new_tokens=30)
            second = eng.submit([9, 9, 9], max_new_tokens=30)
            second.cancel()
            out2 = second.wait(timeout=5)  # resolves immediately
            assert out2 == []
            out1 = first.wait(timeout=120)
            assert len(out1) == 30  # the live request is unaffected
        finally:
            eng.stop()

    def test_cancel_live_request_frees_slot(self, tiny_llama):
        eng = make_engine(tiny_llama, num_slots=1, decode_chunk=1)
        try:
            import time as _time

            long_req = eng.submit(list(range(1, 20)), max_new_tokens=80)
            _time.sleep(0.5)  # let it enter decode
            long_req.cancel()
            assert long_req.done.is_set()
            # the freed slot must serve a new request promptly
            out = eng.generate([1, 2, 3], max_new_tokens=3, timeout=60)
            assert len(out) == 3
        finally:
            eng.stop()

    def test_engine_stats_and_metrics_endpoint(self, tiny_llama):
        import json as _json
        import urllib.request

        from kubeflow_tpu.serving.continuous import ContinuousLlamaGenerator
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.storage import register_mem

        cfg, params = tiny_llama
        ref = register_mem("stats-llama", (cfg, params))
        m = ContinuousLlamaGenerator(
            "statgen", {"params_ref": ref, "max_new_tokens": 3,
                        "num_slots": 2, "block_size": 16,
                        "warmup_groups": []})
        srv = ModelServer()
        srv.register(m)
        srv.start()
        try:
            url = f"http://127.0.0.1:{srv.port}"
            req = urllib.request.Request(
                f"{url}/v1/models/statgen:predict",
                data=_json.dumps({"instances": [[1, 2, 3]]}).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=60).read()
            with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
                text = r.read().decode()
            assert 'kft_engine_tokens_emitted{model="statgen"} 3' in text
            assert 'kft_engine_slots_capacity{model="statgen"} 2' in text
            assert "# TYPE kft_engine_slots_capacity gauge" in text
            # chunked-prefill scheduler observability (ISSUE 2) rides the
            # same stats -> gauge export
            assert "# TYPE kft_engine_prefill_chunks_dispatched gauge" in text
            assert 'kft_engine_prefill_tokens_inflight{model="statgen"} 0' \
                in text
            assert "kft_engine_decode_stall_ms_total" in text
            # speculative-decoding observability (ISSUE 4) rides the
            # same stats -> gauge export (spec off here: counters 0)
            assert 'kft_engine_spec_tokens_proposed_total{model="statgen"}' \
                " 0" in text
            assert 'kft_engine_spec_tokens_accepted_total{model="statgen"}' \
                " 0" in text
            assert 'kft_engine_spec_dispatches_total{model="statgen"} 0' \
                in text
            assert "# TYPE kft_engine_spec_acceptance_rate gauge" in text
            # paged-KV block economy (ISSUE 6) rides the same export:
            # totals/free expose capacity, COW + prefix-block counters
            # expose the sharing economy, fragmentation the waste
            assert 'kft_engine_kv_blocks_total{model="statgen"} 16' \
                in text  # 2 slots * ceil(128/16)
            assert "# TYPE kft_engine_kv_blocks_free gauge" in text
            assert 'kft_engine_kv_blocks_cow_copies_total{model="statgen"}' \
                " 0" in text
            assert 'kft_engine_prefix_block_hits_total{model="statgen"}' \
                in text
            assert "# TYPE kft_engine_kv_fragmentation_ratio gauge" in text
            # live KV migration (ISSUE 8) rides the same export: counts,
            # bytes, failures and the latency histogram buckets
            assert 'kft_engine_kv_migrations_total{model="statgen"} 0' \
                in text
            assert 'kft_engine_kv_migrate_bytes_total{model="statgen"} 0' \
                in text
            assert ('kft_engine_kv_migrate_failures_total{model="statgen"}'
                    " 0") in text
            assert "# TYPE kft_engine_kv_migrate_latency_ms_bucket_le_5 " \
                "gauge" in text
            assert "kft_engine_kv_migrate_latency_ms_bucket_le_inf" in text
            assert "kft_engine_kv_migrate_latency_ms_count" in text
            assert "kft_engine_kv_migrate_latency_ms_sum" in text
        finally:
            srv.stop()


class TestPerRequestTemperature:
    def test_greedy_request_unaffected_by_sampling_neighbor(
            self, tiny_llama, reference_generator):
        """A temperature=0 request must stay exactly greedy even while a
        high-temperature request shares the pool dispatch."""
        eng = make_engine(tiny_llama, temperature=0.0)
        try:
            hot = eng.submit(list(range(1, 10)), max_new_tokens=6,
                             temperature=5.0)
            cold = eng.submit([1, 2, 3], max_new_tokens=6)
            got = cold.wait(300)
            hot_out = hot.wait(300)
            assert got == reference_generator.predict_batch([[1, 2, 3]])[0]
            assert len(hot_out) == 6
            assert all(0 <= t < 256 for t in hot_out)
        finally:
            eng.stop()

    def test_request_overrides_engine_default(self, tiny_llama,
                                              reference_generator):
        """Engine default temperature > 0, but a per-request temperature=0
        override must decode greedily."""
        eng = make_engine(tiny_llama, temperature=2.0)
        try:
            got = eng.generate([1, 2, 3], max_new_tokens=6, temperature=0.0)
            assert got == reference_generator.predict_batch([[1, 2, 3]])[0]
        finally:
            eng.stop()

    def test_openai_payload_temperature_reaches_engine(self):
        from kubeflow_tpu.serving.text import TextGenerator
        from kubeflow_tpu.serving.storage import register_mem

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(3), jnp.ones((1, 8), jnp.int32))["params"]
        ref = register_mem("temp-llama", (cfg, params))
        # engine default temperature 3.0: without the per-request
        # override the two calls would almost surely differ
        m = TextGenerator("tg", {
            "params_ref": ref, "max_new_tokens": 6, "num_slots": 2,
            "decode_chunk": 2, "temperature": 3.0, "warmup_groups": []})
        m.start()
        try:
            a = m.openai_completions(
                {"prompt": "hello", "max_tokens": 6, "temperature": 0})
            b = m.openai_completions(
                {"prompt": "hello", "max_tokens": 6, "temperature": 0})
            assert a["choices"][0]["text"] == b["choices"][0]["text"]
        finally:
            m.stop()


class TestNTierEngine:
    """r4 weak #7, re-anchored by ISSUE 6: ``tier_lens`` classifies
    requests by known total length and guarantees each class its
    concurrency share — as an admission policy over ONE paged pool, not
    per-tier KV pools (deleted, not wrapped)."""

    def _setup(self):
        import jax
        import jax.numpy as jnp
        from flax import linen as nn

        from kubeflow_tpu.models import llama as llamalib

        cfg = llamalib.tiny()  # max_seq_len 128
        params = nn.meta.unbox(llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        return cfg, params

    def test_three_tier_routing_and_parity(self):
        import jax

        from kubeflow_tpu.serving.continuous import (
            ContinuousEngine,
            TieredEngine,
        )

        cfg, params = self._setup()
        ref = ContinuousEngine(cfg, params, num_slots=3, decode_chunk=2,
                               eos_id=None, prefix_cache=False)
        prompts = [[1, 2, 3], [5] * 30, [9] * 70]
        try:
            want = [ref.generate(p, max_new_tokens=4) for p in prompts]
        finally:
            ref.stop()
        eng = TieredEngine(
            cfg, params, num_slots=6, tier_lens=[16, 64],
            tier_slots=[2, 2], decode_chunk=2, eos_id=None,
            prefix_cache=False)
        try:
            assert len(eng.pools) == 1  # ONE paged pool, ladder = policy
            assert eng.quotas == [2, 2, 2]
            # classification still splits the ladder (totals 7, 34, 74)
            import types

            classes = [eng._classify(types.SimpleNamespace(
                prompt=p, max_new_tokens=4)) for p in prompts]
            assert classes == [0, 1, 2]
            got = [eng.generate(p, max_new_tokens=4) for p in prompts]
            st = eng.stats()
            assert st["tokens_emitted"] == 12
            assert [c["quota"] for c in st["classes"]] == [2, 2, 2]
        finally:
            eng.stop()
        assert got == want

    def test_build_engine_tier_lens(self):
        from kubeflow_tpu.serving.continuous import TieredEngine, build_engine

        cfg, params = self._setup()
        eng = build_engine(cfg, params, {
            "num_slots": 6, "tier_lens": [16, 64], "warmup_groups": [],
            "prefix_cache": False})
        try:
            assert isinstance(eng, TieredEngine)
            assert eng.caps == [16, 64]
            out = eng.generate([1, 2, 3], max_new_tokens=3)
            assert len(out) == 3
        finally:
            eng.stop()

    def test_bad_tier_config_rejected(self):
        import pytest

        from kubeflow_tpu.serving.continuous import TieredEngine

        cfg, params = self._setup()
        with pytest.raises(ValueError, match="ascending"):
            TieredEngine(cfg, params, tier_lens=[64, 16], num_slots=6)
        with pytest.raises(ValueError, match="uncapped"):
            TieredEngine(cfg, params, tier_lens=[16, 64],
                         tier_slots=[3, 3], num_slots=6)


class TestSamplingFilters:
    """Per-request top_p / top_k (the OpenAI sampling family) — HF warp
    order temperature -> top-k -> top-p, per slot, in one dispatch;
    greedy-only pools skip the vocab sort via lax.cond."""

    def _engine(self):
        import jax
        import jax.numpy as jnp
        from flax import linen as nn

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        cfg = llamalib.tiny()
        params = nn.meta.unbox(llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
        return cfg, params, ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=2, eos_id=None,
            prefix_cache=False)

    def test_degenerate_filters_equal_greedy(self):
        _, _, eng = self._engine()
        try:
            greedy = eng.generate([1, 2, 3], max_new_tokens=5)
            k1 = eng.generate([1, 2, 3], max_new_tokens=5,
                              temperature=0.8, top_k=1)
            p0 = eng.generate([1, 2, 3], max_new_tokens=5,
                              temperature=0.8, top_p=1e-6)
        finally:
            eng.stop()
        assert k1 == greedy
        assert p0 == greedy

    def test_top_k_restricts_support(self):
        import jax.numpy as jnp
        import numpy as np

        from kubeflow_tpu.models import llama as llamalib

        cfg, params, eng = self._engine()
        try:
            logits = llamalib.Llama(cfg).apply(
                {"params": params}, jnp.asarray([[1, 2, 3]], jnp.int32))
            top5 = set(np.asarray(
                logits[0, -1], np.float32).argsort()[-5:].tolist())
            outs = {eng.generate([1, 2, 3], max_new_tokens=1,
                                 temperature=5.0, top_k=5)[0]
                    for _ in range(12)}
            # all sampled tokens inside the top-5 support, and the
            # filter actually bites (unfiltered T=5 escapes it)
            wild = {eng.generate([1, 2, 3], max_new_tokens=1,
                                 temperature=5.0)[0] for _ in range(12)}
        finally:
            eng.stop()
        assert outs <= top5, (sorted(outs), sorted(top5))
        assert len(outs) > 1  # still sampling, not collapsed to greedy
        assert not (wild <= top5)

    def test_mixed_slots_one_dispatch(self):
        """Greedy, top-k and unfiltered requests coexist in one pool:
        the greedy request's tokens must be bit-stable regardless of
        its neighbors' sampling settings."""
        _, _, eng = self._engine()
        try:
            want = eng.generate([1, 2, 3], max_new_tokens=4)
            reqs = [
                eng.submit([1, 2, 3], max_new_tokens=4),
                eng.submit([4, 5, 6], max_new_tokens=4,
                           temperature=2.0, top_k=3),
                eng.submit([7, 8, 9], max_new_tokens=4, temperature=1.5),
            ]
            outs = [r.wait(300) for r in reqs]
        finally:
            eng.stop()
        assert outs[0] == want
        assert all(len(o) == 4 for o in outs)

    def test_openai_payload_passthrough(self):
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg, params, eng = self._engine()
        eng.stop()
        ref = register_mem("samplellama", (cfg, params))
        m = TextGenerator("t", {"params_ref": ref, "max_new_tokens": 4,
                                "warmup_groups": []})
        m.start()
        try:
            out = m.openai_completions({
                "prompt": "ab", "max_tokens": 4,
                "temperature": 0.9, "top_p": 0.01, "top_k": 1})
            greedy = m.openai_completions({"prompt": "ab", "max_tokens": 4})
            assert (out["choices"][0]["text"]
                    == greedy["choices"][0]["text"])
        finally:
            m.stop()


class TestDispatchHygiene:
    """jit_recompiles_total (analysis/runtime.py recompile_guard): the
    engine must reach steady state — chunked prefill riding decode
    dispatches, admissions, retirement, slot reuse — without ever
    re-tracing a compiled program.  A recompile mid-serving freezes the
    whole pool for a trace+compile; the guard wraps every cached program
    and this assertion is the platform's proof the dispatch path stays
    shape-stable (ISSUE 3 acceptance)."""

    def test_zero_steady_state_recompiles_chunked(self, tiny_llama):
        eng = make_engine(tiny_llama, decode_chunk=2, prefill_budget=4)
        try:
            eng.warmup()
            # wave 1: concurrent chunked admissions fused into decode
            reqs = [eng.submit([1, 2, 3, 4, 5, 6, 7], max_new_tokens=6)
                    for _ in range(3)]
            for r in reqs:
                r.wait(300)
            # wave 2: slot reuse + prefix-cache route after retirement
            reqs = [eng.submit([1, 2, 3, 4, 5, 6, 7, 8, 9], max_new_tokens=4)
                    for _ in range(2)]
            for r in reqs:
                r.wait(300)
            stats = eng.stats()
            assert stats["prefill_chunks_dispatched"] > 0  # chunked ran
            assert stats["jit_recompiles_total"] == 0, stats
        finally:
            eng.stop()

    def test_zero_recompiles_legacy_burst_padding(self, tiny_llama):
        """A 3-request burst into a {1, num_slots}-warmed legacy pool
        must pad up to the warmed group shape (_pad_group), not compile
        a fresh [2, bucket] prefill mid-serving — the exact stall the
        recompile guard caught when the gauge landed."""
        eng = make_engine(tiny_llama, decode_chunk=2, prefill_budget=0)
        try:
            eng.warmup()
            reqs = [eng.submit([i + 1, i + 2, i + 3], max_new_tokens=5)
                    for i in range(3)]
            for r in reqs:
                r.wait(300)
            assert eng.stats()["jit_recompiles_total"] == 0
        finally:
            eng.stop()
