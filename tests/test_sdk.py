"""SDK clients: KatibClient.tune() over real trial processes and
KServeClient CRUD + data plane (the reference's three Python clients)."""

import pytest

from kubeflow_tpu.api.common import ObjectMeta
from kubeflow_tpu.api.inference import (
    ComponentSpec,
    InferenceService,
    InferenceServiceSpec,
)
from kubeflow_tpu.controlplane.cluster import Cluster
from kubeflow_tpu.runtime.platform import LocalPlatform
from kubeflow_tpu.sdk import KatibClient, KServeClient, search_double


@pytest.mark.e2e
class TestKatibClient:
    def test_tune_one_call(self, tmp_path):
        with LocalPlatform(num_hosts=2, chips_per_host=4,
                           root_dir=str(tmp_path)) as p:
            client = KatibClient(p)
            exp = client.tune(
                name="lr-sweep",
                entrypoint="tests.hpo_objective:objective_main",
                parameters={"lr": search_double(0.001, 0.1)},
                objective_metric="score",
                algorithm="tpe",
                max_trials=4,
                parallel_trials=2,
                timeout=300,
            )
            assert exp.status.completed
            assert exp.status.trials_succeeded == 4
            best = client.get_optimal_hyperparameters("lr-sweep")
            assert best["value"] is not None
            assert 0.001 <= best["assignments"]["lr"] <= 0.1
            trials = client.list_trials("lr-sweep")
            assert len(trials) == 4
            assert all(t.status.phase == "Succeeded" for t in trials)


class TestKServeClient:
    def test_crud_wait_predict_explain(self):
        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        cluster.enable_serving()
        with cluster:
            client = KServeClient(cluster)
            client.create(InferenceService(
                metadata=ObjectMeta(name="svc"),
                spec=InferenceServiceSpec(
                    predictor=ComponentSpec(
                        handler="tests.test_serving:FirstTwoSum"),
                    explainer=ComponentSpec(
                        handler="kubeflow_tpu.serving.explainer:OcclusionExplainer",
                        config={"num_segments": 4}),
                )))
            isvc = client.wait_isvc_ready("svc")
            assert isvc.status.url
            assert client.predict("svc", [[1.0, 2.0, 5.0, 5.0]]) == [3.0]
            exp = client.explain("svc", [[3.0, 5.0, 1.0, 2.0]])
            assert exp[0]["attributions"] == [3.0, 5.0, 0.0, 0.0]
            client.delete("svc")
            assert client.get("svc") is None

    def test_wait_surfaces_failure(self):
        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        cluster.enable_serving()
        with cluster:
            client = KServeClient(cluster)
            client.create({
                "kind": "InferenceService",
                "metadata": {"name": "bad"},
                "spec": {"predictor": {"modelFormat": {"name": "mystery"}}},
            })
            with pytest.raises(RuntimeError, match="mystery"):
                client.wait_isvc_ready("bad", timeout=20)
