"""Pallas flash attention vs the dense reference (interpret mode on CPU —
the same kernel code that compiles for the TPU MXU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.llama import _causal_attention
from kubeflow_tpu.ops.flash_attention import flash_attention


@pytest.fixture(scope="module")
def qkv():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    b, s, h, kv, d = 2, 128, 4, 2, 32
    return (
        jax.random.normal(k1, (b, s, h, d)),
        jax.random.normal(k2, (b, s, kv, d)),
        jax.random.normal(k3, (b, s, kv, d)),
    )


@pytest.mark.parametrize("block", [32, 64, 128])
def test_forward_matches_dense(qkv, block):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    out = flash_attention(q, k, v, q_per_kv=2, block_q=block, block_k=block)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_mixed_block_sizes(qkv):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    out = flash_attention(q, k, v, q_per_kv=2, block_q=64, block_k=32)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_gradients_match_dense(qkv):
    q, k, v = qkv

    def floss(q, k, v):
        return (flash_attention(q, k, v, q_per_kv=2, block_q=64, block_k=64) ** 2).sum()

    def dloss(q, k, v):
        return (_causal_attention(q, k, v, 2) ** 2).sum()

    gf = jax.grad(floss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dloss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5)


def test_mha_no_gqa():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    b, s, h, d = 1, 64, 2, 16
    q = jax.random.normal(k1, (b, s, h, d))
    k = jax.random.normal(k2, (b, s, h, d))
    v = jax.random.normal(k3, (b, s, h, d))
    ref = np.asarray(_causal_attention(q, k, v, 1))
    out = flash_attention(q, k, v, q_per_kv=1, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_model_flash_impl_matches_dense():
    from kubeflow_tpu.models import llama

    toks = jnp.ones((2, 32), jnp.int32)
    dense_model = llama.Llama(llama.tiny())
    params = dense_model.init(jax.random.PRNGKey(0), toks)
    expected = np.asarray(dense_model.apply(params, toks))
    flash_model = llama.Llama(llama.tiny(attention_impl="flash"))
    out = np.asarray(flash_model.apply(params, toks))
    np.testing.assert_allclose(out, expected, atol=2e-4, rtol=2e-4)
