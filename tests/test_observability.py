"""Request-lifecycle tracing (ISSUE 13): phase-attributed latency
across the whole serving path.

Layers, matching the tentpole:

- TRACE UNITS: Span/Trace/TraceSink/Tracer — the contiguous phase
  track (phase durations tile the root span), bounded ring + span cap,
  sampling, X-KFT-Trace header round-trip, the autoscaler summary;
- ENGINE: a traced request's phases tile its end-to-end latency within
  5%, dispatch spans carry program family + warmed rung, and
  ``sample=0`` creates NO spans on the dispatch path (the
  zero-overhead contract);
- THE PINNED E2E TRACE: router -> prefill tier -> ``kv_migrate``
  handoff -> decode tier, one trace id across the router and replica
  sinks, every phase span parent-linked, phase durations summing to
  within 5% of the observed end-to-end latency;
- EXPOSITION: ``kft_phase_seconds`` histograms (with exemplar trace
  ids) and the ServerMetrics request-latency histogram on /metrics,
  promtool-style linted (unique series, valid names, escaped label
  values, no per-tenant metric-NAME suffixes — the PR 8 round-9
  regression class) on BOTH the server and the router;
- SATELLITES: the cluster prefix poller's heat gauges, the
  ``metrics-contract`` runtime audit across a stats pair, and the
  ``tracing``/``prefix_poll_s`` knobs as ONE Failed status at ISvc
  conf-freeze.
"""

import json
import re
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.analysis.runtime import audit_stats_pair
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.trace import (
    MAX_SPANS_PER_TRACE,
    Trace,
    Tracer,
    TraceSink,
    parse_header,
    parse_wire_context,
    validate_tracing,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


@pytest.fixture(scope="module")
def text_ref(tiny_llama):
    from kubeflow_tpu.serving.storage import register_mem

    return register_mem("observability-tests", tiny_llama)


def post(url: str, payload: dict, headers=None, timeout: float = 120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, dict(e.headers), body


def get_text(url: str, timeout: float = 30.0, headers=None) -> str:
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read().decode()


# -- trace units ----------------------------------------------------------


class TestTraceUnits:
    def test_phase_track_tiles_the_root(self):
        tr = Trace(name="request")
        tr.phase("a")
        time.sleep(0.02)
        tr.phase("b")
        time.sleep(0.01)
        with tr.span("detail", k=1) as sp:
            time.sleep(0.005)
        tr.phase("c")
        time.sleep(0.01)
        tr.finish()
        d = tr.to_dict()
        names = [p["name"] for p in d["phases"]]
        assert names == ["a", "b", "c"]
        # contiguity: each phase starts exactly where the previous
        # ended, so the sum tiles the span from first phase to finish
        total = sum(p["duration_s"] for p in d["phases"])
        assert abs(total - d["duration_s"]) < 0.005
        # detail spans parent to the phase active at open
        assert sp.parent_id == tr.phases[1].span_id
        assert d["spans"][0]["attrs"]["k"] == 1

    def test_phase_reentry_is_idempotent(self):
        tr = Trace()
        p1 = tr.phase("decode")
        p2 = tr.phase("decode")
        assert p1 is p2
        assert len(tr.phases) == 1

    def test_finish_idempotent_and_durations_freeze(self):
        tr = Trace()
        tr.phase("x")
        tr.finish()
        d1 = tr.duration_s
        time.sleep(0.01)
        tr.finish()
        assert tr.duration_s == d1

    def test_span_cap_counts_drops(self):
        tr = Trace()
        for _ in range(MAX_SPANS_PER_TRACE + 10):
            tr.begin("s").done()
        assert len(tr.spans) == MAX_SPANS_PER_TRACE
        assert tr.dropped_spans == 11  # root occupies spans[0]

    def test_header_roundtrip_and_malformed(self):
        tr = Trace()
        tid, parent = parse_header(tr.header())
        assert tid == tr.trace_id and parent == tr.root.span_id
        assert parse_header(None) is None
        assert parse_header("") is None
        assert parse_header("onlyid") is None
        assert parse_header("a:b:0") is None  # unsampled flag
        assert parse_wire_context(tr.wire_context()) == (tid, parent)
        assert parse_wire_context({"id": ""}) is None
        assert parse_wire_context("nope") is None

    def test_sampling_and_continuation(self):
        t0 = Tracer(sample=0.0)
        assert t0.start() is None  # never sampled fresh
        upstream = Trace()
        cont = t0.start(header=upstream.header())
        assert cont is not None and cont.trace_id == upstream.trace_id
        assert cont.root.parent_id == upstream.root.span_id
        t1 = Tracer(sample=1.0)
        assert t1.start() is not None

    def test_ring_bound_and_slowest(self):
        sink = TraceSink(ring=4)
        for i in range(8):
            tr = Trace()
            tr.phase("p")
            time.sleep(0.001 * (i + 1))
            sink.finish(tr)
        assert len(sink.traces()) == 4
        assert sink.finished_total == 8
        slow = sink.slowest(2)
        assert len(slow) == 2
        assert slow[0]["duration_s"] >= slow[1]["duration_s"]
        # jsonl is one object per line
        rows = [json.loads(ln) for ln in sink.jsonl().splitlines()]
        assert len(rows) == 4 and all("trace_id" in r for r in rows)

    def test_summary_aggregates_queue_wait_and_stalls(self):
        sink = TraceSink(ring=16)
        for _ in range(3):
            tr = Trace()
            tr.meta["class"] = "gold"
            tr.phase("router.door")
            time.sleep(0.005)
            tr.phase("engine.decode")
            sink.finish(tr)
        shed = Trace()
        shed.meta["class"] = "gold"
        shed.meta["stall"] = "shed:rate_limited"
        sink.finish(shed)
        s = sink.summary(window_s=60.0)
        gold = s["classes"]["gold"]
        assert gold["traces"] == 4
        assert gold["queue_wait_sum_s"] >= 0.015
        assert gold["stalls"] == {"shed:rate_limited": 1}
        assert gold["phases"]["router.door"]["count"] == 3
        # an expired window is empty
        assert sink.summary(window_s=0.0)["classes"] == {}

    def test_validate_tracing(self):
        assert validate_tracing({"sample": 0.5, "ring": 8}) == {
            "sample": 0.5, "ring": 8}
        assert validate_tracing({})["sample"] == 0.1
        for bad in ({"sample": 7}, {"sample": -0.1}, {"ring": 0},
                    {"ring": "lots"}, {"bogus": 1}, "nope",
                    {"sample": None}):
            with pytest.raises(ValueError):
                validate_tracing(bad)

    def test_phase_metrics_render_through_shared_histograms(self):
        sink = TraceSink()
        tr = Trace()
        tr.phase("engine.decode")
        time.sleep(0.002)
        sink.finish(tr)
        sink.observe_phase("kv.host_spill", 0.5)
        lines = sink.phase_metrics(base_labels='model="m"',
                                   exemplars=True)
        text = "\n".join(lines)
        assert lines[0] == "# TYPE kft_phase_seconds histogram"
        assert ('kft_phase_seconds_bucket{model="m",'
                'phase="engine.decode",le="+Inf"}') in text
        # the exemplar carries the trace id on the +Inf bucket —
        # OpenMetrics syntax, so it renders ONLY when asked for
        assert f'trace_id="{tr.trace_id}"' in text
        assert 'kft_phase_seconds_count{model="m",phase="kv.host_spill"} 1' \
            in text
        assert "trace_id" not in "\n".join(
            sink.phase_metrics(base_labels='model="m"'))

    def test_adopted_traces_reap_on_read(self):
        import threading

        tracer = Tracer(sample=1.0, ring=8)
        upstream = Trace()
        tr = tracer.adopt(upstream.wire_context())
        assert tr is not None and tr.trace_id == upstream.trace_id
        done = threading.Event()
        tracer.watch(done, tr)
        assert tracer.reap() == 0  # not finished yet
        assert tracer.sink.stats()["traces_finished_total"] == 0
        done.set()
        assert tracer.reap() == 1  # finalized on the reader's thread
        assert tracer.sink.stats()["traces_finished_total"] == 1
        assert tracer.reap() == 0  # idempotent


# -- engine ---------------------------------------------------------------


LONG = list(range(1, 65))


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("block_size", 16)
    kw.setdefault("prefill_budget", 16)
    return ContinuousEngine(cfg, params, **kw)


@pytest.fixture(scope="class")
def shared_engine(tiny_llama):
    """ONE engine for the whole engine-tracing class: the tests vary
    the tracer (swappable), not the pool — rebuilding per test would
    pay the compile set three times for nothing."""
    eng = make_engine(tiny_llama)
    yield eng
    eng.stop()


class TestEngineTracing:
    def test_phases_tile_e2e_with_family_and_rung(self, shared_engine):
        eng = shared_engine
        tracer = Tracer(sample=1.0, ring=8)
        eng.tracer = tracer
        tr = tracer.start(name="request")
        req = eng.submit(LONG, max_new_tokens=16, trace=tr)
        req.wait(120)
        tracer.finish(tr)
        d = tr.to_dict()
        names = [p["name"] for p in d["phases"]]
        assert names[0] == "engine.queue"
        assert "engine.prefill" in names
        assert "engine.decode" in names
        total = sum(p["duration_s"] for p in d["phases"])
        assert abs(total - d["duration_s"]) <= 0.05 * d["duration_s"]
        spans = d["spans"]
        fams = {s["attrs"]["family"] for s in spans
                if s["name"] == "dispatch"}
        assert fams & {"paged_decode", "paged_fused"}
        rungs = {s["attrs"]["rung"] for s in spans
                 if s["name"] == "dispatch"}
        assert all(isinstance(r, int) and r > 0 for r in rungs)
        assert any(s["name"] == "prefill.chunk" for s in spans)
        # parent links: every span/phase anchors to a known id
        ids = {d["root"]["span_id"]}
        ids |= {p["span_id"] for p in d["phases"]}
        ids |= {s["span_id"] for s in spans}
        for s in d["phases"] + spans:
            assert s["parent_id"] in ids, s

    def test_sample_zero_creates_no_spans(self, shared_engine):
        eng = shared_engine
        tracer = Tracer(sample=0.0, ring=8)
        eng.tracer = tracer
        assert tracer.start() is None
        req = eng.submit(LONG, max_new_tokens=8)  # untraced
        req.wait(120)
        assert req.trace is None
        assert tracer.sink.stats()["traces_finished_total"] == 0
        assert tracer.sink.phase_metrics() == []

    def test_stats_pair_honors_metrics_contract(self, shared_engine):
        """The metrics-contract runtime half (ISSUE 13 satellite):
        every `_total` stats counter is monotone across real traffic
        and every numeric key renders to a valid Prometheus name."""
        eng = shared_engine
        s0 = eng.stats()
        eng.generate(LONG, max_new_tokens=8)
        assert audit_stats_pair(s0, eng.stats()) == []

    def test_audit_stats_pair_catches_violations(self):
        assert audit_stats_pair({"a_total": 5}, {"a_total": 3}) != []
        assert audit_stats_pair({"a_total": 5}, {}) != []
        assert audit_stats_pair({"bad-name": 1}, {"bad-name": 1}) != []
        assert audit_stats_pair(
            {"a_total": 1, "g": 2.5}, {"a_total": 1, "g": 0.5}) == []


class TestTracingLeavesMechanismsClean:
    """The acceptance bar: jit_recompiles_total == 0 and BlockLedger
    audits clean with tracing enabled across migration, resize and
    hibernate — tracing changes what is OBSERVED, never what is
    dispatched."""

    @staticmethod
    def _submit_traced_until(eng, tracer, n_tokens, max_new=120):
        tr = tracer.start(name="request")
        req = eng.submit(LONG, max_new_tokens=max_new, trace=tr)
        deadline = time.time() + 120
        while len(req.tokens) < n_tokens:
            assert time.time() < deadline, "no progress"
            time.sleep(0.01)
        return req, tr

    @pytest.mark.slow
    def test_traced_migration_and_hibernate_zero_recompiles(
            self, tiny_llama, tmp_path):
        from kubeflow_tpu.analysis.runtime import BlockLedger
        from kubeflow_tpu.serving.storage import KvSpillStore

        ledger = BlockLedger()
        tracer = Tracer(sample=1.0, ring=16)
        src = make_engine(tiny_llama)
        dst = make_engine(tiny_llama)
        store = KvSpillStore(str(tmp_path / "spill"))
        for e in (src, dst):
            e.attach_block_ledger(ledger)
            e.tracer = tracer
            e.attach_spill_store(store)
        try:
            # live migration of a TRACED request mid-decode
            req, tr = self._submit_traced_until(src, tracer, 8)
            snap = src.export_sequence(req)
            assert snap is not None and snap.get("trace")
            dst.import_sequence(snap, req=req)
            src.release_sequence(req)
            req.wait(120)
            tracer.finish(tr)
            names = [p.name for p in tr.phases]
            assert "engine.decode" in names
            spans = {s.name for s in tr.spans}
            assert {"kv.export", "kv.import"} <= spans
            # hibernate/thaw a traced request
            req2, tr2 = self._submit_traced_until(dst, tracer, 8)
            assert dst.hibernate_sequence(req2, "sess-1")
            assert not req2.done.is_set()
            thawed, info = dst.thaw_sequence("sess-1", req=req2)
            thawed.wait(120)
            tracer.finish(tr2)
            assert not info["degraded"]
            names2 = [p.name for p in tr2.phases]
            assert "kv.hibernate" in names2 and "kv.thaw" in names2
            for e in (src, dst):
                assert e.audit_blocks() == []
                assert e.stats()["jit_recompiles_total"] == 0
                assert e.stats()["kv_blocks_leaked_total"] == 0
            assert ledger.conservation_errors == []
        finally:
            src.stop()
            dst.stop()

    @pytest.mark.slow
    def test_traced_resize_records_phase_decomposition(self, tiny_llama):
        from kubeflow_tpu.analysis.runtime import BlockLedger
        from kubeflow_tpu.serving.resize import GangResizer

        ledger = BlockLedger()
        tracer = Tracer(sample=1.0, ring=16)
        eng = make_engine(tiny_llama)
        eng.attach_block_ledger(ledger)
        eng.tracer = tracer  # GangResizer picks it up from the engine
        rz = GangResizer(eng, warmup_groups=[])
        try:
            req, tr = self._submit_traced_until(eng, tracer, 8)
            new = rz.resize(None)
            req.wait(120)
            tracer.finish(tr)
            # the request's own trace shows the stall cause
            names = [p.name for p in tr.phases]
            assert "resize.frozen" in names
            assert names[-1] == "engine.decode"
            # the per-resize trace decomposes the Tenplex phases
            resize_traces = [d for d in tracer.sink.traces()
                             if d["root"]["name"] == "resize"]
            assert len(resize_traces) == 1
            rnames = [p["name"] for p in resize_traces[0]["phases"]]
            assert rnames == ["resize.export", "resize.reshard",
                              "resize.commit", "resize.cutover"]
            assert new.audit_blocks() == []
            assert new.stats()["jit_recompiles_total"] == 0
            assert ledger.conservation_errors == []
        finally:
            rz.engine.stop()


# -- the pinned e2e trace -------------------------------------------------


def _parse_traces(text: str) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for ln in text.splitlines():
        d = json.loads(ln)
        out.setdefault(d["trace_id"], []).append(d)
    return out


class TestEndToEndTrace:
    def test_router_prefill_migrate_decode_trace(self, text_ref):
        """THE acceptance trace: one sampled request crosses router ->
        prefill tier -> kv_migrate wire handoff -> decode tier; every
        phase span parent-links, and the replica-side phase durations
        sum to within 5% of the observed end-to-end latency."""
        from kubeflow_tpu.serving.controller import Router
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.text import TextGenerator

        srv = ModelServer()
        srv.register(TextGenerator("m", {
            "params_ref": text_ref, "tokenizer": "bytes",
            "num_slots": 2, "decode_chunk": 2, "block_size": 16,
            "prefill_budget": 16, "max_new_tokens": 24,
            "prefix_cache": False, "warmup_groups": [],
            "disaggregation": {"prefill": 1, "decode": 1, "wire": True},
            "tracing": {"sample": 1.0, "ring": 32},
        }))
        srv.start()
        router = Router(activate=lambda: None)
        router.set_backends([srv.url])
        router.configure_tracing({"sample": 1.0, "ring": 32})
        try:
            code, _, body = post(
                router.url + "/openai/v1/completions",
                {"model": "m", "prompt": "trace me through the tiers",
                 "max_tokens": 24})
            assert code == 200
            assert body["choices"][0]["text"]
            # finalization runs on the handler threads after the
            # response bytes hit the wire: poll briefly for both sinks
            deadline = time.time() + 5
            rt = st = {}
            while time.time() < deadline and not (
                    set(rt) & set(st)):
                rt = _parse_traces(get_text(router.url + "/traces"))
                st = _parse_traces(get_text(srv.url + "/traces"))
                time.sleep(0.02)
            shared = set(rt) & set(st)
            assert len(shared) == 1, (set(rt), set(st))
            tid = shared.pop()
            router_tr = rt[tid][0]
            replica_tr = st[tid][0]
            r_names = [p["name"] for p in router_tr["phases"]]
            assert r_names == ["router.door", "router.route",
                               "router.forward"]
            names = [p["name"] for p in replica_tr["phases"]]
            assert names[0] == "replica.door"
            assert "engine.queue" in names
            assert "engine.prefill" in names
            assert "engine.handoff" in names
            # decode happens on the DECODE tier after the wire handoff
            assert names[-1] == "engine.decode"
            assert names.index("engine.handoff") > \
                names.index("engine.prefill")
            span_names = {s["name"] for s in replica_tr["spans"]}
            assert {"kv.export", "kv.transfer",
                    "prefill.chunk", "dispatch"} <= span_names
            # the replica continued the ROUTER's trace decision
            assert replica_tr["root"]["parent_id"] == \
                router_tr["root"]["span_id"]
            # parent links hold across the whole tree
            ids = {replica_tr["root"]["span_id"]}
            ids |= {p["span_id"] for p in replica_tr["phases"]}
            ids |= {s["span_id"] for s in replica_tr["spans"]}
            for s in replica_tr["phases"] + replica_tr["spans"]:
                assert s["parent_id"] in ids, s
            # THE 5% BAR: phase durations tile the end-to-end latency
            total = sum(p["duration_s"] for p in replica_tr["phases"])
            e2e = replica_tr["duration_s"]
            assert abs(total - e2e) <= 0.05 * e2e, (total, e2e)
            # handoff actually crossed the kv_migrate wire
            eng = srv.models()["m"].engine
            assert eng.stats()["kv_migrations_total"] >= 1
            assert eng.stats()["jit_recompiles_total"] == 0
        finally:
            router.stop()
            srv.stop()


# -- exposition lint (promtool-style) -------------------------------------


_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{([^}]*)\})?"                     # optional labels
    r" (-?[0-9.eE+-]+|NaN)"                 # value
    r"(?: # \{.*\} -?[0-9.eE+-]+)?$")       # optional exemplar
_LABEL = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"$')


def prom_lint(text: str) -> list[str]:
    """Promtool-style exposition lint: parseable samples, valid names,
    escaped label values, one TYPE per family, unique (name, labels)
    series."""
    errors: list[str] = []
    types: dict[str, str] = {}
    series: set[tuple] = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) != 4:
                    errors.append(f"bad TYPE line: {line}")
                    continue
                if parts[2] in types:
                    errors.append(f"duplicate TYPE for {parts[2]}")
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE.match(line)
        if m is None:
            errors.append(f"unparseable sample: {line!r}")
            continue
        name, labels = m.group(1), m.group(2)
        if labels:
            # split on commas OUTSIDE quoted values
            for pair in re.split(r',(?=[a-zA-Z_][a-zA-Z0-9_]*=")',
                                 labels):
                if not _LABEL.match(pair):
                    errors.append(f"bad label pair {pair!r} in: {line}")
        key = (name, labels or "")
        if key in series:
            errors.append(f"duplicate series: {line}")
        series.add(key)
    return errors


class TestExposition:
    def test_latency_histogram_and_traces_endpoint(self, text_ref):
        """ONE server drives both read surfaces: the request-latency
        histogram satellite on /metrics (with the phase histograms
        riding the same scrape) and the /traces JSONL + ?slowest=N
        view."""
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.text import TextGenerator

        srv = ModelServer()
        srv.register(TextGenerator("m", {
            "params_ref": text_ref, "tokenizer": "bytes",
            "num_slots": 2, "decode_chunk": 2, "block_size": 16,
            "max_new_tokens": 8, "warmup_groups": [],
            "tracing": {"sample": 1.0, "ring": 8},
        }))
        srv.start()
        try:
            code, _, _ = post(srv.url + "/openai/v1/completions",
                              {"model": "m", "prompt": "hi",
                               "max_tokens": 2})
            assert code == 200
            text = get_text(srv.url + "/metrics")
            assert "# TYPE kft_request_latency_seconds histogram" in text
            assert ('kft_request_latency_seconds_bucket{model="m",'
                    'le="+Inf"} 1') in text
            assert 'kft_request_latency_seconds_count{model="m"} 1' \
                in text
            # the phase histograms ride the same scrape with the
            # sampled request's phases
            assert 'kft_phase_seconds_bucket{model="m",' \
                   'phase="engine.decode"' in text
            assert 'kft_trace_traces_finished_total{model="m"} 1' in text
            assert prom_lint(text) == [], prom_lint(text)[:5]
            # exemplars are OpenMetrics syntax: absent on the classic
            # scrape (a trailer would fail real Prometheus parsers),
            # present + # EOF-terminated when negotiated
            assert "trace_id" not in text
            om = get_text(srv.url + "/metrics", headers={
                "Accept": "application/openmetrics-text"})
            assert "trace_id=" in om and om.endswith("# EOF\n")
            # /traces: a second, slower request; poll briefly —
            # finalization runs on the handler thread after the
            # response bytes hit the wire
            post(srv.url + "/openai/v1/completions",
                 {"model": "m", "prompt": "hello", "max_tokens": 8})
            deadline = time.time() + 5
            rows = []
            while time.time() < deadline and len(rows) < 2:
                rows = [json.loads(ln) for ln in get_text(
                    srv.url + "/traces").splitlines()]
                time.sleep(0.02)
            assert len(rows) == 2
            slow = [json.loads(ln) for ln in get_text(
                srv.url + "/traces?slowest=1").splitlines()]
            assert len(slow) == 1
            assert slow[0]["duration_s"] == max(
                r["duration_s"] for r in rows)
        finally:
            srv.stop()

    def test_scrapes_lint_clean_with_tenant_classes(self, text_ref):
        """The PR 8 round-9 regression class, now promtool-pinned on
        BOTH endpoints: hyphenated tenant/class names must appear only
        as label VALUES, never in metric names."""
        from kubeflow_tpu.serving.controller import Router
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.text import TextGenerator
        from kubeflow_tpu.serving.traffic import TrafficPlane

        srv = ModelServer()
        srv.register(TextGenerator("m", {
            "params_ref": text_ref, "tokenizer": "bytes",
            "num_slots": 2, "decode_chunk": 2, "block_size": 16,
            "max_new_tokens": 4, "warmup_groups": [],
            "qos": {"team-a": {"rate": 100}},
            "tracing": {"sample": 1.0, "ring": 8},
        }))
        srv.start()
        router = Router(activate=lambda: None)
        router.set_backends([srv.url])
        router.set_traffic(TrafficPlane({"team-a": {"rate": 100}}))
        router.configure_tracing({"sample": 1.0, "ring": 8})
        try:
            code, _, _ = post(router.url + "/openai/v1/completions",
                              {"model": "m", "prompt": "x",
                               "max_tokens": 4, "user": "team-a"})
            assert code == 200
            for url in (srv.url, router.url):
                text = get_text(url + "/metrics")
                assert prom_lint(text) == [], (url,
                                               prom_lint(text)[:5])
                for line in text.splitlines():
                    if line.startswith("#") or not line.strip():
                        continue
                    name = line.split("{")[0].split(" ")[0]
                    assert "team-a" not in name, line
                assert 'class="team-a"' in text
        finally:
            router.stop()
            srv.stop()


# -- cluster prefix poller (satellite) ------------------------------------


class TestClusterPrefixPoller:
    def test_poller_exports_cluster_heat(self, text_ref):
        from kubeflow_tpu.serving.controller import Router
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.text import TextGenerator

        srv = ModelServer()
        srv.register(TextGenerator("m", {
            "params_ref": text_ref, "tokenizer": "bytes",
            "num_slots": 2, "decode_chunk": 2, "block_size": 4,
            "max_new_tokens": 4, "warmup_groups": [],
        }))
        srv.start()
        router = Router(activate=lambda: None)
        router.set_backends([srv.url])
        try:
            # generate so the replica advertises prefix-digest rows
            code, _, _ = post(srv.url + "/openai/v1/completions",
                              {"model": "m",
                               "prompt": "a shared prefix long enough "
                                         "to fill blocks",
                               "max_tokens": 4})
            assert code == 200
            router.start_prefix_poller(interval_s=999.0)
            rows = router.prefix_poller.poll_once()
            assert rows > 0
            heat = router.prefix_poller.heat()
            assert heat and all(v == 1 for v in heat.values())
            text = get_text(router.url + "/metrics")
            assert "# TYPE kft_cluster_prefix_replicas gauge" in text
            assert "kft_cluster_prefix_replicas{key=" in text
            assert f"kft_cluster_prefix_keys {len(heat)}" in text
            assert prom_lint(text) == [], prom_lint(text)[:5]
            # the registry learned the same keys (locate answers)
            assert router.prefix_poller.registry.stats()[
                "kv_registry_entries"] == len(heat)
        finally:
            router.stop()
            srv.stop()


# -- conf-freeze (satellite) ----------------------------------------------


class TestConfFreeze:
    def test_bad_tracing_knobs_are_one_failed_status(self):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        cases = {
            "bad-trace-sample": {"tracing": {"sample": 7}},
            "bad-trace-ring": {"tracing": {"ring": 0}},
            "bad-trace-shape": {"tracing": {"bogus": 1}},
            "bad-poll": {"prefix_poll_s": -1},
        }
        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            for name, cfg in cases.items():
                cluster.store.create(InferenceService(
                    metadata=ObjectMeta(name=name),
                    spec=InferenceServiceSpec(predictor=ComponentSpec(
                        model_format=ModelFormat(name="llama-continuous"),
                        config={"params_ref": "mem://never-fetched",
                                **cfg}))))
            for name in cases:
                deadline = time.time() + 20
                isvc = None
                while time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    (name, isvc.status)
                needle = ("prefix_poll_s" if name == "bad-poll"
                          else "tracing")
                assert needle in (isvc.status.message or ""), \
                    (name, isvc.status.message)
