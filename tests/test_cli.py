"""REST API server (controlplane/apiserver.py) + kft CLI (cli.py).

The reference's public interface is the k8s REST API driven by kubectl
(every SURVEY §3 call stack starts at ``kubectl apply``); these tests pin
the HTTP CRUD surface, apiserver error conventions, and the CLI verbs
end-to-end against a live cluster.
"""

import json
import urllib.request

import pytest

from kubeflow_tpu import cli
from kubeflow_tpu.controlplane.cluster import Cluster


@pytest.fixture()
def api_cluster():
    cluster = Cluster()
    cluster.add_tpu_slice("slice-0", 1, 4)
    cluster.enable_serving()
    with cluster:
        url = cluster.serve_api()
        yield cluster, url


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


ISVC_YAML = """
apiVersion: serving.kft.io/v1
kind: InferenceService
metadata:
  name: cli-echo
spec:
  predictor:
    modelFormat:
      name: echo
    minReplicas: 1
    maxReplicas: 1
"""


class TestApiServer:
    def test_healthz_and_kinds(self, api_cluster):
        _, url = api_cluster
        assert _get(f"{url}/healthz")["ok"] is True
        kinds = _get(f"{url}/apis")["kinds"]
        assert "JaxJob" in kinds and "InferenceService" in kinds

    def test_crud_and_error_conventions(self, api_cluster):
        _, url = api_cluster
        body = {"kind": "Profile", "metadata": {"name": "team-x"},
                "spec": {"owner": "x@corp"}}
        req = urllib.request.Request(
            f"{url}/apis/Profile", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        # duplicate create -> 409; unknown object -> 404; unknown kind -> 404
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
        for path, code in (("/apis/Profile/default/nope", 404),
                           ("/apis/Mystery", 404)):
            try:
                urllib.request.urlopen(f"{url}{path}", timeout=10)
                raise AssertionError(f"expected {code}")
            except urllib.error.HTTPError as e:
                assert e.code == code
        # kind aliases resolve like kubectl shortnames
        got = _get(f"{url}/apis/profiles/default/team-x")
        assert got["metadata"]["name"] == "team-x"


class TestKftCli:
    def test_apply_get_describe_delete(self, api_cluster, tmp_path, capsys):
        _, url = api_cluster
        f = tmp_path / "isvc.yaml"
        f.write_text(ISVC_YAML)
        assert cli.main(["--server", url, "apply", "-f", str(f)]) == 0
        assert "created" in capsys.readouterr().out

        # reconciler drives it to Ready; the CLI sees the live status
        import time
        deadline = time.time() + 30
        phase = ""
        while time.time() < deadline:
            assert cli.main(
                ["--server", url, "get", "isvc", "cli-echo", "-o", "json"]) == 0
            obj = json.loads(capsys.readouterr().out)
            phase = (obj.get("status") or {}).get("phase", "")
            if phase == "Ready":
                break
            time.sleep(0.1)
        assert phase == "Ready"

        assert cli.main(["--server", url, "get", "isvc"]) == 0
        table = capsys.readouterr().out
        assert "cli-echo" in table and "Ready" in table

        assert cli.main(["--server", url, "describe", "isvc", "cli-echo"]) == 0
        desc = capsys.readouterr().out
        assert "Events:" in desc and "ReplicaStarted" in desc

        # apply the same file again -> update path ("configured")
        assert cli.main(["--server", url, "apply", "-f", str(f)]) == 0
        assert "configured" in capsys.readouterr().out

        assert cli.main(["--server", url, "delete", "isvc", "cli-echo"]) == 0
        capsys.readouterr()
        assert cli.main(
            ["--server", url, "get", "isvc", "cli-echo"]) == 1
        assert "kft:" in capsys.readouterr().err

    def test_api_resources(self, api_cluster, capsys):
        _, url = api_cluster
        assert cli.main(["--server", url, "api-resources"]) == 0
        out = capsys.readouterr().out
        assert "JaxJob" in out and "Experiment" in out

    def test_no_server_configured(self, capsys, monkeypatch):
        monkeypatch.delenv("KFT_SERVER", raising=False)
        assert cli.main(["get", "jaxjobs"]) == 2
        assert "no API server" in capsys.readouterr().err


class TestWatch:
    def test_watch_long_poll_sees_create(self, api_cluster):
        """kubectl -w analog: a watcher blocked on ?watch=true receives the
        ADDED event when an object lands."""
        import threading

        _, url = api_cluster
        got = {}

        def watcher():
            got["events"] = _get(
                f"{url}/apis/Profile?watch=true&timeout=10")["items"]

        t = threading.Thread(target=watcher)
        t.start()
        import time
        time.sleep(0.3)  # watcher in the long poll before the create
        body = {"kind": "Profile", "metadata": {"name": "watched"},
                "spec": {"owner": "w@corp"}}
        req = urllib.request.Request(
            f"{url}/apis/Profile", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)
        t.join(timeout=15)
        assert not t.is_alive()
        evs = got["events"]
        assert any(e["type"] == "ADDED"
                   and e["object"]["metadata"]["name"] == "watched"
                   for e in evs), evs

    def test_watch_timeout_returns_empty(self, api_cluster):
        _, url = api_cluster
        out = _get(f"{url}/apis/Notebook?watch=true&timeout=0.3")
        assert out["items"] == []

    def test_watch_cursor_resumes_between_polls(self, api_cluster):
        """Events landing BETWEEN polls are recovered by re-polling with
        the returned cursor (the resourceVersion-resume analog)."""
        _, url = api_cluster
        first = _get(f"{url}/apis/Profile?watch=true&timeout=0.2")
        cursor = first["cursor"]
        # object lands while NO poll is in flight
        body = {"kind": "Profile", "metadata": {"name": "between"},
                "spec": {"owner": "b@corp"}}
        req = urllib.request.Request(
            f"{url}/apis/Profile", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)
        out = _get(
            f"{url}/apis/Profile?watch=true&timeout=5&cursor={cursor}")
        assert any(e["type"] == "ADDED"
                   and e["object"]["metadata"]["name"] == "between"
                   for e in out["items"]), out

    def test_kft_get_watch_flag(self, api_cluster, capsys):
        """kft get <kind> -w streams events until --watch-seconds."""
        import threading
        import time as _time

        _, url = api_cluster

        def late_create():
            _time.sleep(0.4)
            body = {"kind": "Profile", "metadata": {"name": "streamed"},
                    "spec": {"owner": "s@corp"}}
            req = urllib.request.Request(
                f"{url}/apis/Profile", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10)

        t = threading.Thread(target=late_create)
        t.start()
        rc = cli.main(["--server", url, "get", "profiles", "-w",
                       "--watch-seconds", "2"])
        t.join()
        assert rc == 0
        out = capsys.readouterr().out
        assert "ADDED\tdefault/streamed" in out, out


class TestStructuredErrors:
    def test_error_reasons(self, api_cluster):
        """The apiserver returns a structured ``reason`` (kube Status
        analog) — clients branch on it, never on message substrings."""
        import urllib.error

        _, url = api_cluster
        body = {"kind": "Profile", "metadata": {"name": "reasoned"},
                "spec": {"owner": "r@corp"}}
        req = urllib.request.Request(
            f"{url}/apis/Profile", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("expected 409")
        except urllib.error.HTTPError as e:
            assert json.loads(e.read())["reason"] == "AlreadyExists"
        try:
            urllib.request.urlopen(
                f"{url}/apis/Profile/default/ghost", timeout=10)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert json.loads(e.read())["reason"] == "NotFound"

    def test_watch_cursor_expiry_410(self, api_cluster):
        """A cursor older than the retained window gets 410 Gone with a
        resync cursor instead of a silent gap (kube-apiserver semantics)."""
        import urllib.error
        from collections import deque

        cluster, url = api_cluster
        api = cluster._apiserver
        # shrink the buffer so eviction is reachable, then overflow it
        with api._events_cond:
            api._events = deque(api._events, maxlen=4)
        for i in range(8):
            body = {"kind": "Profile", "metadata": {"name": f"spam-{i}"},
                    "spec": {"owner": "s@corp"}}
            req = urllib.request.Request(
                f"{url}/apis/Profile", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10)
        import time as _time
        deadline = _time.time() + 10
        while api._evicted_seq == 0 and _time.time() < deadline:
            _time.sleep(0.05)
        assert api._evicted_seq > 0
        try:
            _get(f"{url}/apis/Profile?watch=true&timeout=0.2&cursor=1")
            raise AssertionError("expected 410")
        except urllib.error.HTTPError as e:
            payload = json.loads(e.read())
            assert e.code == 410 and payload["reason"] == "Expired"
            # resync cursor = eviction boundary: re-polling with it must
            # deliver the RETAINED window, not skip to the head
            out = _get(f"{url}/apis/Profile?watch=true&timeout=0.2"
                       f"&cursor={payload['cursor']}")
            assert out["items"], "retained events lost on resync"


class TestApiAuthn:
    """Bearer-token authn (the documented single-admin-credential scoping
    — apiserver.py docstring): with a token set, every route except
    /healthz requires Authorization; the kft CLI sends --token/$KFT_TOKEN."""

    def test_token_required_and_honored(self, capsys):
        import urllib.error

        from kubeflow_tpu.controlplane.cluster import Cluster

        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        with cluster:
            url = cluster.serve_api(token="s3cret")
            # healthz stays open (liveness probes carry no credentials)
            assert _get(f"{url}/healthz")["ok"] is True
            try:
                _get(f"{url}/apis")
                raise AssertionError("expected 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
                assert json.loads(e.read())["reason"] == "Unauthorized"
            req = urllib.request.Request(
                f"{url}/apis",
                headers={"Authorization": "Bearer s3cret"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert "JaxJob" in json.loads(r.read())["kinds"]
            # wrong token is rejected too
            req = urllib.request.Request(
                f"{url}/apis",
                headers={"Authorization": "Bearer wrong"})
            try:
                urllib.request.urlopen(req, timeout=10)
                raise AssertionError("expected 401")
            except urllib.error.HTTPError as e:
                assert e.code == 401
            # the CLI path end to end
            assert cli.main(
                ["--server", url, "--token", "s3cret", "api-resources"]) == 0
            assert "JaxJob" in capsys.readouterr().out
            assert cli.main(
                ["--server", url, "--token", "nope", "api-resources"]) == 1


class TestProfileAuthn:
    """Per-profile API identity (SURVEY §2.4 Profile multi-tenancy — r4
    verdict missing... #9): a profile token authenticates AS that
    profile, whose name is its tenant namespace; mutations elsewhere are
    403 Forbidden, reads stay cluster-wide, admin keeps everything."""

    def _req(self, url, token=None, method="GET", body=None):
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())

    def test_cross_profile_denial(self):
        import urllib.error

        from kubeflow_tpu.controlplane.cluster import Cluster

        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        with cluster:
            url = cluster.serve_api(
                token="admin-secret",
                profile_tokens={"alice": "tok-a", "bob": "tok-b"})
            job = {"kind": "JaxJob",
                   "metadata": {"name": "j1", "namespace": "alice"},
                   "spec": {"replica_specs": {"worker": {
                       "replicas": 1,
                       "template": {"command": ["true"]}}}}}
            # alice creates in her own namespace
            code, _ = self._req(f"{url}/apis/JaxJob", token="tok-a",
                                method="POST", body=job)
            assert code == 201
            # bob may READ alice's job (cluster-wide reads)...
            code, got = self._req(f"{url}/apis/JaxJob/alice/j1",
                                  token="tok-b")
            assert code == 200 and got["metadata"]["name"] == "j1"
            # ...but not DELETE it
            try:
                self._req(f"{url}/apis/JaxJob/alice/j1", token="tok-b",
                          method="DELETE")
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403
                assert json.loads(e.read())["reason"] == "Forbidden"
            # nor CREATE there
            try:
                job2 = {**job, "metadata": {"name": "j2",
                                            "namespace": "alice"}}
                self._req(f"{url}/apis/JaxJob", token="tok-b",
                          method="POST", body=job2)
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # a tenant cannot grant itself power by editing Profiles
            # (they live in kft-profiles, not the tenant namespace)
            try:
                self._req(
                    f"{url}/apis/Profile", token="tok-a", method="POST",
                    body={"kind": "Profile",
                          "metadata": {"name": "alice",
                                       "namespace": "kft-profiles"},
                          "spec": {"owner": "alice"}})
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # admin mutates anywhere
            code, _ = self._req(f"{url}/apis/JaxJob/alice/j1",
                                token="admin-secret", method="DELETE")
            assert code == 200

    def test_profile_object_token(self):
        """Profile.spec.api_token is a live credential: creating the
        Profile object grants the identity, no server restart."""
        import urllib.error

        from kubeflow_tpu.api.platform import Profile, ProfileSpec
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.controlplane.cluster import Cluster

        cluster = Cluster()
        cluster.add_tpu_slice("s0", 1, 4)
        with cluster:
            url = cluster.serve_api(token="admin-secret")
            cluster.store.create(Profile(
                metadata=ObjectMeta(name="carol", namespace="kft-profiles"),
                spec=ProfileSpec(owner="carol", api_token="tok-c")))
            job = {"kind": "JaxJob",
                   "metadata": {"name": "cj", "namespace": "carol"},
                   "spec": {"replica_specs": {"worker": {
                       "replicas": 1,
                       "template": {"command": ["true"]}}}}}
            code, _ = self._req(f"{url}/apis/JaxJob", token="tok-c",
                                method="POST", body=job)
            assert code == 201
            try:
                self._req(f"{url}/apis/JaxJob", token="tok-c",
                          method="POST",
                          body={**job, "metadata": {"name": "cj2",
                                                    "namespace": "default"}})
                raise AssertionError("expected 403")
            except urllib.error.HTTPError as e:
                assert e.code == 403
