"""Test harness config.

Force the CPU backend with 8 virtual devices so every sharding/mesh test runs
the same SPMD code path XLA uses on a real v5e slice (SURVEY.md §4: the
honest multi-host stand-in).  Must be set before jax imports anywhere in the
test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Keep the axon TPU plugin (registered by a sitecustomize in some images)
# from claiming the process: tests must run the CPU SPMD path.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# A sitecustomize in some images imports jax before conftest runs, so the
# env var alone is too late — force the platform through the live config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ckpt_dir(tmp_path):
    d = tmp_path / "ckpt"
    d.mkdir()
    return str(d)
