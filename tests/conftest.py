"""Test harness config.

Force the CPU backend with 8 virtual devices so every sharding/mesh test runs
the same SPMD code path XLA uses on a real v5e slice (SURVEY.md §4: the
honest multi-host stand-in).  Must be set before jax imports anywhere in the
test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_ckpt_dir(tmp_path):
    d = tmp_path / "ckpt"
    d.mkdir()
    return str(d)
