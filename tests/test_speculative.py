"""Speculative decoding (ISSUE 4, serving/continuous.py).

n-gram (prompt-lookup) drafts verified k-at-a-time in ONE dispatch:
these tests pin the contract the feature ships under — greedy tokens
BIT-IDENTICAL to non-speculative decode (plain, prefix-cache, chunked-
prefill and tiered variants), exact mid-burst EOS/stop retirement,
engine observability counters, and zero steady-state recompiles across
warmup -> spec decode -> accept/reject waves -> retirement -> slot
reuse.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import (
    ContinuousEngine,
    NgramProposer,
    TieredEngine,
)

#: a prompt whose greedy continuation on the tiny model develops the
#: repetitive structure prompt-lookup exists for (verified: acceptance
#: rate > 0.5 over 60+ tokens) — the engine-level tests only need
#: SOME accepted and SOME rejected drafts, which any trajectory gives
LOOPY = np.random.default_rng(7).integers(1, 256, size=5).tolist()


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    return ContinuousEngine(cfg, params, **kw)


@pytest.fixture(scope="module")
def plain_tokens(tiny_llama):
    """Greedy oracle: the non-speculative engine."""
    eng = make_engine(tiny_llama)
    try:
        return {
            "loopy": eng.generate(LOOPY, max_new_tokens=60, timeout=300),
            "short": eng.generate([7, 8, 9], max_new_tokens=8),
            "victim": eng.generate([7, 8, 9], max_new_tokens=40),
        }
    finally:
        eng.stop()


class TestNgramProposer:
    def test_matches_most_recent_occurrence(self):
        p = NgramProposer(2)
        #           match here --v        v-- tail
        hist = [1, 2, 9, 9, 5, 1, 2, 3, 4, 1, 2]
        # the match's own next token (3) is t1's position — the verify
        # emits the true token there for free (DraftProposer alignment
        # contract), so drafts start one past it
        assert p.propose(hist, 3) == [4, 1, 2]

    def test_no_match_returns_empty(self):
        assert NgramProposer(3).propose([1, 2, 3, 4, 5], 4) == []

    def test_short_history_returns_empty(self):
        assert NgramProposer(3).propose([1, 2], 4) == []

    def test_proposal_capped_at_k_extends_past_history_end(self):
        p = NgramProposer(2)
        hist = [5, 6, 7, 8, 5, 6]
        # the match's continuation runs off the end of history after
        # [7, 8, 5, 6]; copy-and-continue keeps drafting the period
        assert p.propose(hist, 8) == [8, 5, 6, 7, 8, 5, 6, 7]
        assert p.propose(hist, 2) == [8, 5]

    def test_constant_run_still_proposes(self):
        # a period-1 tail (constant run) abuts its own match — the
        # extension must keep proposing the constant, not go silent
        assert NgramProposer(3).propose([7] * 6, 4) == [7, 7, 7, 7]

    def test_cycle_alignment_accepts_whole_window(self):
        """On a perfect cycle the shifted drafts line up exactly with
        the verify layout [t1, g_1..g_k]: g_i predicts position
        front+i.  Walk the cycle host-side the way the engine does —
        t1 is the true next token, drafts must equal the k tokens
        after it.  (A period-1 cycle cannot see a misalignment; this
        period-5 one fails for any off-by-one.)"""
        cycle = [11, 22, 33, 44, 55]
        hist = (cycle * 4)[:18]  # ends mid-cycle: ..., 44, 55, 11, 22, 33
        p = NgramProposer(3)
        t1 = cycle[(hist[-1] // 11) % 5]  # true next after 33 is 44
        want_after_t1 = [55, 11, 22, 33]
        assert p.propose(hist, 4) == want_after_t1
        assert p.propose(hist, 4)[0] != t1  # not t1's position

    def test_bad_ngram_rejected(self):
        with pytest.raises(ValueError, match=">= 1"):
            NgramProposer(0)
        with pytest.raises(ValueError, match="window"):
            NgramProposer(2, window=0)

    def test_window_caps_scan(self):
        """The lookup runs between dispatches on the depth-1 critical
        path — it scans at most the trailing ``window`` tokens, so a
        match strictly older than the window is forgone (bounded host
        work per proposal instead of O(history))."""
        hist = [1, 2, 3] + [0] * 7 + [1, 2]
        assert NgramProposer(2).propose(hist, 2) == [0, 0]
        assert NgramProposer(2, window=8).propose(hist, 2) == []


class TestResidualBanWarpOrder:
    """The residual re-draw after a rejected draft must come from the
    residual of the WARPED distribution: _sample_step bans the token
    AFTER temperature/top-k/top-p, never before — masking first would
    shift the kept set and let spec-on emit tokens spec-off sampling
    assigns zero probability."""

    def test_ban_applies_after_topk_warp(self):
        from kubeflow_tpu.serving.continuous import _sample_step
        logits = jnp.asarray([[0.0, 3.0, 2.0, 1.0]])  # argmax = 1
        temps = jnp.asarray([1.0], jnp.float32)
        ones = jnp.asarray([1.0], jnp.float32)
        top2 = jnp.asarray([2], jnp.int32)  # warped kept set = {1, 2}
        ban_top = jnp.asarray([1], jnp.int32)
        for s in range(16):
            t = _sample_step(logits, temps, ones, top2,
                             jax.random.PRNGKey(s), banned=ban_top)
            # residual of top-2 minus the banned top token is a point
            # mass on token 2 — token 3 (which pre-warp masking would
            # admit into the kept set) must never appear
            assert int(t[0]) == 2

    def test_no_ban_and_greedy_unaffected(self):
        from kubeflow_tpu.serving.continuous import _sample_step
        logits = jnp.asarray([[0.0, 3.0, 2.0, 1.0]])
        ones = jnp.asarray([1.0], jnp.float32)
        off = jnp.asarray([0], jnp.int32)
        none = jnp.asarray([-1], jnp.int32)
        key = jax.random.PRNGKey(0)
        base = _sample_step(logits, ones, ones, off, key)
        assert int(_sample_step(logits, ones, ones, off, key,
                                banned=none)[0]) == int(base[0])
        # greedy slots ignore the ban entirely (argmax != ban is
        # already proven by the rejection that armed it)
        zero_t = jnp.asarray([0.0], jnp.float32)
        got = _sample_step(logits, zero_t, ones, off, key,
                           banned=jnp.asarray([2], jnp.int32))
        assert int(got[0]) == 1


class TestSpeculativeParity:
    def test_greedy_parity_and_drafts_actually_accepted(
            self, tiny_llama, plain_tokens):
        """Spec-on output is bit-identical to spec-off, AND the run
        genuinely speculated: drafts were proposed, some accepted
        (fewer decode dispatches than tokens) and some rejected (the
        rollback path ran)."""
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            got = eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            st = eng.stats()
        finally:
            eng.stop()
        assert got == plain_tokens["loopy"]
        assert st["spec_dispatches_total"] > 0
        assert st["spec_tokens_accepted_total"] > 0
        assert st["spec_tokens_accepted_total"] < \
            st["spec_tokens_proposed_total"]  # rejections happened too
        assert st["decode_steps"] < 60  # accepted runs amortized

    def test_greedy_parity_concurrent_slots(self, tiny_llama, plain_tokens):
        """Speculating and non-matching requests share verify
        dispatches; every slot's stream stays bit-exact."""
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            a = eng.submit(LOOPY, max_new_tokens=60)
            b = eng.submit([7, 8, 9], max_new_tokens=8)
            got_a, got_b = a.wait(300), b.wait(300)
        finally:
            eng.stop()
        assert got_a == plain_tokens["loopy"]
        assert got_b == plain_tokens["short"]

    def test_misbehaving_proposer_degrades_not_kills(
            self, tiny_llama, plain_tokens):
        """The DraftProposer seam takes UNTRUSTED guesses: a custom
        proposer that raises, or returns more than the planned budget,
        must degrade to "no draft / clamped draft" for that slot — not
        blow up the scheduler thread and fail every in-flight request.
        Output stays bit-identical either way (drafts never change
        tokens, only dispatch count)."""
        calls = {"n": 0}

        class Evil:
            def propose(self, history, k):
                calls["n"] += 1
                if calls["n"] % 3 == 0:
                    raise RuntimeError("proposer bug")
                # overlong: violates the "up to k" contract
                return NgramProposer(3).propose(history, k) + [1, 2, 3]

        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4,
                          draft_proposer=Evil())
        try:
            got = eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            st = eng.stats()
        finally:
            eng.stop()
        assert calls["n"] > 3  # both behaviors exercised
        assert got == plain_tokens["loopy"]
        # clamping held: never more than spec_k proposals per slot-plan
        assert st["spec_tokens_proposed_total"] <= 4 * calls["n"]

    def test_parity_with_prefix_cache(self, tiny_llama):
        """The prefix-cache admission route composes with speculative
        decode: repeats admit via the on-device copy and still emit
        identical tokens."""
        cold = make_engine(tiny_llama, spec_k=0)
        try:
            want = cold.generate(list(range(1, 49)), max_new_tokens=12)
        finally:
            cold.stop()
        eng = make_engine(tiny_llama, spec_k=4, prefix_cache=True,
                          min_prefix=8)
        try:
            a = eng.generate(list(range(1, 49)), max_new_tokens=12)
            b = eng.generate(list(range(1, 49)), max_new_tokens=12)
            assert eng.prefix_hits == 1
        finally:
            eng.stop()
        assert a == want and b == want

    def test_parity_with_chunked_prefill_fused_verify(
            self, tiny_llama, plain_tokens):
        """prefill_budget + spec_k: the admitting prompt's chunks fuse
        into VERIFY dispatches (make_fused_verify_program) while a
        victim decodes speculatively — both bit-identical to solo."""
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4,
                          prefill_budget=8)
        try:
            victim = eng.submit(LOOPY, max_new_tokens=60)
            while eng.step_counter < 5:
                time.sleep(0.005)
            late = eng.submit([7, 8, 9], max_new_tokens=8)
            got_late = late.wait(300)
            got_victim = victim.wait(300)
            st = eng.stats()
            assert st["prefill_chunks_dispatched"] >= 1
            assert st["spec_dispatches_total"] > 0
        finally:
            eng.stop()
        assert got_victim == plain_tokens["loopy"]
        assert got_late == plain_tokens["short"]

    @pytest.mark.slow
    def test_parity_tiered(self, tiny_llama, plain_tokens):
        """spec knobs flow into every tier's pool; routing + tokens
        match the untiered oracle."""
        cfg, params = tiny_llama
        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=2, prefix_cache=False, spec_k=4)
        try:
            assert all(p.spec_k == 4 for p in eng.pools)
            got_short = eng.generate([7, 8, 9], max_new_tokens=8)
            got_long = eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            st = eng.stats()
            assert st["spec_acceptance_rate"] <= 1.0
        finally:
            eng.stop()
        assert got_short == plain_tokens["short"]
        assert got_long == plain_tokens["loopy"]

    def test_eos_mid_burst_truncates_at_exact_token(
            self, tiny_llama, plain_tokens):
        """EOS landing inside a burst of accepted tokens retires the
        request AT the EOS token, not at the burst end."""
        want = plain_tokens["loopy"]
        # the token whose FIRST occurrence is deepest: the stream loops,
        # so most tokens recur early — EOS must not fire before
        # speculation is in swing
        first: dict[int, int] = {}
        for i, t in enumerate(want):
            first.setdefault(t, i)
        eos, idx = max(first.items(), key=lambda kv: kv[1])
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4, eos_id=eos)
        try:
            got = eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            assert eng.spec_dispatches_total > 0
        finally:
            eng.stop()
        assert got == want[: idx + 1]

    def test_slot_reuse_after_speculation(self, tiny_llama, plain_tokens):
        """Stale draft KV from a retired speculating occupant never
        leaks into the slot's next occupant (the rollback is a pointer,
        the pool relies on masking)."""
        eng = make_engine(tiny_llama, num_slots=1, decode_chunk=1,
                          spec_k=4)
        try:
            first = eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            second = eng.generate([7, 8, 9], max_new_tokens=8)
        finally:
            eng.stop()
        assert first == plain_tokens["loopy"]
        assert second == plain_tokens["short"]

    def test_greedy_neighbor_unaffected_by_sampling_slot(
            self, tiny_llama, plain_tokens):
        """A temperature=0 request stays bit-exact while a sampling
        request shares its verify dispatches (per-slot rejection
        sampling is independent)."""
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            hot = eng.submit(LOOPY, max_new_tokens=30, temperature=2.0)
            cold = eng.submit(LOOPY, max_new_tokens=60)
            got = cold.wait(300)
            hot_out = hot.wait(300)
        finally:
            eng.stop()
        assert got == plain_tokens["loopy"]
        assert len(hot_out) == 30
        assert all(0 <= t < 256 for t in hot_out)

    def test_stochastic_spec_supports_match_greedy_degenerates(
            self, tiny_llama, plain_tokens):
        """temperature > 0 with top_k=1 collapses rejection sampling to
        the greedy accept rule — output must equal plain greedy even
        through accept/reject/residual-ban waves."""
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            got = eng.generate(LOOPY, max_new_tokens=60, timeout=300,
                               temperature=0.8, top_k=1)
        finally:
            eng.stop()
        assert got == plain_tokens["loopy"]


class TestSpeculativeStats:
    def test_counters_and_rate(self, tiny_llama):
        eng = make_engine(tiny_llama, decode_chunk=1, spec_k=4)
        try:
            eng.generate(LOOPY, max_new_tokens=60, timeout=300)
            st = eng.stats()
        finally:
            eng.stop()
        for k in ("spec_tokens_proposed_total", "spec_tokens_accepted_total",
                  "spec_dispatches_total", "spec_acceptance_rate"):
            assert k in st
        assert st["spec_acceptance_rate"] == round(
            st["spec_tokens_accepted_total"]
            / max(st["spec_tokens_proposed_total"], 1), 4)
        assert 0.0 <= st["spec_acceptance_rate"] <= 1.0

    def test_spec_off_counters_stay_zero(self, tiny_llama):
        eng = make_engine(tiny_llama)
        try:
            eng.generate([1, 2, 3], max_new_tokens=4)
            st = eng.stats()
        finally:
            eng.stop()
        assert st["spec_dispatches_total"] == 0
        assert st["spec_tokens_proposed_total"] == 0

    def test_bad_knobs_rejected(self, tiny_llama):
        with pytest.raises(ValueError, match="spec_k"):
            make_engine(tiny_llama, spec_k=-1)
        with pytest.raises(ValueError, match="spec_ngram"):
            make_engine(tiny_llama, spec_k=2, spec_ngram=0)

    def test_bad_knobs_fail_isvc_at_conf_freeze(self):
        """Satellite: a bad spec knob on an ISvc (gang or not) is ONE
        Failed status with the knob named — caught at conf-freeze in
        the controller, before any engine/pod ever constructs (no
        params are even fetched, so this test needs no model)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec, InferenceService, InferenceServicePhase,
            InferenceServiceSpec, ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="bad-spec"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="llama-continuous"),
                    config={"params_ref": "mem://never-fetched",
                            "spec_k": -2}))))
            deadline = time.time() + 20
            isvc = None
            while time.time() < deadline:
                isvc = cluster.store.try_get("InferenceService", "bad-spec")
                if (isvc is not None
                        and isvc.status.phase == InferenceServicePhase.FAILED):
                    break
                time.sleep(0.05)
            assert isvc is not None
            assert isvc.status.phase == InferenceServicePhase.FAILED, \
                isvc.status
            assert "spec_k" in (isvc.status.message or "")


class TestSpeculativeDispatchHygiene:
    """ISSUE 4 acceptance: jit_recompiles_total == 0 across warmup ->
    spec decode -> accept/reject waves -> retirement -> slot reuse."""

    def test_zero_steady_state_recompiles_spec(self, tiny_llama):
        eng = make_engine(tiny_llama, decode_chunk=2, spec_k=4)
        try:
            eng.warmup()
            # wave 1: speculating + draft-free requests share the pool
            # (60 tokens: the trajectory's repetitive tail is where the
            # n-gram proposer starts firing)
            reqs = [eng.submit(LOOPY, max_new_tokens=60),
                    eng.submit([7, 8, 9], max_new_tokens=6)]
            for r in reqs:
                r.wait(300)
            # wave 2: slot reuse after retirement, speculation resumes
            reqs = [eng.submit(LOOPY, max_new_tokens=60)
                    for _ in range(2)]
            for r in reqs:
                r.wait(300)
            st = eng.stats()
            assert st["spec_dispatches_total"] > 0  # speculation ran
            assert st["jit_recompiles_total"] == 0, st
        finally:
            eng.stop()

    def test_zero_recompiles_spec_with_chunked_prefill(self, tiny_llama):
        eng = make_engine(tiny_llama, decode_chunk=2, spec_k=4,
                          prefill_budget=4)
        try:
            eng.warmup()
            victim = eng.submit(LOOPY, max_new_tokens=30)
            while eng.step_counter < 3:
                time.sleep(0.005)
            late = eng.submit(list(range(1, 20)), max_new_tokens=6)
            late.wait(300)
            victim.wait(300)
            st = eng.stats()
            assert st["prefill_chunks_dispatched"] > 0
            assert st["jit_recompiles_total"] == 0, st
        finally:
            eng.stop()


class TestStopSequenceBursts:
    """Satellite: serving/text.py must retire a stop that completes
    mid-burst at the EXACT token — a verify dispatch delivers up to
    spec_k+1 tokens at once, so the stop routinely lands inside one."""

    def _text_model(self, tiny_llama, **extra):
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg, params = tiny_llama
        ref = register_mem(f"spec-text-{extra.get('spec_k', 0)}",
                           (cfg, params))
        m = TextGenerator("tg", {
            "params_ref": ref, "max_new_tokens": 96, "num_slots": 2,
            "decode_chunk": 1, "warmup_groups": [], "prefix_cache": False,
            "eos_id": None, **extra})
        m.start()
        return m

    def test_stop_spanning_accept_boundary(self, tiny_llama):
        """A stop string that spans burst boundaries truncates the text
        before the stop and retires at the EXACT covering token: the
        spec run (tokens arriving in bursts of up to spec_k+1) must
        land on the same retirement token as the token-by-token
        reference run."""
        from kubeflow_tpu.serving.text import ByteTokenizer, _ids_covering

        tok = ByteTokenizer()
        ref = self._text_model(tiny_llama, spec_k=0)
        try:
            ref_ids = ref.engine.generate(tok.encode("ab"),
                                          max_new_tokens=96, timeout=300)
        finally:
            ref.stop()
        full = tok.decode(ref_ids)
        # the 3-char stop with the DEEPEST first occurrence: the stream
        # loops, so a late-position substring usually also occurs early
        # — the deepest one guarantees speculation is in swing when the
        # stop completes, and a 3-char stop regularly straddles an
        # accept boundary at spec_k=4
        stop, cut = max(
            ((full[j: j + 3], full.find(full[j: j + 3]))
             for j in range(len(full) - 3)), key=lambda sc: sc[1])
        expect_tokens = len(_ids_covering(tok, ref_ids, cut + len(stop)))
        m = self._text_model(tiny_llama, spec_k=4)
        try:
            out = m.openai_completions(
                {"prompt": "ab", "max_tokens": 96, "stop": stop})
            assert m.engine.spec_tokens_accepted_total > 0  # bursts ran
        finally:
            m.stop()
        choice = out["choices"][0]
        assert choice["text"] == full[:cut]
        assert choice["finish_reason"] == "stop"
        # exact-token retirement: usage counts the ids whose decode
        # covers the stop — NOT the burst tail the dispatch delivered
        assert out["usage"]["completion_tokens"] == expect_tokens

    def test_stop_scanner_hit_end_across_feeds(self):
        """_StopScanner reports the hit END even when the stop's bytes
        arrive split across two scans (the accept-boundary shape)."""
        from kubeflow_tpu.serving.text import ByteTokenizer, _StopScanner

        tok = ByteTokenizer()
        s = _StopScanner(tok, ["XYZ"])
        ids = tok.encode("aaXY") + tok.encode("Zbb")
        assert s.scan(ids[:4]) is None  # stop only half-arrived
        cut = s.scan(ids)
        assert cut == 2
        assert s.last_hit_end == 5

    def test_ids_covering_exact_token(self):
        from kubeflow_tpu.serving.text import ByteTokenizer, _ids_covering

        tok = ByteTokenizer()
        ids = tok.encode("hello world")
        assert _ids_covering(tok, ids, 5) == tok.encode("hello")
        assert _ids_covering(tok, ids, len("hello world") + 9) == ids

    def test_ids_covering_multibyte_prefix_not_cut_early(self):
        """The prefix-re-decode fallback (HF path: no
        incremental_decoder) must not cut a token early when a prefix
        decode ends in an INCOMPLETE multi-byte char: the trailing
        U+FFFD inflates the char count, so "aé" split as
        [a, é-byte-1, é-byte-2] already measures 2 chars at 2 ids —
        but that boundary is dirty and cutting there drops the stop's
        final character."""
        from kubeflow_tpu.serving.text import _ids_covering

        class ByteLevel:  # decode-only tokenizer, not prefix-stable
            def decode(self, ids):
                return bytes(ids).decode("utf-8", errors="replace")

        ids = [0x61, 0xC3, 0xA9]  # "aé", é split across two ids
        got = _ids_covering(ByteLevel(), ids, 2)  # stop ends at char 2
        assert got == ids
        assert ByteLevel().decode(got) == "aé"

    def test_ids_covering_non_additive_cleanup(self):
        """HF decode is not additive: clean_up_tokenization_spaces
        collapses ' ,' to ',', so the prefix ['Hello', ' '] measures
        the same 6 chars as the full decode 'Hello,' — a length-only
        cut would drop the ',' that completed the stop."""
        from kubeflow_tpu.serving.text import _ids_covering

        class Cleanup:
            vocab = {0: "Hello", 1: " ", 2: ","}

            def decode(self, ids):
                return "".join(
                    self.vocab[i] for i in ids).replace(" ,", ",")

        ids = [0, 1, 2]  # full decode "Hello," — stop "," ends at 6
        got = _ids_covering(Cleanup(), ids, 6)
        assert got == ids  # not cut at ['Hello', ' '] (also 6 chars)
        assert Cleanup().decode(got) == "Hello,"
