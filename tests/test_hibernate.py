"""Durable sessions (ISSUE 12): crash-safe KV tiering with
hibernate/resume on any replica.

Layers, matching the tentpole:

- HOST TIER UNITS: the bounded numpy mirror (HostBlockPool) — LRU
  eviction, capacity truncation, prefix match/take — plus the
  BlockLedger's host-tier conservation extension;
- STORAGE TIER UNITS: KvSpillStore's atomic publish (tmp+fsync+rename),
  manifest verify-on-read (a torn payload is DETECTED, never attached),
  SpillCorrupt on an unreadable manifest, stale-staging GC;
- HIBERNATE/THAW: a live sequence spills to storage and resumes
  bit-identically — on the same engine, on the same Request handle, or
  on a FRESH replica after the source died (the cross-replica
  satellite: greedy parity, ``jit_recompiles_total == 0``, BlockLedger
  clean on both allocators); a corrupt spill re-prefills from the
  manifest's token record instead of serving wrong KV;
- HOST-TIER ENGINE: watermark-driven spill at retirement, restore at
  admission (parity + the ISSUE 12 gauge set);
- CLUSTER REGISTRY: prefix_digest -> /metrics rows -> KvBlockRegistry
  locate, and the kv_fetch wire: a cold replica imports a hot prefix
  from a peer (install_prefix) instead of recomputing it.
"""

import os
import tempfile
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.analysis.runtime import BlockLedger
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.paged import HostBlockPool, block_keys, prefix_digest
from kubeflow_tpu.serving.storage import KvSpillStore, SpillCorrupt


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64 tokens = 4 blocks at block_size 16


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("block_size", 16)
    eng = ContinuousEngine(cfg, params, **kw)
    eng.attach_block_ledger(BlockLedger())
    return eng


def assert_no_leaks(*engines):
    for eng in engines:
        assert eng.audit_blocks() == []
        assert eng.stats()["kv_blocks_leaked_total"] == 0
        assert eng.block_ledger.conservation_errors == []


@pytest.fixture(scope="module")
def oracle(tiny_llama):
    """Uninterrupted greedy truth."""
    eng = make_engine(tiny_llama)
    try:
        return {
            "long120": eng.generate(LONG, max_new_tokens=120),
            "long8": eng.generate(LONG, max_new_tokens=8),
        }
    finally:
        eng.stop()


def _submit_until(eng, prompt, max_new, n_tokens):
    req = eng.submit(prompt, max_new_tokens=max_new)
    deadline = time.time() + 120
    while len(req.tokens) < n_tokens:
        assert time.time() < deadline, "engine made no progress"
        time.sleep(0.01)
    return req


def _fake_block(v, n=3):
    return [np.full((1, 2), v, np.float32) for _ in range(n)]


# -- host tier units ------------------------------------------------------


class TestHostBlockPool:
    def test_put_match_take(self):
        pool = HostBlockPool(capacity_blocks=8, block_size=4)
        toks = [1, 2, 3, 4, 5, 6, 7, 8]
        hid = pool.put(toks, [_fake_block(0), _fake_block(1)])
        assert hid >= 0 and pool.blocks_held == 2
        got, n = pool.match(np.asarray(toks, np.int64), len(toks))
        assert got == hid and n == 8
        blks = pool.take(hid, 2)
        assert len(blks) == 2
        assert float(blks[1][0][0, 0]) == 1.0
        # partial prefix still matches
        _, n2 = pool.match(np.asarray([1, 2, 3, 4, 99], np.int64), 5)
        assert n2 == 4

    def test_lru_eviction_and_touch(self):
        pool = HostBlockPool(capacity_blocks=4, block_size=4)
        a = pool.put([1] * 8, [_fake_block(0), _fake_block(1)])
        b = pool.put([2] * 8, [_fake_block(2), _fake_block(3)])
        assert pool.take(a, 1) is not None  # touch a: b becomes LRU
        c = pool.put([3] * 8, [_fake_block(4), _fake_block(5)])
        assert pool.blocks_held == 4 and pool.evictions_total == 1
        assert pool.take(b, 1) is None      # b evicted
        assert pool.take(a, 1) is not None and pool.take(c, 1) is not None

    def test_entry_wider_than_pool_truncates_to_head(self):
        pool = HostBlockPool(capacity_blocks=2, block_size=4)
        hid = pool.put(list(range(16)), [_fake_block(i) for i in range(4)])
        assert hid >= 0 and pool.blocks_held == 2
        # the HEAD of the prefix survives (the hot part)
        _, n = pool.match(np.asarray(list(range(16)), np.int64), 16)
        assert n == 8

    def test_contains_prefix_dedup_probe(self):
        pool = HostBlockPool(capacity_blocks=8, block_size=4)
        pool.put([5] * 8, [_fake_block(0), _fake_block(1)])
        assert pool.contains_prefix([5] * 8, min_tokens=8)
        assert not pool.contains_prefix([6] * 8, min_tokens=8)

    def test_ledger_tolerates_multi_evict_put(self):
        """A put that needs SEVERAL evictions to converge is not an
        over-capacity violation — mid-loop the pool is legitimately
        over; only the post-put/audit boundary enforces the bound
        (review regression)."""
        ledger = BlockLedger()
        pool = ledger.attach_host_pool(HostBlockPool(4, 4))
        pool.put([1] * 8, [_fake_block(0), _fake_block(1)])
        pool.put([2] * 8, [_fake_block(2), _fake_block(3)])
        # 3-block entry: two evictions before the loop converges
        pool.put([3] * 12, [_fake_block(4), _fake_block(5),
                            _fake_block(6)])
        assert pool.blocks_held == 3 and pool.evictions_total == 2
        assert ledger.conservation_errors == []
        assert ledger.audit_host(pool) == []

    def test_ledger_host_conservation(self):
        ledger = BlockLedger()
        pool = ledger.attach_host_pool(HostBlockPool(8, 4))
        pool.put([1] * 8, [_fake_block(0), _fake_block(1)])
        assert ledger.audit_host(pool) == []
        # inject gauge drift around the wrapped verbs: detected once
        pool.blocks_held += 3
        errs = ledger.audit_host(pool)
        assert errs and "host tier holds" in errs[0]
        assert pool.blocks_held == 2  # resynced
        assert ledger.audit_host(pool) == []


# -- storage tier units ---------------------------------------------------


def _snapshot(nblocks=2, with_logits=True):
    snap = {
        "v": 1, "phase": "decode", "block_size": 4,
        "prompt": [1, 2, 3, 4, 5, 6, 7, 8], "generated": [9, 10],
        "position": 10, "remaining": 6, "max_new_tokens": 8,
        "temperature": 0.0, "top_p": 1.0, "top_k": 0, "priority": 1,
        "spec_ban": -1,
        "blocks": [[np.full((1, 2, 4), i, np.float32),
                    np.full((1, 4, 3), i + 10, np.float32)]
                   for i in range(nblocks)],
    }
    if with_logits:
        snap["logits"] = np.arange(8, dtype=np.float32)
    return snap


class TestKvSpillStore:
    def test_roundtrip_verified(self, tmp_path):
        store = KvSpillStore(str(tmp_path))
        store.write("s1", _snapshot(), block_keys=[11, 22])
        assert store.contains("s1") and store.session_count() == 1
        snap, ok = store.read("s1")
        assert ok
        assert snap["position"] == 10 and len(snap["blocks"]) == 2
        np.testing.assert_array_equal(snap["logits"],
                                      np.arange(8, dtype=np.float32))
        np.testing.assert_array_equal(snap["blocks"][1][1],
                                      np.full((1, 4, 3), 11, np.float32))
        mf = store.read_manifest("s1")
        assert mf["block_keys"] == [11, 22]

    def test_overwrite_newest_wins(self, tmp_path):
        store = KvSpillStore(str(tmp_path))
        store.write("s", _snapshot())
        newer = _snapshot()
        newer["position"] = 99
        store.write("s", newer)
        snap, ok = store.read("s")
        assert ok and snap["position"] == 99
        assert store.session_count() == 1

    def test_old_entry_debris_hidden_and_gcd(self, tmp_path):
        """A crash between the overwrite's two renames leaves the
        displaced copy under a hidden ``.old-`` name: never counted as
        a session, collected by the next same-key write (review
        regression — a visible ``<key>.old-*`` inflated
        kv_sessions_hibernated forever)."""
        store = KvSpillStore(str(tmp_path))
        entry = store.write("s", _snapshot())
        key = os.path.basename(entry)
        debris = os.path.join(str(tmp_path), f".old-{key}-deadbeef")
        os.makedirs(debris)
        with open(os.path.join(debris, "spill.json"), "w") as f:
            f.write("{}")
        assert store.session_count() == 1
        assert store.sessions() == ["s"]
        store.write("s", _snapshot())  # same-key write GCs the debris
        assert not os.path.exists(debris)
        assert store.session_count() == 1

    def test_torn_payload_detected_never_attached(self, tmp_path):
        store = KvSpillStore(str(tmp_path))
        entry = store.write("s", _snapshot())
        KvSpillStore._tear(entry, 32)
        snap, ok = store.read("s")
        assert not ok
        assert "blocks" not in snap and "logits" not in snap
        # the scheduler meta still re-prefills the session
        assert snap["prompt"] == [1, 2, 3, 4, 5, 6, 7, 8]
        assert store.verify_failures_total == 1

    def test_manifest_corrupt_raises(self, tmp_path):
        store = KvSpillStore(str(tmp_path))
        entry = store.write("s", _snapshot())
        with open(os.path.join(entry, "spill.json"), "w") as f:
            f.write("{not json")
        with pytest.raises(SpillCorrupt):
            store.read("s")
        with pytest.raises(SpillCorrupt):
            store.read_manifest("s")

    def test_missing_session_raises(self, tmp_path):
        with pytest.raises(SpillCorrupt):
            KvSpillStore(str(tmp_path)).read("nope")

    def test_stale_staging_gc_on_next_write(self, tmp_path):
        from kubeflow_tpu.chaos.plan import FaultPlan

        plan = FaultPlan(seed=5).spill_kill_mid_write("meta")
        store = KvSpillStore(str(tmp_path), chaos=plan)
        with pytest.raises(Exception):
            store.write("s", _snapshot())
        assert not store.contains("s")
        staging = [n for n in os.listdir(str(tmp_path))
                   if n.startswith(".staging-")]
        assert staging  # the kill -9 analog left its debris
        # young debris is protected (a concurrent stager may own it);
        # age it past the grace and the next same-key write collects it
        for n in staging:
            os.utime(os.path.join(str(tmp_path), n), (1, 1))
        store.write("s", _snapshot())  # chaos drained: clean write
        assert store.contains("s")
        staging = [n for n in os.listdir(str(tmp_path))
                   if n.startswith(".staging-")]
        assert not staging  # aged same-key debris collected at publish


# -- hibernate / thaw -----------------------------------------------------


class TestHibernateResume:
    def test_same_engine_parity_frees_hbm(self, tiny_llama, oracle,
                                          tmp_path):
        store = KvSpillStore(str(tmp_path))
        eng = make_engine(tiny_llama, prefix_cache=False)
        try:
            eng.attach_spill_store(store)
            req = _submit_until(eng, LONG, 120, 12)
            free_before = eng.stats()["kv_blocks_free"]
            assert eng.hibernate_sequence(req, "conv-1")
            st = eng.stats()
            # free-HBM-recovered: the hibernated session's span is back
            # on the free list while it sleeps in storage
            assert st["kv_blocks_free"] > free_before
            assert st["kv_spills_total"] == 1
            assert st["kv_sessions_hibernated"] == 1
            assert not req.done.is_set()  # parked, not failed
            req2, info = eng.thaw_sequence("conv-1")
            out = req2.wait(120)
            assert out == oracle["long120"]
            assert not info["degraded"]
            st = eng.stats()
            assert st["kv_thaws_total"] == 1
            assert st["kv_sessions_hibernated"] == 0  # entry consumed
            assert st["jit_recompiles_total"] == 0
            assert_no_leaks(eng)
        finally:
            eng.stop()

    def test_cross_replica_thaw_bit_identical(self, tiny_llama, oracle,
                                              tmp_path):
        """The headline satellite: hibernate on engine A, DESTROY A,
        thaw on a fresh engine B from the storage tier alone — greedy
        bit-identical, zero recompiles, ledger clean on both."""
        store = KvSpillStore(str(tmp_path))
        a = make_engine(tiny_llama)
        a.attach_spill_store(store)
        req = _submit_until(a, LONG, 120, 14)
        assert a.hibernate_sequence(req, "conv-x")
        # the freeze drained in-flight chunks first, so the handle's
        # transcript is exactly the pre-hibernate delivery
        delivered = list(req.tokens)
        assert_no_leaks(a)
        a.stop()
        del a

        b = make_engine(tiny_llama)
        try:
            b.attach_spill_store(store)
            req2, info = b.thaw_sequence("conv-x")
            out = req2.wait(120)
            assert out == oracle["long120"]
            # exactly-once: the thawed handle carries the pre-hibernate
            # transcript, and the continuation extends it
            assert out[: len(delivered)] == delivered
            assert info["tokens"] == delivered
            assert b.stats()["jit_recompiles_total"] == 0
            assert_no_leaks(b)
        finally:
            b.stop()

    def test_same_handle_resume(self, tiny_llama, oracle, tmp_path):
        store = KvSpillStore(str(tmp_path))
        eng = make_engine(tiny_llama, prefix_cache=False)
        try:
            eng.attach_spill_store(store)
            req = _submit_until(eng, LONG, 120, 10)
            assert eng.hibernate_sequence(req, "h")
            req2, _info = eng.thaw_sequence("h", req=req)
            assert req2 is req  # the same API handle resumes
            assert req.wait(120) == oracle["long120"]
            assert_no_leaks(eng)
        finally:
            eng.stop()

    def test_degraded_thaw_reprefills_bit_identical(
            self, tiny_llama, oracle, tmp_path):
        from kubeflow_tpu.chaos.plan import FaultPlan

        plan = FaultPlan(seed=7).spill_torn(64)
        store = KvSpillStore(str(tmp_path), chaos=plan)
        a = make_engine(tiny_llama)
        a.attach_spill_store(store)
        req = _submit_until(a, LONG, 120, 12)
        assert a.hibernate_sequence(req, "s")
        a.stop()
        del a
        b = make_engine(tiny_llama)
        try:
            b.attach_spill_store(store)
            req2, info = b.thaw_sequence("s")
            out = req2.wait(120)
            assert info["degraded"]  # corrupt payload NEVER scattered
            assert out == oracle["long120"]  # re-prefill, same greedy
            st = b.stats()
            assert st["kv_spill_verify_failures_total"] == 1
            assert st["kv_thaws_degraded_total"] == 1
            assert st["jit_recompiles_total"] == 0
            assert_no_leaks(b)
        finally:
            b.stop()

    def test_hibernate_finished_request_is_noop(self, tiny_llama,
                                                tmp_path):
        store = KvSpillStore(str(tmp_path))
        eng = make_engine(tiny_llama)
        try:
            eng.attach_spill_store(store)
            req = eng.submit([3, 4, 5], max_new_tokens=4)
            req.wait(60)
            assert eng.hibernate_sequence(req, "done") is False
            assert not store.contains("done")
        finally:
            eng.stop()

    def test_mid_prefill_hibernate_resumes(self, tiny_llama, oracle,
                                           tmp_path):
        """A sequence hibernated at a chunk boundary mid-prefill thaws
        and finishes admission on the destination."""
        store = KvSpillStore(str(tmp_path))
        a = make_engine(tiny_llama, prefill_budget=16,
                        prefix_cache=False)
        a.attach_spill_store(store)
        req = a.submit(LONG, max_new_tokens=120)
        # freeze fast — likely mid-prefill (any boundary is valid)
        assert a.hibernate_sequence(req, "p")
        a.stop()
        del a
        b = make_engine(tiny_llama, prefill_budget=16,
                        prefix_cache=False)
        try:
            b.attach_spill_store(store)
            req2, _info = b.thaw_sequence("p")
            assert req2.wait(120) == oracle["long120"]
            assert_no_leaks(b)
        finally:
            b.stop()


# -- host tier in the engine ---------------------------------------------


class TestHostTierEngine:
    def test_spill_restore_parity_and_gauges(self, tiny_llama, oracle):
        eng = make_engine(tiny_llama, num_blocks=16, host_blocks=32,
                          host_watermark=1.0)  # always under pressure
        try:
            r = eng.submit(LONG, max_new_tokens=8)
            r.wait(60)
            deadline = time.time() + 10
            while eng.stats()["kv_blocks_host_tier"] == 0:
                assert time.time() < deadline, "host tier never spilled"
                time.sleep(0.05)
            # churn the HBM free list until the registry entry dies
            for i in range(6):
                eng.generate([100 + i, 101 + i, 102 + i] * 12,
                             max_new_tokens=4)
            out = eng.generate(LONG, max_new_tokens=8)
            assert out == oracle["long8"]
            st = eng.stats()
            assert st["kv_host_restores_total"] >= 1
            assert st["kv_thaws_total"] >= 1
            assert st["kv_spills_total"] >= 1
            assert st["prefix_hits"] >= 1
            assert st["jit_recompiles_total"] == 0
            assert_no_leaks(eng)
        finally:
            eng.stop()

    def test_no_spill_without_pressure(self, tiny_llama):
        eng = make_engine(tiny_llama, host_blocks=32,
                          host_watermark=0.0)  # watermark 0: never
        try:
            eng.generate(LONG, max_new_tokens=8)
            time.sleep(0.3)
            assert eng.stats()["kv_blocks_host_tier"] == 0
        finally:
            eng.stop()

    def test_host_tier_requires_paged_pool(self, tiny_llama):
        cfg, params = tiny_llama
        with pytest.raises(ValueError, match="host"):
            ContinuousEngine(cfg, params, block_size=0, host_blocks=8)


# -- cluster block registry ----------------------------------------------


class TestClusterRegistry:
    def test_prefix_digest_chain(self):
        digest = prefix_digest([np.asarray(LONG, np.int64)], 16)
        keys = block_keys(LONG, 16)
        assert digest[f"{keys[-1]:016x}"] == 4
        assert digest[f"{keys[0]:016x}"] == 1  # whole chain published

    def test_registry_locate_and_forget(self):
        from kubeflow_tpu.serving.traffic import KvBlockRegistry

        digest = prefix_digest([np.asarray(LONG, np.int64)], 16)
        text = "\n".join(
            f'kft_kv_prefix_key{{model="m",key="{k}"}} {d}'
            for k, d in digest.items())
        reg = KvBlockRegistry()
        assert reg.observe_metrics("r1", text) == 4
        backend, depth = reg.locate(block_keys(LONG, 16))
        assert backend == "r1" and depth == 4
        # a query sharing only the first 2 blocks still resolves
        backend2, d2 = reg.locate(block_keys(LONG[:32] + [999] * 32, 16))
        assert backend2 == "r1" and d2 == 2
        assert reg.locate(block_keys([7] * 64, 16)) == (None, 0)
        reg.forget("r1")
        assert reg.locate(block_keys(LONG, 16)) == (None, 0)

    def test_kv_fetch_install_across_replicas(self, tiny_llama, oracle):
        """Prefill-once-per-cluster: replica A computed a hot prefix;
        cold replica B fetches it over the kv_fetch wire and serves the
        same prompt with a prefix hit — bit-identical, no recompute."""
        from kubeflow_tpu.serving.gang import (
            KvMigrationServer,
            fetch_kv_prefix,
        )

        a = make_engine(tiny_llama)
        b = make_engine(tiny_llama)
        srv = None
        try:
            a.generate(LONG, max_new_tokens=8)
            srv = KvMigrationServer(a, token="t")
            # wrong token: refused, nothing served
            assert fetch_kv_prefix("127.0.0.1", srv.port, LONG,
                                   token="bad") == ([], [])
            covered, blocks = fetch_kv_prefix(
                "127.0.0.1", srv.port, LONG, token="t")
            assert len(covered) == 64 and len(blocks) == 4
            assert b.install_prefix(covered, blocks)
            st = b.stats()
            # installed blocks sit on the free list, content-registered
            assert st["kv_blocks_free"] == st["kv_blocks_total"]
            out = b.generate(LONG, max_new_tokens=8)
            assert out == oracle["long8"]
            st = b.stats()
            assert st["prefix_hits"] == 1
            assert st["prefix_tokens_saved"] >= 48
            assert st["jit_recompiles_total"] == 0
            assert srv.prefix_serves_total == 1
            assert_no_leaks(a, b)
        finally:
            if srv is not None:
                srv.close()
            a.stop()
            b.stop()

    def test_fetch_miss_returns_empty(self, tiny_llama):
        from kubeflow_tpu.serving.gang import (
            KvMigrationServer,
            fetch_kv_prefix,
        )

        a = make_engine(tiny_llama)
        srv = KvMigrationServer(a, token="t")
        try:
            covered, blocks = fetch_kv_prefix(
                "127.0.0.1", srv.port, [9] * 64, token="t")
            assert covered == [] and blocks == []
        finally:
            srv.close()
            a.stop()


class TestDisaggHibernate:
    def test_hibernate_finds_the_owning_tier(self, tiny_llama, oracle,
                                             tmp_path):
        """Under disaggregation a live sequence decodes on the DECODE
        tier — hibernate_session must try every paged engine, not just
        pools[0] (a prefill-role engine reports nothing-to-export:
        review regression), and resume must land on a decode-capable
        engine."""
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        cfg, params = tiny_llama
        ref = register_mem("disagg-hib", (cfg, params))
        model = TextGenerator("m", dict(
            params_ref=ref, tokenizer="bytes", num_slots=4,
            decode_chunk=2, block_size=16, prefill_budget=16,
            prefix_cache=False, max_new_tokens=8, warmup_groups=[],
            disaggregation={"prefill": 1, "decode": 1},
            hibernation={"root": str(tmp_path)}))
        model.load()
        try:
            req = model.engine.submit(LONG, max_new_tokens=120)
            deadline = time.time() + 120
            while len(req.tokens) < 6:
                assert time.time() < deadline
                time.sleep(0.01)
            assert model.hibernate_session(req, "d-sess")
            assert model.spill_store.contains("d-sess")
            req2, info = model.resume_session("d-sess", req=req)
            out = req2.wait(180)
            assert out == oracle["long120"]
            assert not info["degraded"]
        finally:
            model.stop()


# -- server surface: gauges + registry rows at /metrics -------------------


class TestServerSurface:
    @pytest.fixture(scope="class")
    def text_ref(self, tiny_llama):
        from kubeflow_tpu.serving.storage import register_mem

        cfg, params = tiny_llama
        return register_mem("hib-text", (cfg, params))

    def test_metrics_exports_tier_gauges_and_prefix_keys(
            self, text_ref, tmp_path):
        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.text import TextGenerator

        srv = ModelServer()
        model = TextGenerator("m", dict(
            params_ref=text_ref, tokenizer="bytes", num_slots=4,
            decode_chunk=2, block_size=16, prefix_cache=True,
            host_blocks=32, max_new_tokens=4, warmup_groups=[],
            hibernation={"root": str(tmp_path)}))
        srv.register(model)
        srv.start()
        try:
            import json as _json

            payload = _json.dumps({
                "model": "m", "prompt": "s" * 40,
                "max_tokens": 2}).encode()
            req = urllib.request.Request(
                srv.url + "/openai/v1/completions", data=payload,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
            # park one live session durably through the runtime helper
            eng = model.engine
            live = _submit_until(eng, LONG, 120, 4)
            assert model.hibernate_session(live, "sess-42")
            with urllib.request.urlopen(
                    srv.url + "/metrics", timeout=30) as r:
                text = r.read().decode()
            for gauge in ("kft_engine_kv_spills_total",
                          "kft_engine_kv_thaws_total",
                          "kft_engine_kv_spill_verify_failures_total",
                          "kft_engine_kv_blocks_host_tier",
                          "kft_engine_kv_sessions_hibernated"):
                assert gauge in text, gauge
            assert 'kft_engine_kv_sessions_hibernated{model="m"} 1' \
                in text
            # the block-registry probe surface (rank-0 /metrics rows)
            assert "kft_kv_prefix_key" in text
            # resume on the same handle through the runtime helper
            req2, info = model.resume_session("sess-42", req=live)
            assert req2 is live and not info["degraded"]
            req2.wait(120)
        finally:
            srv.stop()
