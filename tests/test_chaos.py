"""Chaos-driven recovery tests (ISSUE 1): the fault-injection layer
exercising every recovery path — gang restart under a mid-run crash with
jittered backoff, restart-budget window reset, node drain, kubelet
stalls, follower reconnect on the gang control stream, and Degraded
routing while a gang re-forms.

All control-plane scenarios run against the FakeKubelet (no real
processes); the gang-channel scenarios run real sockets between threads.
"""

import json
import threading
import time

import pytest

from kubeflow_tpu.api import Container, JaxJob, ObjectMeta, ReplicaSpec, Resources
from kubeflow_tpu.api.common import (
    JobConditionType,
    RestartPolicy,
    has_condition,
)
from kubeflow_tpu.api.jaxjob import KIND_JAXJOB
from kubeflow_tpu.chaos import ChaosSocket, FaultPlan
from kubeflow_tpu.controlplane import (
    Cluster,
    FakeKubelet,
    KIND_POD,
    PodScript,
    ScriptPhase,
    events_for,
)
from kubeflow_tpu.controlplane.objects import KIND_NODE, PodPhase
from kubeflow_tpu.serving.gang import ChannelClosed, GangChannel
from kubeflow_tpu.utils.net import allocate_port


def wait_for(fn, timeout=15.0, interval=0.02, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def make_job(name="job", replicas=2, tpu=0, restart_policy=RestartPolicy.ON_FAILURE,
             **run_policy):
    job = JaxJob(
        metadata=ObjectMeta(name=name),
        spec={
            "replica_specs": {
                "worker": ReplicaSpec(
                    replicas=replicas,
                    restart_policy=restart_policy,
                    template=Container(
                        resources=Resources(cpu=1, memory_gb=1, tpu=tpu)),
                )
            },
            "run_policy": run_policy,
        },
    )
    return job


def run_cluster(plan=None, default=None, hosts=4):
    c = Cluster()
    c.add_tpu_slice("s0", num_hosts=hosts, chips_per_host=4)
    script = plan.script_fn(default=default) if plan else default
    kubelet = FakeKubelet(c.store, script, chaos=plan)
    return c, kubelet


def await_terminal(c, name, timeout=30.0):
    def check():
        job = c.store.try_get(KIND_JAXJOB, name)
        if job and (
            has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
            or has_condition(job.status.conditions, JobConditionType.FAILED)
        ):
            return job
        return None

    return wait_for(check, timeout=timeout, desc=f"{name} terminal")


class TestFaultPlan:
    def test_seeded_plans_are_deterministic(self):
        picks_a = [FaultPlan(seed=7).crash_random_member(world=16).faults[0].index
                   for _ in range(3)]
        picks_b = [FaultPlan(seed=8).crash_random_member(world=16).faults[0].index
                   for _ in range(3)]
        assert len(set(picks_a)) == 1
        # different seeds decorrelate (16 choices; seeds 7/8 differ)
        assert picks_a[0] != picks_b[0] or FaultPlan(seed=7).rng.random() != \
            FaultPlan(seed=8).rng.random()

    def test_gang_member_loss_seeded_and_permanent(self):
        """ISSUE 10 satellite: the permanent-loss fault — seeded member
        + kill time frozen at build, rank 0 spared (losing the leader
        is a restart, not a resize), permanence expressed as a crash
        for effectively unlimited pod incarnations, and the in-process
        actuator fires each loss exactly once."""
        a = FaultPlan(seed=9).gang_member_loss(world=4)
        b = FaultPlan(seed=9).gang_member_loss(world=4)
        assert a.faults[0].index == b.faults[0].index >= 1
        assert a.faults[0].at == b.faults[0].at
        assert a.faults[0].times >= 1_000_000  # permanent: never heals
        transient = FaultPlan(seed=9).gang_member_loss(
            world=4, permanent=False)
        assert transient.faults[0].times == 1
        # actuator poll: due after `at`, fired exactly once
        plan = FaultPlan(seed=3).gang_member_loss(world=2, at=0.0)
        plan.activate()
        assert plan.due_member_losses() == [1]
        assert plan.due_member_losses() == []

    def test_kill_mid_resize_seeded_failpoint(self):
        """The resize chaos seam: a seeded phase choice, a failpoint
        that raises at exactly that phase, at most ``times`` firings."""
        assert (FaultPlan(seed=4).kill_mid_resize().faults[0].role
                == FaultPlan(seed=4).kill_mid_resize().faults[0].role)
        assert FaultPlan(seed=4).kill_mid_resize().faults[0].role \
            in FaultPlan.RESIZE_PHASES
        plan = FaultPlan(seed=0).kill_mid_resize(phase="commit")
        fp = plan.resize_failpoint()
        fp("export")  # clean pass-through off-phase
        fp("reshard")
        with pytest.raises(RuntimeError, match="mid-commit"):
            fp("commit")
        fp("commit")  # times=1: spent

    def test_multiphase_script_barrier_and_activity(self):
        """A pod can run healthy, cross the barrier, go quiet, then
        finish — three phases, one kubelet."""
        c, kubelet = run_cluster(default=lambda pod: PodScript(
            exit_code=0,
            phases=[
                ScriptPhase(duration=0.1, barrier=True),
                ScriptPhase(duration=0.15, activity=False),
            ]))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="phased", replicas=1))
                job = await_terminal(c, "phased")
                assert has_condition(
                    job.status.conditions, JobConditionType.SUCCEEDED)
                pod = c.store.get(KIND_POD, "phased-worker-0")
                assert pod.status.phase == PodPhase.SUCCEEDED
                assert pod.status.barrier_time is not None
                assert pod.status.last_activity is not None
                # the quiet phase stopped the heartbeat well before finish
                assert (pod.status.finish_time
                        - pod.status.last_activity) >= 0.1
                assert job.status.gang_startup_seconds is not None
            finally:
                kubelet.stop()


class TestChaosGangRestart:
    def test_mid_run_crash_restarts_with_backoff_and_recovers(self):
        """The acceptance scenario: a seeded FaultPlan kills a random
        gang member mid-run; the JaxJob returns to RUNNING through a
        jittered-backoff restart (not a fixed 0.05 s storm), and the
        recovery latency lands in status + a structured event."""
        plan = FaultPlan(seed=3).crash_random_member(world=2, at=0.1)
        c, kubelet = run_cluster(
            plan, default=lambda pod: PodScript(run_seconds=2.5))
        with c:
            kubelet.start()
            try:
                job = make_job(name="chaos", replicas=2, backoff_limit=3,
                               restart_backoff_seconds=0.4)
                c.store.create(job)
                job = wait_for(
                    lambda: (j := c.store.get(KIND_JAXJOB, "chaos"))
                    and j.status.last_recovery_seconds is not None and j,
                    desc="gang recovered")
                assert job.status.restart_count == 1
                assert has_condition(
                    job.status.conditions, JobConditionType.RUNNING)
                assert not has_condition(
                    job.status.conditions, JobConditionType.RESTARTING)
                # backoff floor: base 0.4 with jitter in [0.5, 1.5) means
                # the gang may not re-form sooner than 0.2 s after the
                # restart decision
                assert job.status.last_recovery_seconds >= 0.2
                reasons = [e.reason for e in
                           events_for(c.store, KIND_JAXJOB, "chaos")]
                assert "Restarting" in reasons and "GangRecovered" in reasons
                ev = next(e for e in events_for(c.store, KIND_JAXJOB, "chaos")
                          if e.reason == "GangRecovered")
                rec = json.loads(ev.message)
                assert rec["restart"] == 1
                assert rec["recovery_seconds"] >= 0.2
                # the restart event carries its backoff (structured)
                rev = next(e for e in events_for(c.store, KIND_JAXJOB, "chaos")
                           if e.reason == "Restarting")
                assert 0.2 <= json.loads(rev.message)["backoff_seconds"] < 0.6
            finally:
                kubelet.stop()

    def test_backoff_limit_exhaustion_under_flapping(self):
        plan = FaultPlan(seed=0).flaky(index=0, failures=10)
        c, kubelet = run_cluster(
            plan, default=lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(
                    name="flap", replicas=2, backoff_limit=1,
                    restart_backoff_seconds=0.05))
                job = await_terminal(c, "flap")
                assert has_condition(
                    job.status.conditions, JobConditionType.FAILED)
                # exactly one restart: the workqueue's in-flight dedup
                # serializes per-key reconciles, so one failure cannot be
                # double-counted by concurrent workers
                assert job.status.restart_count == 1
            finally:
                kubelet.stop()

    def test_restart_window_resets_budget(self):
        """A job that crashes every ~0.6 s but is stable longer than the
        0.3 s restart window between crashes survives 3 crashes on a
        backoff_limit of 1 — the budget bounds flapping, not lifetime."""
        plan = FaultPlan(seed=0).crash_pod(index=0, at=0.6, times=3)
        c, kubelet = run_cluster(
            plan, default=lambda pod: PodScript(run_seconds=1.0))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(
                    name="windowed", replicas=2, backoff_limit=1,
                    restart_backoff_seconds=0.05,
                    restart_window_seconds=0.3))
                job = await_terminal(c, "windowed", timeout=45)
                assert has_condition(
                    job.status.conditions, JobConditionType.SUCCEEDED), (
                    job.status)
                reasons = [e.reason for e in
                           events_for(c.store, KIND_JAXJOB, "windowed")]
                assert "RestartBudgetReset" in reasons
            finally:
                kubelet.stop()

    def test_node_drain_preempts_and_gang_reforms(self):
        plan = FaultPlan(seed=0).node_drain("s0-host-0", at=0.3)
        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=2, chips_per_host=4)
        c.add_tpu_slice("s1", num_hosts=2, chips_per_host=4)
        kubelet = FakeKubelet(
            c.store, plan.script_fn(
                default=lambda pod: PodScript(run_seconds=1.2)),
            chaos=plan)
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(
                    name="drained", replicas=2, tpu=4, backoff_limit=2,
                    restart_backoff_seconds=0.05))
                job = await_terminal(c, "drained", timeout=45)
                assert has_condition(
                    job.status.conditions, JobConditionType.SUCCEEDED), (
                    job.status)
                assert job.status.restart_count >= 1
                assert c.store.try_get(KIND_NODE, "s0-host-0") is None
            finally:
                kubelet.stop()

    def test_kubelet_stall_delays_startup(self):
        plan = FaultPlan(seed=0).kubelet_stall(at=0.0, duration=0.5)
        c, kubelet = run_cluster(
            plan, default=lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="stalled", replicas=2))
                job = await_terminal(c, "stalled")
                assert has_condition(
                    job.status.conditions, JobConditionType.SUCCEEDED)
                # pods could not start while the kubelet was stalled
                assert job.status.gang_startup_seconds >= 0.4
            finally:
                kubelet.stop()

    def test_barrier_hang_never_records_gang_startup(self):
        plan = FaultPlan(seed=0).barrier_hang(index=1)
        c, kubelet = run_cluster(
            plan, default=lambda pod: PodScript(
                hang=True, barrier_after=0.0))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="wedged", replicas=2))
                wait_for(
                    lambda: (j := c.store.get(KIND_JAXJOB, "wedged"))
                    and has_condition(
                        j.status.conditions, JobConditionType.RUNNING),
                    desc="job running")
                time.sleep(0.3)
                job = c.store.get(KIND_JAXJOB, "wedged")
                assert job.status.gang_startup_seconds is None
            finally:
                kubelet.stop()


class TestGangChannelChaos:
    """Control-stream recovery with real sockets, no engine/jax."""

    CHAN = dict(hb_interval=0.05, dead_peer_timeout=0.5,
                reattach_timeout=5.0, reconnect_timeout=5.0)

    def _run_follower(self, port, out, plan=None, token=""):
        def body():
            try:
                ch = GangChannel.connect(
                    "127.0.0.1", port, rank=1, token=token,
                    sock_wrap=plan.socket_wrapper("follower") if plan else None,
                    **self.CHAN)
                while True:
                    msg = ch.next()
                    if msg == ("stop",):
                        break
                    out.setdefault("msgs", []).append(msg)
                ch.close()
            except Exception as e:  # noqa: BLE001
                out["error"] = e

        t = threading.Thread(target=body)
        t.start()
        return t

    def test_follower_reconnect_replays_missed_frames(self):
        """The acceptance scenario: the follower's socket drops
        mid-stream; it reconnects with backoff, re-auths, and rank 0
        replays exactly the missed frames — every message arrives once,
        in order, and the stream survives."""
        port = allocate_port()
        plan = FaultPlan(seed=0).socket_drop(role="follower", after_calls=30)
        out = {}
        t = self._run_follower(port, out, plan=plan, token="s3cret")
        leader = GangChannel.listen(port, 1, token="s3cret", **self.CHAN)
        for i in range(40):
            leader.publish(("n", i))
            time.sleep(0.005)
        leader.publish(("stop",))
        t.join(timeout=20)
        leader.close()
        assert not t.is_alive() and "error" not in out, out.get("error")
        assert out["msgs"] == [("n", i) for i in range(40)]

    def test_heartbeats_keep_idle_stream_alive(self):
        port = allocate_port()
        out = {}
        t = self._run_follower(port, out)
        leader = GangChannel.listen(port, 1, **self.CHAN)
        time.sleep(1.2)  # >> dead_peer_timeout with no publishes
        leader.publish(("late", 1))
        leader.publish(("stop",))
        t.join(timeout=10)
        leader.close()
        assert out.get("msgs") == [("late", 1)] and "error" not in out

    def test_permanently_dead_follower_goes_fatal_after_grace(self):
        port = allocate_port()
        chan = dict(self.CHAN, reattach_timeout=0.6)
        # the follower must die strictly AFTER the leader admitted it:
        # without the gate, a loaded box could deschedule the main
        # thread long enough for the join AND the silent death AND the
        # eviction to all land before listen() checks its follower
        # count — listen then waits for a rank that already came and
        # went (the solo-passing full-suite flake, PR 10's tier-1 run)
        admitted = threading.Event()

        def flash_follower():
            ch = GangChannel.connect("127.0.0.1", port, rank=1, **chan)
            admitted.wait(30)
            ch._closing.set()  # die silently: no acks, socket closed
            ch._sock.close()

        t = threading.Thread(target=flash_follower)
        t.start()
        leader = GangChannel.listen(port, 1, **chan)
        admitted.set()  # listen returned => rank 1 is installed
        t.join()
        deadline = time.time() + 10
        raised = None
        while time.time() < deadline and raised is None:
            try:
                leader.publish(("x",))
                time.sleep(0.05)
            except ChannelClosed as e:
                raised = e
        leader.close()
        assert raised is not None, "publish never went fatal"

    def test_duplicate_rank_replaces_not_consumes_quota(self):
        """An extra token-valid connection for rank 1 REPLACES the
        existing one: the old socket is closed, the new one gets the
        stream, and no follower slot is burned (ADVICE r5)."""
        port = allocate_port()
        leader = GangChannel.listen(port, 0, token="t", **self.CHAN)
        first = GangChannel.connect("127.0.0.1", port, rank=1, token="t",
                                    **self.CHAN)
        wait_for(lambda: 1 not in leader.missing_ranks
                 and leader._followers, desc="first joined")
        second = GangChannel.connect("127.0.0.1", port, rank=1, token="t",
                                     **self.CHAN)
        wait_for(lambda: leader._followers.get(1) is not None
                 and len(leader._followers) == 1, desc="second installed")
        # wait until the second connection has displaced the first
        time.sleep(0.2)
        leader.publish(("hello", 1))
        got = second.next()
        assert got == ("hello", 1)
        leader.close()
        first.close()
        second.close()

    def test_bad_token_never_admitted(self):
        port = allocate_port()
        leader = GangChannel.listen(port, 0, token="right", **self.CHAN)
        intruder = GangChannel.connect(
            "127.0.0.1", port, rank=1, token="wrong", **self.CHAN)
        time.sleep(0.3)
        assert not leader._followers  # handshake rejected, no slot taken
        intruder.close()
        leader.close()

    def test_chaos_socket_delay_passthrough(self):
        """A delay-only ChaosSocket slows sends but corrupts nothing."""
        import socket as socketlib

        a, b = socketlib.socketpair()
        try:
            ca = ChaosSocket(a, send_delay=0.01)
            t0 = time.monotonic()
            ca.sendall(b"ping")
            assert time.monotonic() - t0 >= 0.01
            assert b.recv(4) == b"ping"
        finally:
            a.close()
            b.close()


class TestDegradedRouting:
    def test_degraded_phase_routes_to_healthy_replicas(self):
        """One of two replicas stops answering readiness: the ISvc phase
        goes Degraded (not Ready, not Loading) and the router only holds
        the healthy backend; when the replica returns, phase goes back to
        Ready."""
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            KIND_INFERENCE_SERVICE,
        )

        class _Unready:
            """A predictor handle whose readiness probe fails (a gang
            re-forming after a member loss, from the router's view)."""

            def __init__(self, inner):
                self.inner = inner
                self.ready = False

            def __getattr__(self, name):
                return getattr(self.inner, name)

        c = Cluster()
        c.enable_serving()
        with c:
            c.store.create(InferenceService(
                metadata=ObjectMeta(name="deg"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    handler="kubeflow_tpu.serving.runtimes:EchoModel",
                    min_replicas=2, max_replicas=2))))
            isvc = wait_for(
                lambda: (o := c.store.get(KIND_INFERENCE_SERVICE, "deg"))
                and o.status.phase == InferenceServicePhase.READY and o,
                desc="isvc ready")
            ctrl = next(ct for ct in c.controllers
                        if ct.kind == KIND_INFERENCE_SERVICE)
            dep = ctrl._deployments["default/deg"]
            wait_for(lambda: len(dep.stable.predictors) == 2,
                     desc="two replicas")
            healthy = dep.stable.predictors[1]
            dep.stable.predictors[0] = _Unready(dep.stable.predictors[0])
            isvc = wait_for(
                lambda: (o := c.store.get(KIND_INFERENCE_SERVICE, "deg"))
                and o.status.phase == InferenceServicePhase.DEGRADED and o,
                desc="isvc degraded")
            assert "re-forming" in isvc.status.message
            # the router holds only the healthy backend
            pools = dep.router._pools
            assert [u for urls, _ in pools for u in urls] == [healthy.url]
            # replica comes back -> Ready again
            dep.stable.predictors[0] = dep.stable.predictors[0].inner
            wait_for(
                lambda: c.store.get(KIND_INFERENCE_SERVICE, "deg")
                .status.phase == InferenceServicePhase.READY,
                desc="isvc ready again")


class TestTokenHygiene:
    def test_gang_token_not_in_jaxjob_env(self, tmp_path):
        """The gang admission secret travels by 0600 token file; only the
        PATH appears in the (cluster-readable) JaxJob env."""
        import os

        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            GangSpec,
            InferenceService,
            InferenceServiceSpec,
        )
        from kubeflow_tpu.controlplane import Store
        from kubeflow_tpu.serving.controller import _GangPredictor
        from kubeflow_tpu.serving.gang import ENV_SERVE_CONFIG, _resolve_gang_token

        store = Store()
        isvc = InferenceService(
            metadata=ObjectMeta(name="tok"),
            spec=InferenceServiceSpec(predictor=ComponentSpec(
                handler="kubeflow_tpu.serving.runtimes:EchoModel",
                gang=GangSpec(hosts=2, mesh_axes={"model": 8},
                              chips_per_host=4))))
        handle = _GangPredictor(
            store, isvc, rev=1, gang=isvc.spec.predictor.gang, cfg={})
        job = store.get(KIND_JAXJOB, handle.job_name)
        env = job.spec.replica_specs["worker"].template.env
        conf = json.loads(env[ENV_SERVE_CONFIG])
        assert "gang_token" not in conf
        path = conf["gang_token_file"]
        assert os.stat(path).st_mode & 0o777 == 0o600
        token = _resolve_gang_token(conf)
        assert len(token) == 32  # the secret exists, off-env
        handle.stop()
        assert not os.path.exists(path)  # side channel cleaned up

    def test_profile_api_token_redacted_on_reads(self):
        """ADVICE r5 high: GET /apis/profiles must not leak other
        tenants' bearer tokens; a PUT round-tripping the redaction
        sentinel preserves the stored credential."""
        import urllib.request

        from kubeflow_tpu.api.platform import Profile, ProfileSpec

        c = Cluster()
        with c:
            url = c.serve_api(token="admin-secret")
            c.store.create(Profile(
                metadata=ObjectMeta(name="alice", namespace="kft-profiles"),
                spec=ProfileSpec(owner="alice", api_token="tok-alice")))

            def req(path, method="GET", body=None):
                r = urllib.request.Request(
                    url + path, method=method,
                    data=json.dumps(body).encode() if body else None,
                    headers={"Authorization": "Bearer admin-secret",
                             "Content-Type": "application/json"})
                with urllib.request.urlopen(r, timeout=10) as resp:
                    return json.loads(resp.read())

            listed = req("/apis/profiles")["items"]
            assert all(p["spec"]["api_token"] == "**redacted**"
                       for p in listed if p["spec"].get("api_token"))
            got = req("/apis/Profile/kft-profiles/alice")
            assert got["spec"]["api_token"] == "**redacted**"
            # the stored credential is intact and still authenticates
            assert c.store.get(
                "Profile", "alice", "kft-profiles").spec.api_token == "tok-alice"
            # GET -> PUT round-trip must not clobber the token
            got["spec"]["owner"] = "alice2"
            req("/apis/Profile/kft-profiles/alice", method="PUT", body=got)
            assert c.store.get(
                "Profile", "alice", "kft-profiles").spec.api_token == "tok-alice"

    def test_legacy_inline_gang_token_scrubbed_from_env_reads(self):
        """Defense in depth: a hand-rolled JaxJob with an inline
        gang_token in KFT_SERVE_CONFIG reads back without it."""
        import urllib.request

        c = Cluster()
        with c:
            url = c.serve_api()
            job = make_job(name="legacy", replicas=1)
            job.spec.replica_specs["worker"].template.env = {
                "KFT_SERVE_CONFIG": json.dumps(
                    {"gang_port": 1, "gang_token": "sekrit"})}
            c.store.create(job)
            with urllib.request.urlopen(
                    url + "/apis/JaxJob/default/legacy", timeout=10) as resp:
                got = json.loads(resp.read())
            raw = got["spec"]["replica_specs"]["worker"]["template"]["env"][
                "KFT_SERVE_CONFIG"]
            assert "sekrit" not in raw and "gang_token" not in raw
            assert json.loads(raw)["gang_port"] == 1  # rest intact
            # GET -> PUT round-trip must re-attach the stored token, not
            # silently strip the gang's credential (retry the optimistic-
            # concurrency conflict: the live controller bumps rv too)
            import urllib.error

            for _ in range(20):
                with urllib.request.urlopen(
                        url + "/apis/JaxJob/default/legacy",
                        timeout=10) as resp:
                    got = json.loads(resp.read())
                req = urllib.request.Request(
                    url + "/apis/JaxJob/default/legacy", method="PUT",
                    data=json.dumps(got).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        assert resp.status == 200
                    break
                except urllib.error.HTTPError as e:
                    if e.code != 409:
                        raise
            else:
                raise AssertionError("PUT never beat the controller's rv")
            stored = c.store.get(KIND_JAXJOB, "legacy")
            conf = json.loads(
                stored.spec.replica_specs["worker"].template.env[
                    "KFT_SERVE_CONFIG"])
            assert conf["gang_token"] == "sekrit"


class TestStopScannerIncremental:
    """The O(len^2) stop-rescan fix (ADVICE r5 low): the incremental
    scanner must agree with the naive full rescan."""

    def _naive(self, tokenizer, ids, stops):
        text = tokenizer.decode(ids)
        cut = None
        for ss in stops:
            i = text.find(ss)
            if i >= 0 and (cut is None or i < cut):
                cut = i
        return cut

    def test_matches_naive_scan_over_growing_stream(self):
        from kubeflow_tpu.serving.text import ByteTokenizer, _StopScanner

        tok = ByteTokenizer()
        stops = ["END", "\n\n"]
        text = "hello wörld" + "x" * 50 + "\n\nmore"
        ids = tok.encode(text)
        scanner = _StopScanner(tok, stops)
        hit_at = None
        for n in range(0, len(ids) + 1, 3):  # polls see growing prefixes
            cut = scanner.scan(ids[:n])
            if cut is not None:
                hit_at = (n, cut)
                break
        assert hit_at is not None
        n, cut = hit_at
        assert cut == self._naive(tok, ids[:n], stops)
        assert tok.decode(ids[:n])[:cut].endswith("x")

    def test_multibyte_and_out_of_range_ids(self):
        from kubeflow_tpu.serving.text import ByteTokenizer, _StopScanner

        tok = ByteTokenizer()
        ids = tok.encode("héllo STOP tail") + [999] + tok.encode("STOP")
        scanner = _StopScanner(tok, ["STOP"])
        # feed one id at a time — split multibyte chars land mid-poll
        cut = None
        for n in range(1, len(ids) + 1):
            cut = scanner.scan(ids[:n])
            if cut is not None:
                break
        assert cut == self._naive(tok, ids[:n], ["STOP"])

    def test_incremental_decoder_matches_full_decode(self):
        from kubeflow_tpu.serving.text import ByteTokenizer

        tok = ByteTokenizer()
        ids = tok.encode("aé漢z") + [400] + tok.encode("done")
        dec = tok.incremental_decoder()
        out = "".join(dec.decode([i]) for i in ids)
        assert out == tok.decode(ids)

    def test_no_stops_scanner_unused_wait_path(self):
        """_wait_with_stops without stops defers to Request.wait — guard
        the fast path stays intact (pure signature check, no engine)."""
        from kubeflow_tpu.serving.text import TextGenerator

        class _Req:
            def wait(self, timeout):
                return [1, 2, 3]

        tg = TextGenerator.__new__(TextGenerator)
        assert tg._wait_with_stops(_Req(), []) == [1, 2, 3]


class TestLockAuditUnderChaos:
    """analysis/runtime.py LockAudit as the chaos harness's lock-order
    recorder: the static lock-order rule sees lexical nesting; this
    sees the acquisition orders a FAULTED schedule actually produced —
    reconnect storms drive the leader through admit/evict/replay paths
    a clean run never takes."""

    CHAN = dict(hb_interval=0.05, dead_peer_timeout=0.5,
                reattach_timeout=5.0, reconnect_timeout=5.0)

    def test_no_inversions_across_reconnect_storm(self):
        from kubeflow_tpu.analysis.runtime import LockAudit

        port = allocate_port()
        plan = FaultPlan(seed=3).socket_drop(role="follower",
                                             after_calls=25)
        audit = LockAudit()
        audit.instrument(plan, "_lock", "FaultPlan._lock")
        out = {}

        def follower():
            try:
                ch = GangChannel.connect(
                    "127.0.0.1", port, rank=1, token="t",
                    sock_wrap=plan.socket_wrapper("follower"),
                    **self.CHAN)
                while True:
                    if ch.next() == ("stop",):
                        break
                ch.close()
            except Exception as e:  # noqa: BLE001
                out["error"] = e

        t = threading.Thread(target=follower, daemon=True)
        t.start()
        leader = GangChannel.listen(port, 1, token="t", **self.CHAN)
        # audit the leader's channel lock through the faulted run: the
        # hb loop, publish fan-out, evict, and re-admit replay all take
        # it from different threads while the drop forces reconnects
        audit.instrument(leader, "_lock", "GangChannel._lock")
        for i in range(40):
            leader.publish(("n", i))
            time.sleep(0.005)
        leader.publish(("stop",))
        t.join(timeout=20)
        leader.close()
        assert "error" not in out, out.get("error")
        rep = audit.report()
        assert "GangChannel._lock" in rep["locks"]
        assert audit.inversions() == [], rep


class TestControlPlaneCrash:
    """ISSUE 5 tentpole: kill -9 the control plane at seeded WAL offsets
    mid-reconcile.  A restarted Cluster on the same data_dir must replay
    snapshot+WAL into a consistent store (resumed resourceVersion, torn
    tail tolerated) and reconverge to the no-crash terminal state with
    zero duplicate and zero orphaned pods.  The kubelet (the node) keeps
    running across the crash and is re-pointed at the restarted control
    plane — surviving pods are adopted, never recreated."""

    WORLD = 4

    def _ensure_infra(self, cluster):
        """The client-retry half of recovery: infra + job manifests are
        re-applied idempotently after a restart (a create whose WAL
        record died with the machine was never acknowledged — the
        real-world client retries it)."""
        from kubeflow_tpu.controlplane.store import AlreadyExists

        for i in range(self.WORLD):
            try:
                cluster.add_node(f"s0-host-{i}", tpu=4, slice_id="s0")
            except AlreadyExists:
                pass

    def _assert_consistent_store(self, cluster):
        """Recovered rv counter sits at/above every recovered object, and
        keeps moving — optimistic concurrency survives the restart."""
        from kubeflow_tpu.controlplane.objects import Service as CpService

        rv = cluster.store._last_rv
        for kind in ("JaxJob", "Pod", "Node", "Service", "PodGroup"):
            for o in cluster.store.list(kind):
                assert o.metadata.resource_version <= rv, (kind, o.key)
        probe = cluster.store.create(
            CpService(metadata=ObjectMeta(name="rv-probe")))
        assert probe.metadata.resource_version > rv

    def _assert_exact_gang(self, cluster, name, phase=None):
        """Zero duplicate, zero orphaned pods: one pod per (type, index)
        slot, every one owned by the job."""
        pods = [p for p in cluster.store.list(KIND_POD)
                if p.metadata.labels.get("job-name") == name]
        slots = sorted(
            (p.metadata.labels.get("replica-type"),
             p.metadata.labels.get("replica-index")) for p in pods)
        assert len(pods) == self.WORLD, slots
        assert len(set(slots)) == self.WORLD, f"duplicate slots: {slots}"
        for p in pods:
            assert any(r.kind == KIND_JAXJOB and r.name == name
                       and r.controller
                       for r in p.metadata.owner_references), p.metadata.name
            if phase is not None:
                assert p.status.phase == phase, (p.metadata.name,
                                                 p.status.phase)

    def _crash_restart_jaxjob(self, data_dir, seed, script, run_policy,
                              crash_kwargs, extra_faults=None):
        """One seeded kill/restart cycle; returns the restarted cluster
        (started, kubelet re-attached) and the shared kubelet."""
        plan = FaultPlan(seed=seed).control_plane_crash(**crash_kwargs)
        if extra_faults:
            extra_faults(plan)
        cp = plan.wal_crashpoint()
        c = Cluster(data_dir=data_dir, wal_crashpoint=cp)
        self._ensure_infra(c)
        kubelet = FakeKubelet(c.store, plan.script_fn(default=script),
                              chaos=plan)
        c.start()
        kubelet.start()
        c.store.create(make_job("crash-job", replicas=self.WORLD, tpu=4,
                                **run_policy))
        assert cp.fired.wait(30), "crashpoint never fired"
        # the dead incarnation: nothing it does from here persists; its
        # threads are reaped (the harness standing in for the OS)
        c.stop()

        c2 = Cluster(data_dir=data_dir)
        kubelet.attach_store(c2.store)  # node survived; relist BEFORE start
        c2.start()
        self._ensure_infra(c2)
        if c2.store.try_get(KIND_JAXJOB, "crash-job") is None:
            c2.store.create(make_job("crash-job", replicas=self.WORLD,
                                     tpu=4, **run_policy))
        return c2, kubelet

    def test_crash_during_scaleup_sweep_converges_to_success(self, tmp_path):
        """Seeded sweep: the control plane dies at an arbitrary WAL
        offset while the gang is scaling up; every offset must reconverge
        to the no-crash terminal state (job SUCCEEDED, one SUCCEEDED pod
        per slot)."""
        for seed in (1, 2):
            d = str(tmp_path / f"seed-{seed}")
            c2, kubelet = self._crash_restart_jaxjob(
                d, seed,
                script=lambda pod: PodScript(run_seconds=0.4),
                run_policy={"backoff_limit": 3,
                            "restart_backoff_seconds": 0.05},
                crash_kwargs={"max_records": 40})
            try:
                job = await_terminal(c2, "crash-job", timeout=30)
                assert has_condition(job.status.conditions,
                                     JobConditionType.SUCCEEDED), (
                    seed, job.status.conditions)
                self._assert_exact_gang(c2, "crash-job",
                                        phase=PodPhase.SUCCEEDED)
                self._assert_consistent_store(c2)
            finally:
                kubelet.stop()
                c2.stop()

    def test_crash_during_gang_recovery_reforms_exact_gang(self, tmp_path):
        """The nastiest overlap: a gang member dies, the controller is
        mid-way through the delete-all/restart dance, and THEN the
        control plane dies.  The restarted plane must finish re-forming
        the gang — all workers Running again, no slot doubled, ghosts
        (store pods no node backs) failed over instead of waited on."""
        d = str(tmp_path / "recovery")
        c2, kubelet = self._crash_restart_jaxjob(
            d, 5,
            script=lambda pod: PodScript(run_seconds=60.0),
            run_policy={"backoff_limit": 6,
                        "restart_backoff_seconds": 0.05},
            crash_kwargs={"after_records": 30, "torn_bytes": 11},
            extra_faults=lambda plan: plan.crash_pod(1, at=0.1, times=1))
        try:
            wait_for(
                lambda: sum(
                    p.status.phase == PodPhase.RUNNING
                    for p in c2.store.list(KIND_POD)
                    if p.metadata.labels.get("job-name") == "crash-job")
                == self.WORLD,
                timeout=30, desc="gang re-formed after crash-restart")
            self._assert_exact_gang(c2, "crash-job", phase=PodPhase.RUNNING)
            self._assert_consistent_store(c2)
            job = c2.store.get(KIND_JAXJOB, "crash-job")
            assert job.status.restart_count <= 6
        finally:
            kubelet.stop()
            c2.stop()

    def test_crash_during_isvc_rollout_converges_to_new_revision(
            self, tmp_path):
        """Control-plane death mid-ISvc-rollout: the restarted serving
        controller rebuilds its (intentionally non-durable) deployment
        state from the recovered spec and converges to the same terminal
        state as the no-crash rollout — READY on the new revision."""
        import urllib.request

        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )

        KIND_ISVC = "InferenceService"

        def make_isvc(tag):
            return InferenceService(
                metadata=ObjectMeta(name="svc"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="echo"),
                    min_replicas=1, max_replicas=2,
                    config={"tag": tag})))

        def wait_phase(cluster, phase, timeout=25):
            return wait_for(
                lambda: (isvc := cluster.store.try_get(KIND_ISVC, "svc"))
                and isvc.status.phase == phase and isvc,
                timeout=timeout, desc=f"isvc {phase}")

        d = str(tmp_path / "isvc")
        # arm far away; re-aim at the live WAL offset once READY so the
        # kill lands inside the rollout's reconcile churn
        plan = FaultPlan(seed=9).control_plane_crash(
            after_records=10 ** 9, torn_bytes=7)
        cp = plan.wal_crashpoint()
        c = Cluster(data_dir=d, wal_crashpoint=cp)
        c.add_tpu_slice("s0", num_hosts=1, chips_per_host=4)
        c.enable_serving()
        c.start()
        c.store.create(make_isvc("v1"))
        wait_phase(c, InferenceServicePhase.READY)
        cp.after_records = c.store.wal.appended_records + 2
        c.store.update_with_retry(
            KIND_ISVC, "svc", "default",
            lambda o: o.spec.predictor.config.update({"tag": "v2"}))
        assert cp.fired.wait(20), "crashpoint never fired"
        c.stop()

        c2 = Cluster(data_dir=d)
        c2.enable_serving()
        c2.start()
        try:
            recovered = c2.store.get(KIND_ISVC, "svc")
            if recovered.spec.predictor.config.get("tag") != "v2":
                # the rollout write died with the machine — the client
                # retries it (it was never acknowledged durable)
                c2.store.update_with_retry(
                    KIND_ISVC, "svc", "default",
                    lambda o: o.spec.predictor.config.update({"tag": "v2"}))
            # the RECOVERED status is the pre-crash one (phase READY,
            # old revision, dead URL) — convergence means the restarted
            # controller has re-written it for the v2 revision
            isvc = wait_for(
                lambda: (o := c2.store.try_get(KIND_ISVC, "svc"))
                and o.status.phase == InferenceServicePhase.READY
                and (o.status.stable_spec or {}).get(
                    "predictor", {}).get("config", {}).get("tag") == "v2"
                and o,
                timeout=25, desc="isvc READY on v2 revision")
            # the recovered revision actually serves
            req = urllib.request.Request(
                f"{isvc.status.url}/v1/models/svc:predict",
                data=json.dumps({"instances": [1, 2]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
            self._assert_consistent_store(c2)
        finally:
            c2.stop()


class TestKvMigrateChaos:
    """Seeded kill/socket-drop mid-``kv_migrate`` (ISSUE 8): the
    transfer is copy-then-cutover, so a connection that dies at ANY
    frame leaves the source sequence decoding in place, delivers every
    client token exactly once, and leaks zero blocks on either
    allocator (``kv_blocks_free`` returns to baseline on both ends)."""

    def _tiny_paged(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        kw = dict(num_slots=4, decode_chunk=2, prefix_cache=False,
                  block_size=16)
        return cfg, params, kw, ContinuousEngine

    def test_seeded_drop_mid_migration_copy_then_cutover(self):
        from kubeflow_tpu.serving.gang import (
            KvMigrationServer,
            migrate_sequence,
            register_migration_handle,
            unregister_migration_handle,
        )

        cfg, params, kw, Engine = self._tiny_paged()
        prompt = list(range(1, 65))
        ref = Engine(cfg, params, **kw)
        try:
            want = ref.generate(prompt, max_new_tokens=120)
        finally:
            ref.stop()
        for seed in (0, 1, 2):
            plan = FaultPlan(seed=seed).kv_migrate_drop()
            src = Engine(cfg, params, **kw)
            dst = Engine(cfg, params, **kw)
            srv = KvMigrationServer(dst, token="t")
            try:
                base_src = src.stats()["kv_blocks_free"]
                base_dst = dst.stats()["kv_blocks_free"]
                req = src.submit(prompt, max_new_tokens=120)
                wait_for(lambda: len(req.tokens) >= 3,
                         desc="tokens before export")
                snap = src.export_sequence(req)
                assert snap is not None
                mid = register_migration_handle(req)
                st = migrate_sequence(
                    snap, "127.0.0.1", srv.port, token="t", mid=mid,
                    sock_wrap=plan.socket_wrapper("kv_migrate"),
                    timeout=5.0)
                if st is True:
                    src.release_sequence(req)
                elif st is False or unregister_migration_handle(mid):
                    # definitive: rejected, or kv_commit never reached
                    # the destination — the source resumes immediately
                    unregister_migration_handle(mid)
                    src.kv_migrate_failures_total += 1
                    src.resume_sequence(req)
                else:
                    # commit delivered, ack lost (two-generals tail):
                    # the destination owns it — resuming blind would
                    # double-decode; await the late cutover instead
                    wait_for(lambda: dst._find_req_slot(req) is not None,
                             desc="late cutover after lost ack")
                    src.release_sequence(req)
                # exactly once, exactly the unmigrated tokens
                assert req.wait(120) == want, f"seed {seed}"
                assert len(req.tokens) == 120
                # zero leaked blocks on either side once all retires land
                wait_for(lambda: src.stats()["kv_blocks_free"]
                         == base_src, desc="src blocks back to baseline")
                wait_for(lambda: dst.stats()["kv_blocks_free"]
                         == base_dst, desc="dst blocks back to baseline")
            finally:
                srv.close()
                src.stop()
                dst.stop()

    def test_drop_during_drain_keeps_draining_engine_serving(self):
        """A drain whose wire transfer dies mid-stream falls back to
        decoding in place: migrate_live_sequences reports the failure,
        the conversation finishes on the source, nothing leaks."""
        from kubeflow_tpu.serving.continuous import migrate_live_sequences
        from kubeflow_tpu.serving.gang import (
            KvMigrationServer,
            migrate_sequence,
        )

        cfg, params, kw, Engine = self._tiny_paged()
        prompt = list(range(1, 65))
        ref = Engine(cfg, params, **kw)
        try:
            want = ref.generate(prompt, max_new_tokens=120)
        finally:
            ref.stop()
        plan = FaultPlan(seed=3).kv_migrate_drop(after_frames=2)
        src = Engine(cfg, params, **kw)
        dst = Engine(cfg, params, **kw)
        srv = KvMigrationServer(dst, token="t")
        try:
            base_src = src.stats()["kv_blocks_free"]
            req = src.submit(prompt, max_new_tokens=120)
            wait_for(lambda: len(req.tokens) >= 2, desc="tokens")

            def send(snap, _req):
                return migrate_sequence(
                    snap, "127.0.0.1", srv.port, token="t",
                    sock_wrap=plan.socket_wrapper("kv_migrate"),
                    timeout=5.0)

            moved, failed = migrate_live_sequences(src, send=send)
            assert failed == 1 and moved == 0
            assert src.kv_migrate_failures_total == 1
            assert req.wait(120) == want
            wait_for(lambda: src.stats()["kv_blocks_free"] == base_src,
                     desc="src blocks back to baseline")
            assert dst.stats()["kv_blocks_free"] \
                == dst.stats()["kv_blocks_total"]
        finally:
            srv.close()
            src.stop()
            dst.stop()


class TestKvSpillChaos:
    """Storage-tier faults (ISSUE 12): the spill path absorbs a writer
    dying at any phase (nothing publishes, the source resumes in place),
    a published spill losing bytes at rest (detected at thaw via the
    manifest hashes — re-prefilled, NEVER served), and wedged tier I/O
    (bounded stall on the hibernation worker, live decode unaffected).
    The headline scenario: replica death with hibernated sessions —
    every session resumes on a fresh replica with exactly-once tokens
    and zero leaked blocks on every allocator."""

    def _tiny_paged(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.analysis.runtime import BlockLedger
        from kubeflow_tpu.models import llama as llamalib
        from kubeflow_tpu.serving.continuous import ContinuousEngine

        cfg = llamalib.tiny()
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]

        def make(**kw):
            kw.setdefault("num_slots", 4)
            kw.setdefault("decode_chunk", 2)
            kw.setdefault("prefix_cache", False)
            kw.setdefault("block_size", 16)
            eng = ContinuousEngine(cfg, params, **kw)
            eng.attach_block_ledger(BlockLedger())
            return eng

        return make

    def test_storage_fault_builders_and_actuators(self):
        # seeded phase draw is deterministic; actuators drain per times
        assert (FaultPlan(seed=4).spill_kill_mid_write().faults[0].role
                == FaultPlan(seed=4).spill_kill_mid_write().faults[0].role)
        plan = FaultPlan(seed=0).spill_kill_mid_write("payload", times=2)
        assert plan.due_spill_kills() == ["payload"]
        assert plan.due_spill_kills() == ["payload"]
        assert plan.due_spill_kills() == []
        torn = FaultPlan(seed=1).spill_torn()
        assert torn.faults[0].torn_bytes in (1, 7, 64, 4096)
        assert torn.due_spill_torn() == [torn.faults[0].torn_bytes]
        assert torn.due_spill_torn() == []
        stall = FaultPlan(seed=2).tier_io_stall(0.01)
        assert stall.due_tier_stalls() == [0.01]
        assert stall.due_tier_stalls() == []

    def test_kill_mid_spill_sweep_exactly_once(self, tmp_path):
        """Seeded kill at every write phase: nothing publishes, the
        source resumes in place, tokens land exactly once."""
        from kubeflow_tpu.serving.storage import KvSpillStore

        make = self._tiny_paged()
        prompt = list(range(1, 65))
        ref = make()
        try:
            want = ref.generate(prompt, max_new_tokens=120)
        finally:
            ref.stop()
        for seed in (0, 1, 2):
            plan = FaultPlan(seed=seed).spill_kill_mid_write()  # seeded
            phase = plan.faults[0].role
            store = KvSpillStore(str(tmp_path / f"s{seed}"), chaos=plan)
            eng = make()
            try:
                eng.attach_spill_store(store)
                req = eng.submit(prompt, max_new_tokens=120)
                wait_for(lambda: len(req.tokens) >= 6, desc="tokens")
                with pytest.raises(Exception):
                    eng.hibernate_sequence(req, "conv")
                assert not store.contains("conv"), phase
                # the source still owns the sequence: exactly once
                assert req.wait(120) == want, f"seed {seed} ({phase})"
                assert eng.audit_blocks() == []
                assert eng.stats()["kv_blocks_leaked_total"] == 0
            finally:
                eng.stop()

    def test_torn_spill_sweep_detected_never_served(self, tmp_path):
        """Seeded torn-bytes sweep: every tear is detected at thaw
        (manifest hash mismatch), the session re-prefills from the
        token record, and the continuation stays bit-identical."""
        from kubeflow_tpu.serving.storage import KvSpillStore

        make = self._tiny_paged()
        prompt = list(range(1, 65))
        ref = make()
        try:
            want = ref.generate(prompt, max_new_tokens=120)
        finally:
            ref.stop()
        for seed in (0, 1):
            plan = FaultPlan(seed=seed).spill_torn()  # seeded byte draw
            store = KvSpillStore(str(tmp_path / f"t{seed}"), chaos=plan)
            a = make()
            a.attach_spill_store(store)
            req = a.submit(prompt, max_new_tokens=120)
            wait_for(lambda: len(req.tokens) >= 6, desc="tokens")
            assert a.hibernate_sequence(req, "conv")
            a.stop()
            del a
            b = make()
            try:
                b.attach_spill_store(store)
                req2, info = b.thaw_sequence("conv")
                assert info["degraded"], f"seed {seed}: tear undetected"
                assert req2.wait(120) == want, f"seed {seed}"
                assert store.verify_failures_total == 1
                assert b.audit_blocks() == []
            finally:
                b.stop()

    def test_tier_io_stall_bounded_live_decode_unaffected(self,
                                                          tmp_path):
        """A wedged storage mount stalls the HIBERNATING caller only:
        a concurrent live conversation keeps decoding through the
        window (the stall lands off the scheduler thread by
        construction — the analyzer's *Spill root pins it)."""
        from kubeflow_tpu.serving.storage import KvSpillStore

        make = self._tiny_paged()
        prompt = list(range(1, 65))
        ref = make()
        try:
            want_a = ref.generate(prompt, max_new_tokens=120)
            want_b = ref.generate([7, 8, 9], max_new_tokens=24)
        finally:
            ref.stop()
        plan = FaultPlan(seed=5).tier_io_stall(0.5, times=1)
        store = KvSpillStore(str(tmp_path), chaos=plan)
        eng = make()
        try:
            eng.attach_spill_store(store)
            victim = eng.submit(prompt, max_new_tokens=120)
            wait_for(lambda: len(victim.tokens) >= 6, desc="tokens")
            live = eng.submit([7, 8, 9], max_new_tokens=24)
            t0 = time.monotonic()
            assert eng.hibernate_sequence(victim, "conv")
            stalled = time.monotonic() - t0
            assert stalled >= 0.5  # the stall actually landed
            # the live conversation never noticed
            assert live.wait(120) == want_b
            req2, _ = eng.thaw_sequence("conv", req=victim)
            assert req2.wait(120) == want_a
            assert eng.audit_blocks() == []
        finally:
            eng.stop()

    def test_replica_death_with_hibernated_sessions(self, tmp_path):
        """The headline robustness scenario: a replica hibernates two
        conversations and dies (chaos replica_kill shape: the process
        is simply gone).  A fresh replica sharing the storage root
        thaws BOTH days later — exactly-once tokens, bit-identical
        greedy, zero leaked blocks on every allocator."""
        from kubeflow_tpu.serving.storage import KvSpillStore

        make = self._tiny_paged()
        p1, p2 = list(range(1, 65)), [5, 6, 7] * 8
        ref = make()
        try:
            want1 = ref.generate(p1, max_new_tokens=120)
            want2 = ref.generate(p2, max_new_tokens=90)
        finally:
            ref.stop()
        store = KvSpillStore(str(tmp_path))
        a = make()
        r1 = a.submit(p1, max_new_tokens=120)
        r2 = a.submit(p2, max_new_tokens=90)
        wait_for(lambda: len(r1.tokens) >= 4 and len(r2.tokens) >= 4,
                 desc="both conversations live")
        assert a.hibernate_sequence(r1, "c1", store=store)
        assert a.hibernate_sequence(r2, "c2", store=store)
        assert a.audit_blocks() == []
        assert store.session_count() == 2
        a.stop()  # replica death: nothing of A survives
        del a

        b = make()
        try:
            b.attach_spill_store(store)
            n1, i1 = b.thaw_sequence("c1")
            n2, i2 = b.thaw_sequence("c2")
            assert not i1["degraded"] and not i2["degraded"]
            assert n1.wait(120) == want1
            assert n2.wait(120) == want2
            assert b.stats()["jit_recompiles_total"] == 0
            assert b.audit_blocks() == []
            assert b.stats()["kv_blocks_leaked_total"] == 0
            assert store.session_count() == 0
        finally:
            b.stop()


class TestAutoscaleActuatorChaos:
    """Actuator-failure faults for the ClusterAutoscaler decision loop
    (ISSUE 15): a seeded failed placement / failed drain / failed
    resize must produce exponential backoff with at most ``max_retries``
    attempts per demand episode (then the channel PARKS — bounded, no
    oscillating resize storm), and a transient failure must converge
    back to a clean actuation.  Pure host loop: seeded FaultPlan
    failpoint + manual clock, no engines."""

    CHANNELS = ("replica_up", "replica_down", "resize", "tier", "zero")

    #: one sensor recipe per channel that makes decide() demand it
    SIGS = {
        "replica_up": {"replicas": 1, "min_replicas": 1,
                       "max_replicas": 4, "util": 5.0},
        "replica_down": {"replicas": 3, "min_replicas": 1,
                         "max_replicas": 4, "util": 0.0},
        "resize": {"replicas": 4, "min_replicas": 1, "max_replicas": 4,
                   "util": 5.0, "degree": 1},
        "tier": {"replicas": 1, "min_replicas": 1, "max_replicas": 1,
                 "util": 1.0, "prefill_pressure": 10.0,
                 "decode_pressure": 1.0, "prefill_replicas": 1,
                 "decode_replicas": 2},
        "zero": {"replicas": 1, "min_replicas": 0, "max_replicas": 4,
                 "util": 0.0, "idle_s": 999.0, "live": 0.0},
    }

    def _make(self, plan, sig, *, max_retries=3):
        from kubeflow_tpu.serving.autoscale import (
            AutoscalePolicy,
            ClusterAutoscaler,
        )

        policy = AutoscalePolicy(
            scale_to_zero=True, tp_degrees=(1, 2, 4),
            up_cooldown_s=0.0, down_cooldown_s=0.0, resize_cooldown_s=0.0,
            tier_cooldown_s=0.0, zero_cooldown_s=0.0,
            max_retries=max_retries, backoff_s=0.5, backoff_cap_s=4.0)
        fired = []
        acts = {c: (lambda dec, _c=c: fired.append(_c))
                for c in self.CHANNELS}
        auto = ClusterAutoscaler(
            policy, sensors=lambda: dict(sig), actuators=acts,
            failpoint=plan.autoscale_failpoint() if plan else None)
        return auto, fired

    def test_seeded_builder_deterministic_and_paired(self):
        for seed in (0, 3, 11):
            a = FaultPlan(seed=seed).autoscale_actuator_fail()
            b = FaultPlan(seed=seed).autoscale_actuator_fail()
            assert a.faults[0].role == b.faults[0].role
            assert a.faults[0].role in FaultPlan.AUTOSCALE_ACTUATORS
        plan = FaultPlan(seed=0).autoscale_actuator_fail("resize", times=2)
        assert plan.due_autoscale_fails() == ["resize"]
        fp = plan.autoscale_failpoint()
        fp("replica_up")  # wrong channel: clean pass-through
        with pytest.raises(RuntimeError):
            fp("resize")
        assert plan.due_autoscale_fails() == ["resize"]  # one left
        with pytest.raises(RuntimeError):
            fp("resize")
        assert plan.due_autoscale_fails() == []
        fp("resize")  # exhausted: pass-through
        with pytest.raises(ValueError):
            FaultPlan(seed=0).autoscale_actuator_fail("bogus")

    def test_dead_actuator_parks_after_bounded_retries(self):
        """A permanently failing actuator costs exactly max_retries
        attempts, with exponential backoff between them, then the
        channel parks — 50 more ticks of identical demand fire
        NOTHING (the no-flap contract)."""
        plan = FaultPlan(seed=1).autoscale_actuator_fail(
            "replica_up", times=10_000)
        auto, fired = self._make(plan, self.SIGS["replica_up"])
        t = 100.0
        for _ in range(3):
            auto.tick(now=t)          # attempt -> chaos failure
            gated = auto.tick(now=t + 0.01)  # inside backoff: gated
            assert gated.action == "none" and "backoff" in gated.reason \
                or "parked" in gated.reason
            t += 10.0                 # clear the backoff window
        assert auto.actuator_failures_total == 3
        assert auto.states["replica_up"].parked
        for _ in range(50):
            t += 1.0
            dec = auto.tick(now=t)
            assert dec.action == "none"
            assert "parked" in dec.reason
        assert auto.actuator_failures_total == 3  # bounded, forever
        assert fired == []  # the actuator body never ran
        # no oscillation: nothing else ever fired under constant demand
        assert {a for a, _ok in auto.history} == {"scale_up", "none"}

    def test_demand_change_resets_the_retry_budget(self):
        """Parking is PER DEMAND EPISODE: when the demanded action
        changes (the world moved on), a parked channel gets its retry
        budget back — a later episode may try again, still bounded."""
        plan = FaultPlan(seed=2).autoscale_actuator_fail(
            "replica_up", times=10_000)
        sig = dict(self.SIGS["replica_up"])
        auto, _fired = self._make(plan, sig)
        t = 100.0
        for _ in range(4):
            auto.tick(now=t)
            t += 10.0
        assert auto.states["replica_up"].parked
        assert auto.actuator_failures_total == 3
        # demand goes away (util inside the band): episode over
        sig.clear()
        sig.update({"replicas": 2, "min_replicas": 1, "max_replicas": 4,
                    "util": 1.0})
        for _ in range(30):  # predictor must forget the hot window
            t += 5.0
            auto.tick(now=t)
        assert not auto.states["replica_up"].parked  # reset on change
        # second episode: bounded again, not unbounded
        sig.clear()
        sig.update(self.SIGS["replica_up"])
        for _ in range(10):
            t += 10.0
            auto.tick(now=t)
        assert auto.states["replica_up"].parked
        assert auto.actuator_failures_total == 6  # 3 per episode

    def test_transient_failure_converges_each_channel(self):
        """Seeded sweep over every actuator channel: times=2 failures,
        then the SAME demand's next attempt succeeds — bounded retries
        consume every injected fault and the loop converges."""
        for chan in self.CHANNELS:
            plan = FaultPlan(seed=7).autoscale_actuator_fail(
                chan, times=2)
            auto, fired = self._make(plan, self.SIGS[chan])
            t, ok_actions = 100.0, []
            for _ in range(8):
                dec = auto.tick(now=t)
                if dec.action != "none" and auto.history[-1][1]:
                    ok_actions.append(dec.action)
                t += 10.0
            assert plan.due_autoscale_fails() == [], chan
            assert fired and fired[0] == chan, chan
            assert auto.actuator_failures_total == 2, chan
            assert not auto.states[chan].parked, chan
            assert ok_actions, chan  # converged to a clean actuation

    def test_seeded_draw_sweep_is_deterministic(self):
        roles = [FaultPlan(seed=s).autoscale_actuator_fail().faults[0].role
                 for s in range(16)]
        again = [FaultPlan(seed=s).autoscale_actuator_fail().faults[0].role
                 for s in range(16)]
        assert roles == again
        assert set(roles) <= set(FaultPlan.AUTOSCALE_ACTUATORS)
        assert len(set(roles)) > 1  # the draw actually varies by seed
