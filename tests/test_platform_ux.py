"""Platform-UX tier (SURVEY.md §2.4): profiles/quota, notebooks + culling,
PodDefault injection, central dashboard."""

import json
import time
import urllib.request

from kubeflow_tpu.api.common import Container, ObjectMeta, Resources
from kubeflow_tpu.api.platform import (
    Notebook,
    NotebookSpec,
    PodDefault,
    PodDefaultSpec,
    Profile,
    ProfileSpec,
    STOPPED_ANNOTATION,
)
from kubeflow_tpu.controlplane import Cluster, FakeKubelet, PodScript
from kubeflow_tpu.controlplane.objects import (
    KIND_POD,
    KIND_PODGROUP,
    LABEL_JOB_NAME,
    PodGroupPhase,
)
from kubeflow_tpu.api.jaxjob import KIND_JAXJOB

from .test_controlplane import make_job, wait_for


def _cluster():
    c = Cluster()
    c.add_tpu_slice("s0", num_hosts=4, chips_per_host=4)
    c.enable_platform_ux()
    return c


class TestProfiles:
    def test_quota_blocks_oversized_gang_atomically(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(Profile(
                    metadata=ObjectMeta(name="team-a"),
                    spec=ProfileSpec(owner="a@corp", resource_quota={"tpu": 4})))
                # 2 workers x 4 chips = 8 > quota 4: the WHOLE gang pends
                job = make_job(name="big", replicas=2, tpu=4)
                job.metadata.namespace = "team-a"
                c.store.create(job)
                pg = wait_for(
                    lambda: (
                        g := c.store.try_get(KIND_PODGROUP, "big", "team-a")
                    ) and g.status.message.startswith("profile quota") and g,
                    desc="quota rejection")
                assert pg.status.phase == PodGroupPhase.PENDING
                pods = c.store.list(KIND_POD, "team-a", labels={LABEL_JOB_NAME: "big"})
                assert all(p.spec.node_name is None for p in pods)
                # an in-quota gang from the same profile admits fine
                ok = make_job(name="small", replicas=1, tpu=4)
                ok.metadata.namespace = "team-a"
                c.store.create(ok)
                wait_for(
                    lambda: all(
                        p.spec.node_name
                        for p in c.store.list(
                            KIND_POD, "team-a", labels={LABEL_JOB_NAME: "small"})
                    ) and c.store.list(KIND_POD, "team-a", labels={LABEL_JOB_NAME: "small"}),
                    desc="in-quota gang bound")
                # usage shows up on profile status
                prof = wait_for(
                    lambda: (
                        p := c.store.try_get("Profile", "team-a")
                    ) and p.status.usage.get("tpu") == 4.0 and p,
                    desc="usage accounted")
                assert prof.status.phase == "Ready"
            finally:
                kubelet.stop()

    def test_no_profile_means_no_quota(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="free", replicas=2, tpu=4))
                wait_for(
                    lambda: all(
                        p.spec.node_name
                        for p in c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "free"})
                    ) and c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "free"}),
                    desc="unquota'd gang binds")
            finally:
                kubelet.stop()


class TestPodDefaults:
    def test_env_injected_by_selector(self):
        c = _cluster()
        with c:
            c.store.create(PodDefault(
                metadata=ObjectMeta(name="add-tracking"),
                spec=PodDefaultSpec(
                    selector={LABEL_JOB_NAME: "tagged"},
                    env={"KFT_TRACKING": "on", "KFT_STEPS": "999"},
                    annotations={"team": "a"})))
            job = make_job(name="tagged", replicas=1)
            job.spec.replica_specs["worker"].template.env = {"KFT_STEPS": "3"}
            c.store.create(job)
            pods = wait_for(
                lambda: c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "tagged"}),
                desc="pod created")
            env = pods[0].spec.container.env
            assert env["KFT_TRACKING"] == "on"
            assert env["KFT_STEPS"] == "3"  # pod's own value wins
            assert pods[0].metadata.annotations["team"] == "a"
            # unmatched pods untouched
            c.store.create(make_job(name="plain", replicas=1))
            pods = wait_for(
                lambda: c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "plain"}),
                desc="plain pod")
            assert "KFT_TRACKING" not in pods[0].spec.container.env


class TestNotebooks:
    def _nb(self, name="wb", cull=0.0):
        return Notebook(
            metadata=ObjectMeta(name=name),
            spec=NotebookSpec(
                template=Container(
                    entrypoint="kubeflow_tpu.ux.notebook_server:main",
                    resources=Resources(cpu=1)),
                idle_cull_seconds=cull))

    def test_notebook_runs_with_url(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(self._nb())
                nb = wait_for(
                    lambda: (n := c.store.try_get("Notebook", "wb"))
                    and n.status.phase == "Running" and n,
                    desc="notebook running")
                assert nb.status.url and "wb-notebook-0" in nb.status.url
                assert c.store.try_get(KIND_POD, "wb-notebook-0") is not None
                assert c.store.try_get("Service", "wb-notebook-0") is not None
            finally:
                kubelet.stop()

    def test_idle_culling_then_resume(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(self._nb(name="idle", cull=0.5))
                nb = wait_for(
                    lambda: (n := c.store.try_get("Notebook", "idle"))
                    and n.status.phase == "Stopped" and n,
                    timeout=15, desc="culled")
                assert nb.metadata.annotations[STOPPED_ANNOTATION] == "idle-culled"
                assert c.store.try_get(KIND_POD, "idle-notebook-0") is None
                # resume: drop the annotation -> pod recreated
                def unstamp(o):
                    o.metadata.annotations.pop(STOPPED_ANNOTATION, None)
                    o.spec.idle_cull_seconds = 0.0
                c.store.update_with_retry("Notebook", "idle", "default", unstamp)
                wait_for(
                    lambda: (n := c.store.try_get("Notebook", "idle"))
                    and n.status.phase == "Running",
                    timeout=15, desc="resumed")
            finally:
                kubelet.stop()

    def test_delete_cleans_pod_and_service(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(self._nb(name="gone"))
                wait_for(
                    lambda: (n := c.store.try_get("Notebook", "gone"))
                    and n.status.phase == "Running",
                    desc="running")
                c.store.try_delete("Notebook", "gone")
                wait_for(
                    lambda: c.store.try_get(KIND_POD, "gone-notebook-0") is None
                    and c.store.try_get("Service", "gone-notebook-0") is None,
                    desc="cleaned")
            finally:
                kubelet.stop()


class TestDashboard:
    def test_overview_sections_and_html(self):
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                url = c.serve_dashboard()
                c.store.create(make_job(name="dashjob", replicas=1))
                c.store.create(Profile(
                    metadata=ObjectMeta(name="team-b"),
                    spec=ProfileSpec(owner="b@corp")))
                wait_for(
                    lambda: (j := c.store.try_get(KIND_JAXJOB, "dashjob"))
                    and j.status.conditions, desc="job visible")

                with urllib.request.urlopen(f"{url}/api/overview", timeout=5) as r:
                    ov = json.loads(r.read())
                assert ov["jaxjobs"] == 1 and ov["profiles"] == 1
                with urllib.request.urlopen(f"{url}/api/jaxjobs", timeout=5) as r:
                    jobs = json.loads(r.read())
                assert jobs[0]["name"] == "dashjob" and "status" in jobs[0]
                with urllib.request.urlopen(url, timeout=5) as r:
                    page = r.read().decode()
                assert "kubeflow-tpu dashboard" in page
                assert "default/dashjob" in page and "default/team-b" in page
                with urllib.request.urlopen(f"{url}/api/events", timeout=5) as r:
                    events = json.loads(r.read())
                assert any(e.get("reason") == "PodGroupCreated" for e in events)
            finally:
                kubelet.stop()


class TestDashboardDetail:
    def test_object_detail_and_events(self):
        """Per-object detail route: full dump + its events (the kubectl-
        describe surface the upstream web apps render)."""
        c = _cluster()
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                url = c.serve_dashboard()
                c.store.create(make_job(name="detjob", replicas=1))
                wait_for(
                    lambda: (j := c.store.try_get(KIND_JAXJOB, "detjob"))
                    and j.status.conditions, desc="job visible")
                with urllib.request.urlopen(
                        f"{url}/api/jaxjobs/default/detjob", timeout=5) as r:
                    det = json.loads(r.read())
                assert det["object"]["metadata"]["name"] == "detjob"
                assert det["object"]["status"]["conditions"]
                assert any(e["reason"] for e in det["events"])
                # unknown object -> 404
                try:
                    urllib.request.urlopen(
                        f"{url}/api/jaxjobs/default/nope", timeout=5)
                    raise AssertionError("expected 404")
                except urllib.error.HTTPError as e:
                    assert e.code == 404
            finally:
                kubelet.stop()

    def test_experiment_curves_from_db(self, tmp_path):
        """The Katib-UI main job: per-trial objective curves read from the
        observation DB through the dashboard."""
        from kubeflow_tpu.hpo.db import DbManagerClient, DbManagerServer
        from kubeflow_tpu.ux.dashboard import Dashboard

        c = _cluster()
        server = DbManagerServer(str(tmp_path / "obs.sqlite")).start()
        db = DbManagerClient(server.address)
        with c:
            try:
                # per-step observation log + the final (step=-1) value
                db.report_observation("exp1", "exp1-t1", {"lr": 0.1}, 0.5, step=10)
                db.report_observation("exp1", "exp1-t1", {"lr": 0.1}, 0.8, step=20)
                db.report_observation("exp1", "exp1-t1", {"lr": 0.1}, 0.8)
                db.report_observation("exp1", "exp1-t2", {"lr": 0.01}, 0.3)
                dash = Dashboard(c.store, db=db)
                try:
                    with urllib.request.urlopen(
                            f"{dash.url}/api/experiments/default/exp1/curves",
                            timeout=5) as r:
                        curves = json.loads(r.read())
                    assert set(curves) == {"exp1-t1", "exp1-t2"}
                    t1 = [(pt["step"], pt["value"]) for pt in curves["exp1-t1"]]
                    assert t1 == [(-1, 0.8), (10, 0.5), (20, 0.8)]
                finally:
                    dash.stop()
                # the replay surface still sees ONE final value per trial
                finals = db.get_observations("exp1")
                assert sorted((o["trial"], o["value"]) for o in finals) == [
                    ("exp1-t1", 0.8), ("exp1-t2", 0.3)]
            finally:
                server.stop()
