"""Ring / Ulysses sequence-parallel attention vs the dense reference.

Long-context capability (SURVEY.md §5) validated on the virtual CPU mesh:
same ppermute/all_to_all lowering as the ICI ring on a real slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.llama import _causal_attention
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import ring_attention as ringlib


@pytest.fixture(scope="module")
def qkv():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    b, s, h, kv, d = 2, 64, 8, 4, 16
    return (
        jax.random.normal(k1, (b, s, h, d)),
        jax.random.normal(k2, (b, s, kv, d)),
        jax.random.normal(k3, (b, s, kv, d)),
    )


@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}, {"seq": 2, "model": 2}])
def test_ring_matches_dense(qkv, axes):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh(axes, devices=jax.devices()[: np.prod(list(axes.values()))])
    out = jax.jit(lambda q, k, v: ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("axes", [{"seq": 4}, {"seq": 2, "model": 2}])
def test_ulysses_matches_dense(qkv, axes):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh(axes, devices=jax.devices()[: np.prod(list(axes.values()))])
    out = jax.jit(lambda q, k, v: ringlib.ulysses_attention(q, k, v, q_per_kv=2, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_dense(qkv):
    q, k, v = qkv
    mesh = meshlib.build_mesh({"seq": 8})

    def ring_loss(q, k, v):
        return ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh).sum()

    def dense_loss(q, k, v):
        return _causal_attention(q, k, v, 2).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_ring_falls_back_without_seq_axis(qkv):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh({"data": 8})
    out = ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_ulysses_rejects_indivisible_heads(qkv):
    q, k, v = qkv
    mesh = meshlib.build_mesh({"seq": 8})  # kv=4 not divisible by 8
    with pytest.raises(ValueError, match="divisible"):
        ringlib.ulysses_attention(q, k, v, q_per_kv=2, mesh=mesh)
