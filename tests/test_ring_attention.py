"""Ring / Ulysses sequence-parallel attention vs the dense reference.

Long-context capability (SURVEY.md §5) validated on the virtual CPU mesh:
same ppermute/all_to_all lowering as the ICI ring on a real slice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.llama import _causal_attention
from kubeflow_tpu.parallel import mesh as meshlib
from kubeflow_tpu.parallel import ring_attention as ringlib


@pytest.fixture(scope="module")
def qkv():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    b, s, h, kv, d = 2, 64, 8, 4, 16
    return (
        jax.random.normal(k1, (b, s, h, d)),
        jax.random.normal(k2, (b, s, kv, d)),
        jax.random.normal(k3, (b, s, kv, d)),
    )


@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}, {"seq": 2, "model": 2}])
def test_ring_matches_dense(qkv, axes):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh(axes, devices=jax.devices()[: np.prod(list(axes.values()))])
    out = jax.jit(lambda q, k, v: ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("axes", [{"seq": 4}, {"seq": 2, "model": 2}])
def test_ulysses_matches_dense(qkv, axes):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh(axes, devices=jax.devices()[: np.prod(list(axes.values()))])
    out = jax.jit(lambda q, k, v: ringlib.ulysses_attention(q, k, v, q_per_kv=2, mesh=mesh))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_dense(qkv):
    q, k, v = qkv
    mesh = meshlib.build_mesh({"seq": 8})

    def ring_loss(q, k, v):
        return ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh).sum()

    def dense_loss(q, k, v):
        return _causal_attention(q, k, v, 2).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_ring_falls_back_without_seq_axis(qkv):
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh({"data": 8})
    out = ringlib.ring_attention(q, k, v, q_per_kv=2, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-6)


def test_ulysses_rejects_indivisible_heads(qkv):
    q, k, v = qkv
    mesh = meshlib.build_mesh({"seq": 8})  # kv=4 not divisible by 8
    with pytest.raises(ValueError, match="divisible"):
        ringlib.ulysses_attention(q, k, v, q_per_kv=2, mesh=mesh)


@pytest.mark.parametrize("axes", [{"seq": 4}, {"data": 2, "seq": 2}])
def test_ring_flash_blocks_match_dense(qkv, axes):
    """The Pallas-kernel block path (r1 weak #3 closure): per-block flash
    with logsumexp folding across the ring == dense reference."""
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh(
        axes, devices=jax.devices()[: np.prod(list(axes.values()))])
    out = jax.jit(lambda q, k, v: ringlib.ring_attention(
        q, k, v, q_per_kv=2, mesh=mesh, block_impl="flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)


def test_ring_flash_gradients_match_dense(qkv):
    """Differentiability through the lse combine: the dlse cotangent rides
    the same bwd kernels via the delta rows."""
    q, k, v = qkv
    mesh = meshlib.build_mesh({"seq": 4}, devices=jax.devices()[:4])

    def ring_loss(q, k, v):
        return (ringlib.ring_attention(
            q, k, v, q_per_kv=2, mesh=mesh, block_impl="flash"
        ).astype(jnp.float32) ** 2).sum()

    def dense_loss(q, k, v):
        return (_causal_attention(q, k, v, 2).astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_flash_lse_matches_dense_logsumexp(qkv):
    """flash_attention_lse's lse output is the true per-row logsumexp of
    the scaled (masked) logits, causal and full."""
    from kubeflow_tpu.ops.flash_attention import flash_attention_lse

    q, k, v = qkv
    b, s, h, d = q.shape
    kvh = k.shape[2]
    qh = q.reshape(b, s, kvh, 2, d)
    logits = jnp.einsum("bqkgd,bskd->bkgqs", qh, k) / jnp.sqrt(d)
    logits = logits.reshape(b, kvh * 2, s, s)
    for causal in (True, False):
        masked = (
            jnp.where(jnp.tril(jnp.ones((s, s), bool))[None, None], logits, -1e30)
            if causal else logits)
        want = jax.nn.logsumexp(masked, axis=-1)  # [b, h, s]
        _, lse = flash_attention_lse(q, k, v, q_per_kv=2, causal=causal)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_flash_core_matches_dense(qkv):
    """The TPU ulysses path (flash kernel after the all-to-all), forced on
    the CPU stand-in via interpret mode."""
    q, k, v = qkv
    ref = np.asarray(_causal_attention(q, k, v, 2))
    mesh = meshlib.build_mesh({"seq": 4}, devices=jax.devices()[:4])
    out = jax.jit(lambda q, k, v: ringlib.ulysses_attention(
        q, k, v, q_per_kv=2, mesh=mesh, block_impl="flash"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)
