"""Refcounted shared-prefix segments (r4 verdict missing #6 — the vLLM
paged-KV capacity economy, SURVEY §2.2).

N concurrent requests sharing a long prefix hold ONE immutable segment
plus N short suffix slots, instead of N full-length slots: the engine's
slot pool can be sized for suffixes only, which is what changes
capacity (slots per GiB), not just latency.  Attention stays exact —
one softmax over [segment ; private] (llama._decode_attend).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine, cache_shapes


def _setup():
    base = llamalib.tiny()  # max_seq_len 128
    params = nn.meta.unbox(llamalib.Llama(base).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    rng = np.random.default_rng(0)
    system = rng.integers(1, 256, size=48).tolist()
    prompts = [system + rng.integers(1, 256, size=5).tolist()
               for _ in range(4)]
    return base, params, system, prompts


def _reference(base, params, prompts, n=5):
    eng = ContinuousEngine(base, params, num_slots=len(prompts),
                           decode_chunk=2, eos_id=None, prefix_cache=False)
    try:
        return [eng.generate(p, max_new_tokens=n) for p in prompts]
    finally:
        eng.stop()


def _segment_engine(base, params, **kw):
    suffix_cfg = dataclasses.replace(base, max_seq_len=32)
    defaults = dict(num_slots=4, decode_chunk=2, eos_id=None,
                    prefix_cache=False, prefix_segments=2, segment_len=64,
                    min_prefix=16)
    defaults.update(kw)
    return ContinuousEngine(suffix_cfg, params, **defaults)


class TestSharedSegments:
    def test_concurrent_same_prefix_burst_parity(self):
        """4 requests with a common 48-token prefix decode CONCURRENTLY
        in 32-token suffix slots, token-identical to full-length slots —
        one segment, three hits, no evictions."""
        base, params, _, prompts = _setup()
        want = _reference(base, params, prompts)
        eng = _segment_engine(base, params)
        try:
            reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            got = [r.wait(300) for r in reqs]
            st = eng.stats()
        finally:
            eng.stop()
        assert got == want
        assert st["segments_live"] == 1
        assert st["segment_hits"] == 3
        assert st["segment_tokens_shared"] == 3 * 48
        assert st["segment_evictions"] == 0

    def test_divergence_isolated(self):
        """Requests diverging after the shared prefix must not see each
        other's suffixes: distinct continuations for distinct suffixes,
        identical for identical prompts (the copy-on-write concern
        dissolves because segments are immutable)."""
        base, params, system, _ = _setup()
        a = system + [7, 7, 7]
        b = system + [9, 9, 9]
        want = _reference(base, params, [a, b, a])
        eng = _segment_engine(base, params)
        try:
            reqs = [eng.submit(p, max_new_tokens=5) for p in (a, b, a)]
            got = [r.wait(300) for r in reqs]
        finally:
            eng.stop()
        assert got == want
        assert got[0] == got[2]
        assert got[0] != got[1]

    def test_capacity_bytes_per_request(self):
        """The capacity claim in bytes, on the actual pool trees: suffix
        slots + amortized segment << full-length slots, per request."""
        base, params, _, _ = _setup()
        suffix_cfg = dataclasses.replace(base, max_seq_len=32)
        seg_cfg = dataclasses.replace(base, max_seq_len=64)

        def nbytes(cfg, rows):
            return sum(
                int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
                for s in jax.tree.leaves(cache_shapes(cfg, rows)))

        n = 8  # concurrent same-prefix requests
        legacy = nbytes(base, n)
        shared = nbytes(suffix_cfg, n) + nbytes(seg_cfg, 1)
        # 8 x 128-token slots vs 8 x 32 + one 64-token segment
        assert shared < 0.45 * legacy, (shared, legacy)

    def test_eviction_respects_refcounts(self):
        """A referenced segment is never evicted; refcount-0 LRU is."""
        base, params, system, _ = _setup()
        rng = np.random.default_rng(7)
        other1 = rng.integers(1, 256, size=40).tolist()
        other2 = rng.integers(1, 256, size=40).tolist()
        eng = _segment_engine(base, params)
        try:
            # hold a LIVE reference on the system-prompt segment
            live = eng.submit(system + [3], max_new_tokens=20)
            deadline = 60
            import time as _t

            t0 = _t.monotonic()
            while eng.stats()["segments_live"] < 1:
                assert _t.monotonic() - t0 < deadline
                _t.sleep(0.05)
            # two disjoint-prefix requests: the second must evict the
            # FIRST's (refcount-0) segment, never the referenced one
            eng.generate(other1 + [5], max_new_tokens=2)
            eng.generate(other2 + [5], max_new_tokens=2)
            st = eng.stats()
            assert st["segment_evictions"] >= 1
            # the system segment survived: a new same-prefix request hits
            hits_before = st["segment_hits"]
            eng.generate(system + [9], max_new_tokens=2)
            assert eng.stats()["segment_hits"] > hits_before
            live.wait(300)
        finally:
            eng.stop()

    def test_falls_back_when_suffix_overflows_slot(self):
        """A prompt whose post-prefix suffix exceeds the slot bucket must
        still complete (legacy truncation path), not error."""
        base, params, system, _ = _setup()
        eng = _segment_engine(base, params)
        rng = np.random.default_rng(3)
        # post-SEGMENT suffix must exceed the 32-token slot: segment
        # captures at most segment_len=64 tokens, so 48 system + 100
        # extra leaves a 84-token suffix > seq_buckets[-1]=32
        long_suffix = rng.integers(1, 256, size=100).tolist()
        try:
            out = eng.generate(system + long_suffix, max_new_tokens=3)
            st = eng.stats()
        finally:
            eng.stop()
        assert len(out) == 3
        # proof the fallback (not the segment path) served it: no slot
        # was occupied through a segment reference
        assert st["segment_hits"] == 0

    def test_build_engine_knobs(self):
        from kubeflow_tpu.serving.continuous import build_engine

        base, params, _, prompts = _setup()
        suffix_cfg = dataclasses.replace(base, max_seq_len=32)
        eng = build_engine(suffix_cfg, params, {
            "num_slots": 2, "decode_chunk": 2, "warmup_groups": [],
            "prefix_cache": False, "prefix_segments": 2,
            "segment_len": 64, "min_prefix": 16})
        try:
            out = eng.generate(prompts[0], max_new_tokens=3)
            assert len(out) == 3
            assert eng.stats()["segments_live"] == 1
        finally:
            eng.stop()
