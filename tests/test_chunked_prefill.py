"""Stall-free chunked prefill (ISSUE 2, serving/continuous.py).

Sarathi-style admission: a prompt prefills ``prefill_budget`` tokens per
dispatch, fused into the pool decode program, instead of one monolithic
[1, bucket] dispatch that freezes token emission for every live request.
These tests pin the contract: greedy tokens BIT-IDENTICAL to whole-prompt
admission (plain, prefix-cache, segment and tiered variants), the chunk
count bounded by the budget, cancellation mid-prefill freeing the slot
with the partial KV reusable, and the scheduler observability gauges.
"""

import math
import time

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine, TieredEngine


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64-token prompt: 8 chunks at budget 8


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    return ContinuousEngine(cfg, params, **kw)


@pytest.fixture(scope="module")
def whole_prompt_tokens(tiny_llama):
    """Greedy oracle: the legacy whole-prompt admission path."""
    eng = make_engine(tiny_llama)
    try:
        return {
            "long": eng.generate(LONG, max_new_tokens=6),
            "short": eng.generate([7, 8, 9], max_new_tokens=6),
            "victim": eng.generate([7, 8, 9], max_new_tokens=40),
        }
    finally:
        eng.stop()


class TestChunkedParity:
    def test_idle_pool_admission_parity(self, tiny_llama,
                                        whole_prompt_tokens):
        """Chunked admission into an idle pool (the standalone chunk
        program) produces bit-identical greedy tokens, and the chunk
        count is exactly ceil(len / budget)."""
        eng = make_engine(tiny_llama, prefill_budget=8)
        try:
            got = eng.generate(LONG, max_new_tokens=6)
            assert got == whole_prompt_tokens["long"]
            assert eng.prefill_chunks_dispatched == math.ceil(len(LONG) / 8)
            got_short = eng.generate([7, 8, 9], max_new_tokens=6)
            assert got_short == whole_prompt_tokens["short"]
        finally:
            eng.stop()

    def test_admission_under_live_decode_parity(self, tiny_llama,
                                                whole_prompt_tokens):
        """The fused path: a long prompt admits WHILE another request
        decodes — both come out bit-identical to their solo runs (the
        victim's decode stream rides the same dispatches as the chunks)."""
        eng = make_engine(tiny_llama, prefill_budget=8, decode_chunk=1)
        try:
            victim = eng.submit([7, 8, 9], max_new_tokens=40)
            while eng.step_counter < 5:
                time.sleep(0.005)
            late = eng.submit(LONG, max_new_tokens=6)
            assert late.wait(300) == whole_prompt_tokens["long"]
            assert victim.wait(300) == whole_prompt_tokens["victim"]
            # the admission actually went through the chunked machinery
            assert eng.prefill_chunks_dispatched >= math.ceil(len(LONG) / 8)
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_prefix_cache_composes(self, tiny_llama, whole_prompt_tokens):
        """Chunked admission coexists with the prefix cache: the first
        submit chunk-prefills, the repeat admits via the on-device prefix
        copy — both bit-identical to the oracle."""
        eng = make_engine(tiny_llama, prefill_budget=8, prefix_cache=True,
                          min_prefix=8)
        try:
            a = eng.generate(LONG, max_new_tokens=6)
            b = eng.generate(LONG, max_new_tokens=6)
            assert eng.prefix_hits == 1  # repeat took the prefix route
            assert a == whole_prompt_tokens["long"]
            assert b == whole_prompt_tokens["long"]
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_tiered_pools_compose(self, tiny_llama, whole_prompt_tokens):
        """prefill_budget flows into every tier's pool; routing and
        tokens match the untiered oracle."""
        cfg, params = tiny_llama
        eng = TieredEngine(cfg, params, short_len=32, num_slots=4,
                           decode_chunk=2, prefix_cache=False,
                           prefill_budget=8)
        try:
            assert all(p.prefill_budget == 8 for p in eng.pools)
            # per-pool constant, not summed across pools in merged stats
            assert eng.stats()["prefill_budget"] == 8
            got_short = eng.generate([7, 8, 9], max_new_tokens=6)
            got_long = eng.generate(LONG, max_new_tokens=6)
            assert got_short == whole_prompt_tokens["short"]
            assert got_long == whole_prompt_tokens["long"]
        finally:
            eng.stop()

    @pytest.mark.slow
    def test_segments_compose(self, tiny_llama):
        """A chunked admission proceeds while segment-backed slots decode
        (the standalone-chunk + prefix-decode dispatch pair): both the
        segment burst and the chunked prompt match their legacy tokens."""
        import dataclasses as _dc

        cfg, params = tiny_llama
        scfg = _dc.replace(cfg, max_seq_len=64)
        system = list(range(1, 25))
        seg_prompts = [system + [40 + i] for i in range(2)]
        plain = list(range(60, 100))  # no shared prefix with system

        def build(budget):
            # ONE segment row: the seg burst occupies (and references)
            # it, so the non-matching prompt cannot create its own and
            # must take the legacy/chunked admission route while the
            # segment-backed slots decode
            return ContinuousEngine(
                scfg, params, num_slots=3, decode_chunk=2,
                prefix_cache=False, prefix_segments=1, segment_len=128,
                min_prefix=8, prefill_budget=budget)

        ref = build(0)
        try:
            want_seg = [ref.generate(p, max_new_tokens=4)
                        for p in seg_prompts]
            want_plain = ref.generate(plain, max_new_tokens=4)
        finally:
            ref.stop()
        eng = build(8)
        try:
            reqs = [eng.submit(p, max_new_tokens=24) for p in seg_prompts]
            while not eng._active.any():
                time.sleep(0.002)
            late = eng.submit(plain, max_new_tokens=4)
            got_plain = late.wait(300)
            got_seg = [r.wait(300)[:4] for r in reqs]
            assert eng.segment_hits >= 1
            assert got_seg == want_seg
            assert got_plain == want_plain
            assert eng.prefill_chunks_dispatched >= math.ceil(len(plain) / 8)
        finally:
            eng.stop()


class TestLivenessDuringAdmission:
    @pytest.mark.slow
    def test_finished_request_resolves_while_admission_continues(
            self, tiny_llama):
        """A request whose last decode chunk is already in flight must
        resolve promptly even when the pool then holds ONLY prefill work
        — prefill-only iterations drain the pending fetches (the review
        caught the original code parking them until the whole admission
        finished)."""
        eng = make_engine(tiny_llama, decode_chunk=1, prefill_budget=4,
                          pipeline_depth=3)
        eng.warmup([(1, 64)])  # measure scheduling, not first-compile
        inner_c, inner_f = eng._chunk_prefill_for, eng._fused_for

        def slow(getter):
            def for_(needed):
                prog = getter(needed)

                def call(*args):
                    time.sleep(0.05)
                    return prog(*args)

                return call

            return for_

        eng._chunk_prefill_for = slow(inner_c)
        eng._fused_for = slow(inner_f)
        try:
            short = eng.submit([1, 2, 3], max_new_tokens=2)
            while eng.step_counter < 1:
                time.sleep(0.002)
            late = eng.submit(LONG, max_new_tokens=2)  # 16 slow chunks
            t0 = time.perf_counter()
            short.wait(10)
            waited = time.perf_counter() - t0
            # the admission runs >= 0.7s; the short request must not
            # have been held hostage to it
            assert waited < 0.5, waited
            late.wait(30)
        finally:
            eng.stop()


class TestCancellationMidPrefill:
    @pytest.mark.slow
    def test_cancel_frees_slot_and_partial_kv_reusable(self, tiny_llama,
                                                       whole_prompt_tokens):
        """Cancelling a request mid-chunked-prefill frees its slot at the
        next boundary, and the KV already written stays recorded in the
        slot content — the prefix matcher reuses the partial prefill."""
        eng = make_engine(tiny_llama, num_slots=2, decode_chunk=1,
                          prefix_cache=True, min_prefix=8,
                          prefill_budget=16)
        # slow each chunk down so the cancel deterministically lands
        # mid-prefill (4 chunks for the 64-token prompt; cancelling at
        # >= 3 leaves a partial whose remaining suffix fits the budget,
        # so the resubmit takes the prefix route)
        inner_c, inner_f = eng._chunk_prefill_for, eng._fused_for

        def slow(getter):
            def for_(needed):
                prog = getter(needed)

                def call(*args):
                    time.sleep(0.02)
                    return prog(*args)

                return call

            return for_

        eng._chunk_prefill_for = slow(inner_c)
        eng._fused_for = slow(inner_f)
        try:
            req = eng.submit(LONG, max_new_tokens=6)
            while eng.prefill_chunks_dispatched < 3:
                time.sleep(0.002)
            req.cancel()
            assert req.wait(5) == []  # resolves immediately, no tokens
            deadline = time.time() + 10
            while time.time() < deadline and any(
                    r is not None for r in eng._slots):
                time.sleep(0.01)
            assert all(r is None for r in eng._slots)  # slot freed
            assert eng.stats()["prefill_tokens_inflight"] == 0
            # the partial KV (>= 3 chunks * 4 tokens >= min_prefix) is
            # ground truth for the prefix matcher: resubmitting reuses it
            partial = max(len(c) for c in eng._slot_content)
            assert partial >= 8
            got = eng.generate(LONG, max_new_tokens=6)
            assert eng.prefix_hits >= 1
            assert got == whole_prompt_tokens["long"]
        finally:
            eng.stop()


class TestSchedulerObservability:
    def test_stats_gauges(self, tiny_llama):
        eng = make_engine(tiny_llama, prefill_budget=8)
        try:
            eng.generate(LONG, max_new_tokens=4)
            st = eng.stats()
            assert st["prefill_budget"] == 8
            assert st["prefill_chunks_dispatched"] == math.ceil(len(LONG) / 8)
            assert st["prefill_tokens_inflight"] == 0
            assert isinstance(st["decode_stall_ms_total"], float)
        finally:
            eng.stop()

    def test_chunk_dispatch_failure_fails_only_that_request(
            self, tiny_llama):
        """A chunk dispatch failure resolves THAT request with the error
        (the legacy path's fail-this-group-only contract) — the engine
        keeps serving everyone else."""
        eng = make_engine(tiny_llama, prefill_budget=8)
        inner = eng._chunk_prefill_for
        boom = {"armed": True}

        def for_(needed):
            prog = inner(needed)

            def call(*args):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("induced chunk failure")
                return prog(*args)

            return call

        eng._chunk_prefill_for = for_
        try:
            bad = eng.submit(LONG, max_new_tokens=4)
            with pytest.raises(RuntimeError, match="induced"):
                bad.wait(30)
            out = eng.generate([7, 8, 9], max_new_tokens=4)
            assert len(out) == 4  # engine alive, slot reclaimed
        finally:
            eng.stop()

    def test_legacy_stall_accounted(self, tiny_llama):
        """The legacy whole-prompt path books its admission-dispatch time
        against decode_stall_ms_total when decode work is live."""
        eng = make_engine(tiny_llama, decode_chunk=1)
        try:
            victim = eng.submit([7, 8, 9], max_new_tokens=40)
            while eng.step_counter < 3:
                time.sleep(0.005)
            eng.generate(LONG, max_new_tokens=2)
            victim.wait(300)
            assert eng.stats()["decode_stall_ms_total"] > 0.0
        finally:
            eng.stop()
