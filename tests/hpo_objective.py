"""Trial entrypoint for HPO e2e: a cheap analytic objective.

score = 1 - (lr - 0.03)^2 * 100, maximized at lr=0.03 — no model, so a
trial costs only process startup.  Emitted via the same metric channel real
trainers use (bootstrap.emit_metric -> status jsonl + stdout name=value).
"""

import os

from kubeflow_tpu.runtime import bootstrap


def objective_main(ctx) -> None:
    lr = float(os.environ.get("KFT_LR", "0.1"))
    score = 1.0 - (lr - 0.03) ** 2 * 100.0
    bootstrap.emit_metric(ctx, "score", score)
