"""Spec-layer tests: construction, defaulting, validation, YAML round-trip.

Mirrors the reference's table-driven API tests [upstream:
kubeflow/training-operator -> pkg/apis/kubeflow.org/v1/*_test.go] done as
pytest parametrization over pure functions (SURVEY.md §4a).
"""

import pytest

from kubeflow_tpu.api import (
    AdmissionError,
    Container,
    Experiment,
    InferenceService,
    JaxJob,
    JobCondition,
    JobConditionType,
    ModelFormat,
    ObjectMeta,
    ReplicaSpec,
    Resources,
    ServingRuntime,
    TpuTopology,
    default_jaxjob,
    dump_yaml,
    from_dict,
    get_condition,
    has_condition,
    is_retryable_exit,
    load_yaml,
    replica_pod_name,
    select_runtime,
    set_condition,
    substitute_parameters,
    validate_experiment,
    validate_jaxjob,
)
from kubeflow_tpu.api.inference import ServingRuntimeSpec, SupportedModelFormat


def make_job(replicas=2, tpu=0, mesh=None):
    job = JaxJob(
        metadata=ObjectMeta(name="llama-ft"),
        spec={
            "replica_specs": {
                "worker": ReplicaSpec(
                    replicas=replicas,
                    template=Container(resources=Resources(tpu=tpu)),
                )
            },
            **({"mesh": mesh} if mesh else {}),
        },
    )
    return default_jaxjob(job)


class TestJaxJob:
    def test_defaulting_sets_gang_min_available(self):
        job = make_job(replicas=4)
        assert job.spec.run_policy.scheduling_policy.min_available == 4
        assert job.spec.mesh == {"data": 4}

    def test_defaulting_counts_chips(self):
        job = make_job(replicas=4, tpu=4)
        assert job.spec.mesh == {"data": 16}

    def test_validate_ok(self):
        validate_jaxjob(make_job(replicas=2))

    def test_validate_rejects_zero_workers(self):
        job = make_job(replicas=2)
        job.spec.replica_specs["worker"].replicas = 0
        with pytest.raises(AdmissionError):
            validate_jaxjob(job)

    def test_validate_rejects_mesh_mismatch(self):
        job = make_job(replicas=2, tpu=4, mesh={"data": 2, "model": 2})
        with pytest.raises(AdmissionError, match="mesh"):
            validate_jaxjob(job)

    def test_validate_accepts_factored_mesh(self):
        validate_jaxjob(make_job(replicas=2, tpu=4, mesh={"data": 2, "model": 4}))

    def test_dns_names(self):
        assert replica_pod_name("j", "Worker", 3) == "j-worker-3"

    def test_bad_name_rejected(self):
        with pytest.raises(ValueError):
            ObjectMeta(name="Bad_Name")

    def test_topology(self):
        t = TpuTopology(shape="4x4")
        assert t.num_chips == 16
        with pytest.raises(ValueError):
            TpuTopology(shape="4by4")


class TestConditions:
    def test_terminal_flips_running_off(self):
        conds = []
        conds = set_condition(conds, JobCondition(type=JobConditionType.CREATED))
        conds = set_condition(conds, JobCondition(type=JobConditionType.RUNNING))
        conds = set_condition(
            conds, JobCondition(type=JobConditionType.SUCCEEDED, reason="done")
        )
        assert has_condition(conds, JobConditionType.SUCCEEDED)
        running = get_condition(conds, JobConditionType.RUNNING)
        assert running is not None and running.status is False

    def test_no_transition_keeps_timestamp(self):
        c1 = JobCondition(type=JobConditionType.RUNNING, reason="r")
        conds = set_condition([], c1)
        conds = set_condition(conds, JobCondition(type=JobConditionType.RUNNING, reason="r"))
        assert conds[0].last_transition_time == c1.last_transition_time

    def test_retryable_exit_codes(self):
        assert is_retryable_exit(137)  # SIGKILL
        assert is_retryable_exit(42)
        assert not is_retryable_exit(1)


class TestYaml:
    MANIFEST = """
apiVersion: kubeflow-tpu.dev/v1
kind: JaxJob
metadata:
  name: mnist-smoke
spec:
  runPolicy:
    backoffLimit: 2
  replicaSpecs:
    worker:
      replicas: 2
      template:
        entrypoint: kubeflow_tpu.models.mnist:train_main
        resources:
          tpu: 0
"""

    def test_load_camelcase_manifest(self):
        (job,) = load_yaml(self.MANIFEST)
        assert isinstance(job, JaxJob)
        assert job.spec.run_policy.backoff_limit == 2
        assert job.spec.replica_specs["worker"].replicas == 2

    def test_round_trip(self):
        (job,) = load_yaml(self.MANIFEST)
        default_jaxjob(job)
        (job2,) = load_yaml(dump_yaml(job))
        assert job2.spec == job.spec

    def test_unknown_kind(self):
        with pytest.raises(ValueError, match="unknown kind"):
            from_dict({"kind": "PyTorchJob", "metadata": {"name": "x"}})

    def test_user_data_maps_not_mangled(self):
        """env var names / labels / mesh axes must survive camelCase->snake
        conversion untouched (they are data, not schema keys)."""
        manifest = """
kind: JaxJob
metadata:
  name: envy
  labels:
    myTeam: alpha
spec:
  replicaSpecs:
    worker:
      replicas: 2
      template:
        env:
          MY_FLAG: "1"
          someCamelVar: "x"
  mesh:
    data: 2
"""
        (job,) = load_yaml(manifest)
        env = job.spec.replica_specs["worker"].template.env
        assert env == {"MY_FLAG": "1", "someCamelVar": "x"}
        assert job.metadata.labels == {"myTeam": "alpha"}
        assert job.spec.mesh == {"data": 2}


class TestExperiment:
    def test_substitution_typed_and_embedded(self):
        tree = {
            "lr": "${trialParameters.lr}",
            "args": ["--lr=${trialParameters.lr}", "plain"],
        }
        out = substitute_parameters(tree, {"lr": 0.01})
        assert out["lr"] == 0.01
        assert out["args"][0] == "--lr=0.01"

    def test_unresolved_raises(self):
        with pytest.raises(KeyError):
            substitute_parameters("${trialParameters.missing}", {})

    def test_validate_requires_template(self):
        exp = Experiment(
            metadata=ObjectMeta(name="sweep"),
            spec={
                "parameters": [
                    {
                        "name": "lr",
                        "parameter_type": "double",
                        "feasible_space": {"min": 1e-4, "max": 1e-1},
                    }
                ]
            },
        )
        with pytest.raises(AdmissionError, match="trial_template"):
            validate_experiment(exp)


class TestServingSelection:
    def _rt(self, name, fmt, priority=1, auto=True):
        return ServingRuntime(
            metadata=ObjectMeta(name=name),
            spec=ServingRuntimeSpec(
                supported_model_formats=[
                    SupportedModelFormat(name=fmt, priority=priority, auto_select=auto)
                ],
                server_class="x:Y",
            ),
        )

    def test_priority_selection(self):
        rts = [self._rt("a", "jax", 1), self._rt("b", "jax", 9)]
        assert select_runtime(ModelFormat(name="jax"), rts).metadata.name == "b"

    def test_no_autoselect(self):
        rts = [self._rt("a", "jax", auto=False)]
        assert select_runtime(ModelFormat(name="jax"), rts) is None
