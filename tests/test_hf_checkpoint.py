"""Stock published-Llama checkpoint interop (models/hf_checkpoint.py).

SURVEY.md §3.5 / §2.2 storage row — the fine-tune/serve UX must accept a
GENUINE transformers-layout snapshot (safetensors with per-layer
``q_proj/k_proj/...`` tensors), not just this repo's own published
format.  The WRITER here is test-local (building a synthetic HF-layout
snapshot from known params); the reader under test lives in the repo.
Parity bar: logits from converted params must match logits from the
directly-constructed params bit-for-bit (both f32 on CPU).
"""

import json
import os
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import hf_checkpoint as hflib
from kubeflow_tpu.models import llama as llamalib


# -- test-local safetensors writer + reverse layout map ---------------------


def write_safetensors(path: str, tensors: dict[str, np.ndarray],
                      dtype_tag: str = "F32") -> None:
    header = {}
    blobs = []
    offset = 0
    for name, arr in tensors.items():
        if dtype_tag == "BF16":
            f32 = np.ascontiguousarray(arr, dtype=np.float32)
            raw = ((f32.view(np.uint32) >> 16).astype("<u2")).tobytes()
        else:
            raw = np.ascontiguousarray(arr, dtype="<f4").tobytes()
        header[name] = {
            "dtype": dtype_tag,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(raw)],
        }
        blobs.append(raw)
        offset += len(raw)
    hdr = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hdr)))
        f.write(hdr)
        for b in blobs:
            f.write(b)


def hf_tensors_from_params(cfg, params) -> dict[str, np.ndarray]:
    """Reverse of the repo's converter: repo tree -> HF names/layouts."""
    E, M = cfg.hidden_size, cfg.intermediate_size
    H, KV, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    block = params["layers"]["block"]
    out = {"model.embed_tokens.weight": np.asarray(
        params["embedder"]["embedding"])}
    for layer in range(cfg.num_layers):
        p = f"model.layers.{layer}."
        a = block["attn"]
        out[p + "self_attn.q_proj.weight"] = (
            np.asarray(a["wq"]["kernel"][layer]).reshape(E, H * D).T)
        out[p + "self_attn.k_proj.weight"] = (
            np.asarray(a["wk"]["kernel"][layer]).reshape(E, KV * D).T)
        out[p + "self_attn.v_proj.weight"] = (
            np.asarray(a["wv"]["kernel"][layer]).reshape(E, KV * D).T)
        out[p + "self_attn.o_proj.weight"] = (
            np.asarray(a["wo"]["kernel"][layer]).reshape(H * D, E).T)
        m = block["mlp"]
        out[p + "mlp.gate_proj.weight"] = np.asarray(
            m["w_gate"]["kernel"][layer]).T
        out[p + "mlp.up_proj.weight"] = np.asarray(
            m["w_up"]["kernel"][layer]).T
        out[p + "mlp.down_proj.weight"] = np.asarray(
            m["w_down"]["kernel"][layer]).T
        out[p + "input_layernorm.weight"] = np.asarray(
            block["attn_norm"]["scale"][layer])
        out[p + "post_attention_layernorm.weight"] = np.asarray(
            block["mlp_norm"]["scale"][layer])
    out["model.norm.weight"] = np.asarray(
        params["head"]["final_norm"]["scale"])
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["head"]["unembedding"]).T
    return out


def hf_config_dict(cfg) -> dict:
    return {
        "model_type": "llama",
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
    }


def make_hf_snapshot(tmp_path, cfg, params, shards: int = 1,
                     dtype_tag: str = "F32") -> str:
    path = tmp_path / "hf_snap"
    path.mkdir(exist_ok=True)
    with open(path / "config.json", "w") as f:
        json.dump(hf_config_dict(cfg), f)
    tensors = hf_tensors_from_params(cfg, params)
    if shards == 1:
        write_safetensors(str(path / "model.safetensors"), tensors,
                          dtype_tag)
    else:
        names = sorted(tensors)
        weight_map = {}
        for i in range(shards):
            part = {n: tensors[n] for n in names[i::shards]}
            fname = f"model-{i + 1:05d}-of-{shards:05d}.safetensors"
            write_safetensors(str(path / fname), part, dtype_tag)
            weight_map.update({n: fname for n in part})
        with open(path / "model.safetensors.index.json", "w") as f:
            json.dump({"weight_map": weight_map}, f)
    return str(path)


def _tiny_with_params(**kw):
    cfg = llamalib.tiny(**kw)
    params = llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    from flax import linen as nn

    return cfg, nn.meta.unbox(params)


TOKENS = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)


class TestSafetensorsReader:
    def test_roundtrip_f32(self, tmp_path):
        arrs = {"a": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": np.float32([[1.5]])}
        write_safetensors(str(tmp_path / "x.safetensors"), arrs)
        got = hflib.read_safetensors(str(tmp_path / "x.safetensors"))
        assert set(got) == {"a", "b"}
        assert np.array_equal(got["a"], arrs["a"])

    def test_bf16_upcast(self, tmp_path):
        arr = {"w": np.linspace(-3, 3, 16, dtype=np.float32).reshape(4, 4)}
        write_safetensors(str(tmp_path / "b.safetensors"), arr, "BF16")
        got = hflib.read_safetensors(str(tmp_path / "b.safetensors"))["w"]
        assert got.dtype == np.float32
        # bf16 keeps ~3 decimal digits
        assert np.allclose(got, arr["w"], atol=0.05)

    def test_bad_offsets_rejected(self, tmp_path):
        hdr = json.dumps({"x": {"dtype": "F32", "shape": [4],
                                "data_offsets": [0, 999]}}).encode()
        p = tmp_path / "bad.safetensors"
        with open(p, "wb") as f:
            f.write(struct.pack("<Q", len(hdr)) + hdr + b"\x00" * 16)
        with pytest.raises(ValueError, match="offsets"):
            hflib.read_safetensors(str(p))


class TestHfLlamaConversion:
    def test_logits_parity_exact(self, tmp_path):
        cfg, params = _tiny_with_params()
        snap = make_hf_snapshot(tmp_path, cfg, params)
        cfg2, params2 = llamalib.load_pretrained(snap)  # auto-detect
        assert cfg2.num_kv_heads == cfg.num_kv_heads
        assert cfg2.head_dim == cfg.head_dim
        # evaluate both under the SAME cfg: the converter keeps the
        # repo's TPU dtype defaults (bf16 activations), which is a knob,
        # not an architecture difference
        model = llamalib.Llama(cfg)
        want = model.apply({"params": params}, TOKENS)
        got = model.apply({"params": params2}, TOKENS)
        assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_sharded_snapshot_with_index(self, tmp_path):
        cfg, params = _tiny_with_params(num_layers=3)
        snap = make_hf_snapshot(tmp_path, cfg, params, shards=3)
        cfg2, params2 = llamalib.load_pretrained(snap)
        assert cfg2.num_layers == 3
        want = llamalib.Llama(cfg).apply({"params": params}, TOKENS)
        got = llamalib.Llama(cfg).apply({"params": params2}, TOKENS)
        assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_tied_embeddings(self, tmp_path):
        cfg, params = _tiny_with_params(tie_embeddings=True)
        snap = make_hf_snapshot(tmp_path, cfg, params)
        cfg2, params2 = llamalib.load_pretrained(snap)
        assert cfg2.tie_embeddings
        want = llamalib.Llama(cfg).apply({"params": params}, TOKENS)
        got = llamalib.Llama(cfg).apply({"params": params2}, TOKENS)
        assert np.array_equal(np.asarray(want), np.asarray(got))

    def test_missing_tensor_named_in_error(self, tmp_path):
        cfg, params = _tiny_with_params()
        tensors = hf_tensors_from_params(cfg, params)
        tensors.pop("model.layers.1.mlp.up_proj.weight")
        path = tmp_path / "broken"
        path.mkdir()
        with open(path / "config.json", "w") as f:
            json.dump(hf_config_dict(cfg), f)
        write_safetensors(str(path / "model.safetensors"), tensors)
        with pytest.raises(KeyError, match="up_proj"):
            llamalib.load_pretrained(str(path))

    def test_own_format_still_detected(self, tmp_path):
        """save_pretrained snapshots must keep loading via msgpack —
        the detector must not misfire on the dataclass config.json."""
        cfg, params = _tiny_with_params()
        path = str(tmp_path / "own")
        llamalib.save_pretrained(path, cfg, params)
        assert not hflib.is_hf_snapshot(path)
        cfg2, _ = llamalib.load_pretrained(path)
        assert cfg2 == cfg


class TestHfServingAndFinetune:
    def test_generator_serves_hf_snapshot(self, tmp_path):
        from kubeflow_tpu.serving.runtimes import LlamaGenerator
        from kubeflow_tpu.serving.storage import register_mem

        cfg, params = _tiny_with_params()
        snap = make_hf_snapshot(tmp_path, cfg, params)
        ref = register_mem("hfparity", (cfg, params))
        direct = LlamaGenerator("d", {"params_ref": ref, "max_new_tokens": 4})
        direct.start()
        want = direct.predict_batch([[1, 2, 3]])
        hf = LlamaGenerator(
            "h", {"storage_path": snap, "max_new_tokens": 4})
        hf.start()
        assert hf.predict_batch([[1, 2, 3]]) == want

    def test_trainer_finetunes_from_hf_snapshot(self, tmp_path):
        """KFT_INIT_FROM-equivalent: Trainer(init_from=<hf dir>) starts
        from the converted weights (loss continuity beats scratch)."""
        from kubeflow_tpu.train import trainer as trainlib

        cfg, params = _tiny_with_params()
        snap = make_hf_snapshot(tmp_path, cfg, params)
        t = trainlib.Trainer(trainlib.TrainConfig(
            model=cfg, steps=1, global_batch=8, seq_len=16, init_from=snap))
        state = t.init_state()
        got = state["params"]["embedder"]["embedding"]
        assert np.array_equal(
            np.asarray(got), np.asarray(params["embedder"]["embedding"]))
