"""Elastic serving gangs: TP-degree resize of a live gang (ISSUE 10).

Layers, matching the tentpole:

- the PLAN: ``parallel.sharding.reshard_plan`` — JSON-able per-leaf
  repartition specs derived from the one logical-rules table, with
  illegal degrees rejected at plan time;
- the PRIMITIVE: ``GangResizer`` — copy-then-cutover shrink AND grow of
  a live paged engine, greedy tokens BIT-IDENTICAL to the un-resized
  oracle across plain/chunked/spec/int8-KV variants, with
  ``jit_recompiles_total == 0`` after the new degree's warmup, zero
  leaked blocks on both allocators, and waiting requests following the
  pool;
- SAFETY: the seeded ``kill_mid_resize`` chaos sweep — a resize dying
  mid-export / mid-reshard / mid-commit leaves the old-degree engine
  serving with exactly-once tokens and zero leaked blocks;
- the GANG: leader + follower over a loopback channel — a permanent
  member loss shrinks to the surviving degree (``resize`` op + rs_*
  reshard wire), a fresh member grows it back, follower pool state
  bit-identical;
- the CONTROLLER: ``elastic`` knobs validate at conf-freeze (ONE Failed
  status), and a deployment stuck Degraded past ``degraded_deadline_s``
  emits ``DegradedTimeout`` and escalates into the shrink path.
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.analysis.runtime import BlockLedger
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.resize import (
    GangResizer,
    ResizeAborted,
    degree_of,
    flatten_params,
    unflatten_params,
)


@pytest.fixture(scope="module")
def tiny_llama():
    # heads divide every degree the suite resizes through (1, 2, 4, 8)
    cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    from flax import linen as nn

    return cfg, nn.meta.unbox(params["params"])


KW = dict(num_slots=3, decode_chunk=2, prefix_cache=False, block_size=16,
          seq_buckets=[32])
PROMPT = list(range(1, 25))


def make_engine(tiny_llama, mesh_axes=None, **kw):
    cfg, params = tiny_llama
    merged = {**KW, **kw}
    eng = ContinuousEngine(cfg, params, mesh_axes=mesh_axes, **merged)
    # analyzer block-economy audit (ISSUE 11): GangResizer re-attaches
    # the same ledger to every new-degree engine it builds, so "zero
    # leaked blocks on both allocators" is ONE gauge across the resize
    eng.attach_block_ledger(BlockLedger())
    return eng


@pytest.fixture(scope="module")
def oracle(tiny_llama):
    """Un-resized greedy truth (degree-invariant on the CPU stand-in)."""
    eng = make_engine(tiny_llama)
    try:
        return {
            "long40": eng.generate(PROMPT, max_new_tokens=40, timeout=300),
            "short12": eng.generate([7, 8, 9], max_new_tokens=12,
                                    timeout=300),
        }
    finally:
        eng.stop()


def _wait_tokens(req, n, timeout=120):
    deadline = time.time() + timeout
    while len(req.tokens) < n:
        assert time.time() < deadline, "no tokens emitted"
        time.sleep(0.002)


def _wait_all_free(eng, timeout=15):
    deadline = time.time() + timeout
    while eng.stats()["kv_blocks_free"] != eng.num_blocks:
        assert time.time() < deadline, eng.stats()
        time.sleep(0.01)
    # the ledger audit is the leak oracle (the free-count poll above is
    # only retirement synchronization): zero blocks referenced outside
    # live slot tables, zero conservation drift, gauge at 0
    if eng.block_ledger is not None:
        assert eng.audit_blocks() == []
        assert eng.stats()["kv_blocks_leaked_total"] == 0
        assert eng.block_ledger.conservation_errors == []


class TestReshardPlan:
    def test_plan_is_json_able_and_names_specs(self, tiny_llama):
        from kubeflow_tpu.parallel.sharding import reshard_plan
        from kubeflow_tpu.serving.sharded import (
            build_serving_mesh,
            llama_param_shardings,
        )

        cfg, params = tiny_llama
        mesh = build_serving_mesh({"model": 2})
        src = llama_param_shardings(cfg, mesh)
        dst = jax.tree.map(lambda _: None, params)
        plan = reshard_plan(params, src, dst)
        assert plan and all(
            set(e) == {"path", "shape", "dtype", "src", "dst"}
            for e in plan)
        json.dumps(plan)  # the wire header must frame as pure JSON
        # at least one leaf is TP-sharded at the source and replicated
        # at the destination (the shrink-to-1 shape)
        assert any(any(s is not None for s in e["src"]) for e in plan)
        assert all(all(d is None for d in e["dst"]) for e in plan)

    def test_illegal_degree_rejected_at_plan_time(self, tiny_llama):
        from kubeflow_tpu.parallel.sharding import reshard_plan
        from kubeflow_tpu.serving.sharded import (
            build_serving_mesh,
            llama_param_shardings,
        )

        cfg, params = tiny_llama  # 8 heads cannot split 3 ways
        mesh3 = build_serving_mesh({"model": 3})
        dst = llama_param_shardings(cfg, mesh3)
        src = jax.tree.map(lambda _: None, params)
        with pytest.raises(ValueError, match="does not divide"):
            reshard_plan(params, src, dst)

    def test_block_budget_scales_with_degree(self):
        from kubeflow_tpu.serving.paged import resize_block_budget

        assert resize_block_budget(24, 2, 1) == 12
        assert resize_block_budget(12, 1, 2) == 24
        # floored at what the live sequences already hold
        assert resize_block_budget(24, 2, 1, reserved=17) == 17
        with pytest.raises(ValueError):
            resize_block_budget(24, 0, 1)

    def test_flatten_unflatten_roundtrip(self, tiny_llama):
        _cfg, params = tiny_llama
        leaves = flatten_params(params)
        rebuilt = unflatten_params(dict(leaves))
        flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_b = jax.tree_util.tree_flatten_with_path(rebuilt)[0]
        assert len(flat_a) == len(flat_b)
        for (pa, la), (pb, lb) in zip(flat_a, flat_b):
            assert pa == pb
            assert np.array_equal(np.asarray(la), np.asarray(lb))

    def test_degree_of(self):
        assert degree_of(None) == 1
        assert degree_of({}) == 1
        assert degree_of({"model": 4}) == 4
        assert degree_of({"model": 2, "data": 2}) == 4


class TestResizeParity:
    """Acceptance: a TP-degree change is invisible to greedy
    correctness — shrink AND grow, on original request handles."""

    def test_shrink_then_grow_bit_identical(self, tiny_llama, oracle):
        src = make_engine(tiny_llama, mesh_axes={"model": 2})
        src.warmup()
        events = []
        rz = GangResizer(src, on_event=lambda r, m: events.append(r))
        new = new2 = None
        try:
            req = src.submit(PROMPT, max_new_tokens=40)
            # a queued-but-unadmitted request must follow the pool
            extras = [src.submit([7, 8, 9], max_new_tokens=12)
                      for _ in range(KW["num_slots"] + 1)]
            _wait_tokens(req, 4)
            new = rz.resize({"model": 1})
            assert new.mesh is None  # degree 1 IS the unmeshed engine
            assert req.wait(300) == oracle["long40"]
            for e in extras:
                assert e.wait(300) == oracle["short12"]
            # the SOURCE released everything before it stopped — the
            # shared ledger audits the retired allocator directly (the
            # old free-count compare could not see a refcount drift)
            assert src.audit_blocks() == []
            assert src.stats()["kv_blocks_leaked_total"] == 0
            assert new.stats()["jit_recompiles_total"] == 0
            # grow back with a live conversation aboard
            req2 = new.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req2, 6)
            new2 = rz.resize({"model": 2})
            assert req2.wait(300) == oracle["long40"]
            assert new2.stats()["jit_recompiles_total"] == 0
            _wait_all_free(new2)
            # pool capacity followed the degree both ways
            assert new.num_blocks == src.num_blocks // 2
            assert new2.num_blocks == src.num_blocks
            assert events.count("GangResized") == 2
            # post-cutover traffic lands on the new engine
            assert new2.generate([7, 8, 9], max_new_tokens=12,
                                 timeout=300) == oracle["short12"]
        finally:
            (new2 or new or src).stop()

    @pytest.mark.slow
    def test_chunked_variant_parity(self, tiny_llama, oracle):
        src = make_engine(tiny_llama, mesh_axes={"model": 2},
                          prefill_budget=8, decode_chunk=1)
        ref = make_engine(tiny_llama, prefill_budget=8, decode_chunk=1)
        want = ref.generate(PROMPT, max_new_tokens=40, timeout=300)
        ref.stop()
        src.warmup()
        rz = GangResizer(src)
        new = None
        try:
            req = src.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req, 2)
            new = rz.resize({"model": 1})
            assert req.wait(300) == want
            assert new.stats()["jit_recompiles_total"] == 0
            _wait_all_free(new)
        finally:
            (new or src).stop()

    @pytest.mark.slow
    def test_spec_variant_parity(self, tiny_llama):
        loopy = [5, 6, 5, 6, 5, 6, 5]
        ref = make_engine(tiny_llama, decode_chunk=1)
        want = ref.generate(loopy, max_new_tokens=24, timeout=300)
        ref.stop()
        src = make_engine(tiny_llama, mesh_axes={"model": 2},
                          decode_chunk=1, spec_k=4)
        src.warmup()
        rz = GangResizer(src)
        new = None
        try:
            req = src.submit(loopy, max_new_tokens=24)
            _wait_tokens(req, 2)
            new = rz.resize({"model": 1})
            assert req.wait(300) == want
            assert new.stats()["jit_recompiles_total"] == 0
        finally:
            (new or src).stop()

    @pytest.mark.slow
    def test_int8_kv_variant_parity(self, tiny_llama):
        cfg, params = tiny_llama
        qcfg, qparams = llamalib.quantize_for_serving(
            cfg, params, weights=False, kv=True)
        kw = dict(KW, num_slots=2)
        ref = ContinuousEngine(qcfg, qparams, **kw)
        want = ref.generate(PROMPT, max_new_tokens=24, timeout=300)
        ref.stop()
        src = ContinuousEngine(qcfg, qparams, mesh_axes={"model": 2},
                               **kw)
        src.warmup()
        rz = GangResizer(src)
        new = None
        try:
            req = src.submit(PROMPT, max_new_tokens=24)
            _wait_tokens(req, 2)
            new = rz.resize({"model": 1})
            assert req.wait(300) == want
            assert new.stats()["jit_recompiles_total"] == 0
            _wait_all_free(new)
        finally:
            (new or src).stop()

    def test_sse_stream_survives_mid_stream_resize(self, tiny_llama,
                                                   oracle):
        """The acceptance bar's SSE leg: one stream, no reconnect — the
        chunk concatenation equals the blocking completion even though
        the engine changed TP degree mid-stream (the request handle is
        re-targeted in place, exactly the PR 7 contract)."""
        from kubeflow_tpu.serving.text import TextGenerator

        src = make_engine(tiny_llama, mesh_axes={"model": 2})
        src.warmup()
        model = TextGenerator("m", {"tokenizer": "bytes"}, engine=src)
        model.load()
        rz = GangResizer(
            src, set_engine=lambda e: setattr(model, "engine", e))
        try:
            blocking = model.openai_completions(
                {"prompt": "hello world, this is a prompt",
                 "max_tokens": 24})
            want = blocking["choices"][0]["text"]
            chunks = []
            resized = threading.Event()

            def _resize_soon():
                time.sleep(0.05)
                rz.resize({"model": 1})
                resized.set()

            t = threading.Thread(target=_resize_soon, daemon=True)
            t.start()
            for raw in model.openai_stream(
                    {"prompt": "hello world, this is a prompt",
                     "max_tokens": 24, "stream": True}):
                line = raw.decode()
                if line.startswith("data: ") and "[DONE]" not in line:
                    chunks.append(json.loads(
                        line[len("data: "):])["choices"][0]["text"])
            t.join(timeout=60)
            assert resized.is_set()
            assert "".join(chunks) == want
            assert model.engine is rz.engine
        finally:
            model.engine = None  # the resizer owns engine shutdown
            rz.engine.stop()
            model.stop()


class TestKillMidResize:
    """Copy-then-cutover under seeded failure: a resize dying at ANY
    phase leaves the old-degree engine serving, tokens exactly-once,
    zero leaked blocks on both allocators."""

    def test_mid_export_abort_resumes_in_place(self, tiny_llama, oracle):
        from kubeflow_tpu.chaos import FaultPlan

        plan = FaultPlan(seed=3).kill_mid_resize(phase="export")
        src = make_engine(tiny_llama, mesh_axes={"model": 2})
        src.warmup()
        rz = GangResizer(src, failpoint=plan.resize_failpoint())
        try:
            req = src.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req, 4)
            with pytest.raises(ResizeAborted) as ei:
                rz.resize({"model": 1})
            assert ei.value.phase == "export"
            assert rz.engine is src  # nothing cut over
            # source still serving: the frozen sequence resumed and
            # completes bit-identically — exactly-once tokens
            assert req.wait(300) == oracle["long40"]
            _wait_all_free(src)
            # admissions un-quiesced
            assert src.generate([7, 8, 9], max_new_tokens=12,
                                timeout=300) == oracle["short12"]
            assert src.stats()["jit_recompiles_total"] == 0
        finally:
            src.stop()

    @pytest.mark.slow
    def test_seeded_phase_sweep(self, tiny_llama, oracle):
        """The full seeded sweep: every phase offset (mid-export,
        mid-reshard, mid-commit) aborts cleanly — then the SAME engine
        resizes successfully, proving no state was corrupted by the
        three failed attempts."""
        from kubeflow_tpu.chaos import FaultPlan

        src = make_engine(tiny_llama, mesh_axes={"model": 2})
        src.warmup()
        req = src.submit(PROMPT, max_new_tokens=60)
        _wait_tokens(req, 4)
        new = None
        try:
            for phase in FaultPlan.RESIZE_PHASES:
                plan = FaultPlan(seed=11).kill_mid_resize(phase=phase)
                rz = GangResizer(src, failpoint=plan.resize_failpoint())
                before = len(req.tokens)
                with pytest.raises(ResizeAborted) as ei:
                    rz.resize({"model": 1})
                assert ei.value.phase == phase
                assert rz.engine is src
                # still serving after the abort (tokens keep flowing)
                _wait_tokens(req, before + 1)
            # seeded phase CHOICE is deterministic too
            p1 = FaultPlan(seed=7).kill_mid_resize()
            p2 = FaultPlan(seed=7).kill_mid_resize()
            assert p1.faults[0].role == p2.faults[0].role
            # the battle-scarred engine still resizes cleanly
            rz = GangResizer(src)
            new = rz.resize({"model": 1})
            assert req.wait(300) == src_oracle_long60(oracle, tiny_llama)
            assert new.stats()["jit_recompiles_total"] == 0
            _wait_all_free(new)
        finally:
            (new or src).stop()


def src_oracle_long60(oracle, tiny_llama):
    """60-token oracle (computed once lazily; the module oracle holds
    40 — the sweep needs a longer run to survive three aborts)."""
    if "long60" not in oracle:
        eng = make_engine(tiny_llama)
        try:
            oracle["long60"] = eng.generate(PROMPT, max_new_tokens=60,
                                            timeout=300)
        finally:
            eng.stop()
    return oracle["long60"]


@pytest.mark.slow
class TestGangResize:
    """The gang path over a loopback channel: the ``resize`` control op,
    the rs_* reshard wire, follower rebuild + ack, replayed imports —
    leader and follower pool state bit-identical at the new degree."""

    CHAN = dict(hb_interval=0.05, dead_peer_timeout=0.5,
                reattach_timeout=60.0, reconnect_timeout=2.0)

    def test_member_loss_shrinks_then_fresh_member_grows_back(
            self, tiny_llama, oracle):
        from kubeflow_tpu.serving.gang import (
            GangChannel,
            GangEngine,
            follow,
        )
        from kubeflow_tpu.utils.net import allocate_port

        cfg, params = tiny_llama
        port = allocate_port()
        kw = dict(KW, temperature=0.0, eos_id=None)

        f1 = ContinuousEngine(cfg, params, mesh_axes={"model": 4}, **kw)
        f1_chan = {}

        def run_f1():
            ch = GangChannel.connect("127.0.0.1", port, rank=1,
                                     token="t", **self.CHAN)
            f1_chan["ch"] = ch
            try:
                follow(f1, ch)
            except Exception:  # noqa: BLE001 — killed by the test
                pass
            finally:
                ch.close()

        t1 = threading.Thread(target=run_f1, daemon=True)
        t1.start()
        chan = GangChannel.listen(port, 1, token="t", **self.CHAN)
        leader = GangEngine(cfg, params, channel=chan,
                            mesh_axes={"model": 4}, **kw)
        leader.warmup()
        events = []
        rz = GangResizer(leader, reshard_token="rs",
                         on_event=lambda r, m: events.append(r))
        new = new2 = None
        try:
            req = leader.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req, 4)

            # PERMANENT member loss: the follower's channel dies and
            # never re-dials (ch.close() sets its closing flag)
            f1_chan["ch"].close()
            deadline = time.time() + 60
            while 1 not in chan.missing_ranks:
                assert time.time() < deadline, "leader never evicted"
                time.sleep(0.01)
            chan.forget_rank(1)
            chan.set_want(0)

            # shrink to the surviving degree: leader-only gang at TP=2
            new = rz.resize({"model": 2})
            assert req.wait(300) == oracle["long40"]
            assert new.stats()["jit_recompiles_total"] == 0
            assert events == ["GangResized"]

            # grow-back: a FRESH member joins (no shared history) and
            # the inverse resize rebuilds it through the reshard wire
            chan.set_want(1)
            f2_state = {}
            seed = ContinuousEngine(cfg, params, mesh_axes={"model": 2},
                                    **kw)

            def run_f2():
                ch = GangChannel.connect("127.0.0.1", port, rank=1,
                                         token="t", fresh=True,
                                         **self.CHAN)
                try:
                    follow(seed, ch, fresh=True,
                           on_engine=lambda e: f2_state.update(eng=e))
                finally:
                    ch.close()

            t2 = threading.Thread(target=run_f2, daemon=True)
            t2.start()
            deadline = time.time() + 60
            while not chan.follower_ranks():
                assert time.time() < deadline, "fresh member never joined"
                time.sleep(0.01)

            req2 = new.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req2, 6)
            new2 = rz.resize({"model": 4})
            assert req2.wait(300) == oracle["long40"]
            assert new2.stats()["jit_recompiles_total"] == 0
            assert events == ["GangResized", "GangResized"]
            follower_eng = f2_state.get("eng")
            assert follower_eng is not None, "follower never rebuilt"
            # stop publishes the terminal op; the follower drains the
            # FULL stream before returning — then both pools must be
            # bit-identical, imports and post-resize decodes included
            new2.stop()
            new2 = None
            t2.join(timeout=300)
            assert not t2.is_alive(), "follower did not drain"
            ll = np.asarray(jax.device_get(rz.engine._pool_logits))
            fl = np.asarray(jax.device_get(follower_eng._pool_logits))
            assert np.array_equal(ll, fl)
            for a, b in zip(
                    jax.tree.leaves(jax.device_get(
                        rz.engine._pool_cache)),
                    jax.tree.leaves(jax.device_get(
                        follower_eng._pool_cache))):
                assert np.array_equal(np.asarray(a), np.asarray(b))
        finally:
            rz.engine.stop()
            chan.close()

    def test_follower_rebuild_failure_aborts_and_old_stream_continues(
            self, tiny_llama, oracle):
        """A follower that cannot rebuild acks failure -> the leader
        aborts (resize_abort), the follower keeps its old engine, and
        the old-degree stream continues bit-identically."""
        from kubeflow_tpu.serving import resize as rszlib
        from kubeflow_tpu.serving.gang import (
            GangChannel,
            GangEngine,
            follow,
        )
        from kubeflow_tpu.utils.net import allocate_port

        cfg, params = tiny_llama
        port = allocate_port()
        kw = dict(KW, temperature=0.0, eos_id=None)
        follower = ContinuousEngine(cfg, params, mesh_axes={"model": 4},
                                    **kw)

        def run_f():
            ch = GangChannel.connect("127.0.0.1", port, rank=1,
                                     token="t", **self.CHAN)
            try:
                follow(follower, ch)
            finally:
                ch.close()

        t = threading.Thread(target=run_f, daemon=True)
        t.start()
        chan = GangChannel.listen(port, 1, token="t", **self.CHAN)
        leader = GangEngine(cfg, params, channel=chan,
                            mesh_axes={"model": 4}, **kw)
        leader.warmup()
        # sabotage the follower's rebuild: a wrong reshard token makes
        # its ReshardClient handshake fail — it can never even ack, so
        # the leader's bounded ack wait is what aborts (shortened here:
        # the default 120s is the production grace)
        rz = GangResizer(leader, reshard_token="rs", ack_timeout_s=10.0)
        orig_init = rszlib.ReshardClient.__init__

        def bad_init(self, host, port, *, token="", **kwargs):
            return orig_init(self, host, port, token="WRONG", **kwargs)

        rszlib.ReshardClient.__init__ = bad_init
        try:
            req = leader.submit(PROMPT, max_new_tokens=40)
            _wait_tokens(req, 4)
            with pytest.raises(ResizeAborted):
                rz.resize({"model": 2})
            assert rz.engine is leader
            # the old-degree gang keeps serving, bit-identically
            assert req.wait(300) == oracle["long40"]
        finally:
            rszlib.ReshardClient.__init__ = orig_init
            leader.stop()
            t.join(timeout=60)
            chan.close()


@pytest.mark.slow
class TestElasticSupervisor:
    """Shrink-to-survive end-to-end at the engine layer: the supervisor
    sees a member evicted past resize_deadline_s, forgets the rank, and
    resizes to the surviving degree with a GangResized event — Degraded
    becomes a bounded recovery, not a terminal wait."""

    def test_member_loss_escalates_to_shrink(self, tiny_llama, oracle):
        from kubeflow_tpu.chaos import FaultPlan
        from kubeflow_tpu.serving.gang import (
            GangChannel,
            GangEngine,
            follow,
        )
        from kubeflow_tpu.serving.resize import ElasticGangSupervisor
        from kubeflow_tpu.utils.net import allocate_port

        cfg, params = tiny_llama
        port = allocate_port()
        kw = dict(KW, temperature=0.0, eos_id=None)
        chan_kw = dict(hb_interval=0.05, dead_peer_timeout=0.3,
                       reattach_timeout=60.0, reconnect_timeout=2.0)
        plan = FaultPlan(seed=5).gang_member_loss(world=2, at=0.0)
        assert plan.faults[0].index == 1  # spare_leader pins rank 1

        follower = ContinuousEngine(cfg, params, mesh_axes={"model": 4},
                                    **kw)
        f_chan = {}

        def run_f():
            ch = GangChannel.connect("127.0.0.1", port, rank=1,
                                     token="t", **chan_kw)
            f_chan["ch"] = ch
            try:
                follow(follower, ch)
            except Exception:  # noqa: BLE001 — killed by the plan
                pass
            finally:
                ch.close()

        t = threading.Thread(target=run_f, daemon=True)
        t.start()
        chan = GangChannel.listen(port, 1, token="t", **chan_kw)
        leader = GangEngine(cfg, params, channel=chan,
                            mesh_axes={"model": 4}, **kw)
        leader.warmup()
        events = []
        rz = GangResizer(leader, reshard_token="rs",
                         on_event=lambda r, m: events.append((r, m)))
        sup = ElasticGangSupervisor(
            rz, chan, degree_per_member=2, max_degree=4, min_degree=2,
            resize_deadline_s=0.4, poll_s=0.05)
        try:
            req = leader.submit(PROMPT, max_new_tokens=60)
            _wait_tokens(req, 4)
            plan.activate()
            for rank in plan.due_member_losses():
                f_chan["ch"].close()  # permanent: never re-dials
            # the supervisor escalates within the deadline: a resize to
            # the surviving degree, conversation intact (generous wall
            # clock: the new-degree build + warmup compiles on a loaded
            # 1-core CPU stand-in)
            deadline = time.time() + 180
            while rz.degree() != 2:
                assert time.time() < deadline, \
                    f"no shrink; events={events}"
                time.sleep(0.05)
            assert ("GangResized" in [r for r, _ in events])
            assert req.wait(300)[:40] == oracle["long40"][:40]
            assert 1 not in chan.missing_ranks  # forgotten, not fatal
            assert chan._dead is None
            assert rz.engine.stats()["jit_recompiles_total"] == 0
        finally:
            sup.stop()
            rz.engine.stop()
            chan.close()


class TestElasticControllerKnobs:
    def test_bad_elastic_fails_isvc_at_conf_freeze(self):
        """Satellite: a bad ``elastic`` family is ONE Failed status with
        the knob named — caught at conf-freeze, before any gang pod
        crash-loops (the PR 4/7/8 convention)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            bad = {
                "bad-min": ({"params_ref": "mem://never", "block_size": 16,
                             "elastic": {"min_degree": 0}}, "elastic"),
                "bad-key": ({"params_ref": "mem://never", "block_size": 16,
                             "elastic": {"min_degre": 2}}, "elastic"),
                "bad-ddl": ({"params_ref": "mem://never", "block_size": 16,
                             "elastic": {"degraded_deadline_s": -1}},
                            "elastic"),
                "bad-pool": ({"params_ref": "mem://never",
                              "elastic": {"min_degree": 1}}, "elastic"),
                # the STANDALONE fallback knob validates too (it is
                # float()ed on every reconcile pass at runtime)
                "bad-sddl": ({"params_ref": "mem://never",
                              "degraded_deadline_s": "soon"},
                             "degraded_deadline_s"),
            }
            for name, (cfg, _needle) in bad.items():
                cluster.store.create(InferenceService(
                    metadata=ObjectMeta(name=name),
                    spec=InferenceServiceSpec(predictor=ComponentSpec(
                        model_format=ModelFormat(name="llama-continuous"),
                        config=cfg))))
            for name, (_cfg, needle) in bad.items():
                deadline = time.time() + 20
                isvc = None
                while time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    (name, isvc.status)
                assert needle in (isvc.status.message or ""), \
                    (name, isvc.status.message)

    def test_degraded_deadline_emits_timeout_and_escalates(self):
        """Satellite bugfix: Degraded is no longer unbounded — past
        ``degraded_deadline_s`` the controller emits a structured
        DegradedTimeout, and with ``elastic`` configured re-places the
        degraded gang at the surviving shape (GangResized)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import GangSpec, InferenceService
        from kubeflow_tpu.controlplane.store import Store
        from kubeflow_tpu.serving.controller import (
            InferenceServiceController,
            _Deployment,
            _Revision,
        )

        store = Store()
        isvc = InferenceService(metadata=ObjectMeta(name="el"))
        events = []

        class _Ctl:
            emit_event = staticmethod(
                lambda obj, reason, msg, type_="Normal":
                events.append((reason, msg)))
            _wire = staticmethod(lambda *_a, **_k: None)
            _escalate_shrink = InferenceServiceController._escalate_shrink
            store = None

        _Ctl.store = store

        class _DeadGang:
            gang = GangSpec(hosts=2, mesh_axes={"model": 8},
                            chips_per_host=4)
            ready = False
            stopped = False

            def stop(self):
                type(self).stopped = True

        cfg = {"elastic": {"min_degree": 2, "degraded_deadline_s": 0.5},
               "block_size": 16}
        dep = _Deployment()
        dep.stable = _Revision(1, "fp", isvc.spec, None, cfg)
        dep.stable.predictors = [_DeadGang()]

        track = InferenceServiceController._track_degraded
        # first degraded tick starts the clock, no event
        track(_Ctl(), isvc, dep, True)
        assert dep.degraded_since is not None and not events
        # within the deadline: still no event
        track(_Ctl(), isvc, dep, True)
        assert not events
        # past the deadline: DegradedTimeout + shrink escalation
        dep.degraded_since -= 1.0
        track(_Ctl(), isvc, dep, True)
        reasons = [r for r, _ in events]
        assert reasons[0] == "DegradedTimeout"
        assert "GangResized" in reasons
        assert _DeadGang.stopped  # the degraded placement was replaced
        replacement = dep.stable.predictors[0]
        assert replacement.gang.hosts == 1
        assert replacement.gang.mesh_axes == {"model": 4}
        # one escalation per episode, not one per 4 Hz tick
        n = len(events)
        track(_Ctl(), isvc, dep, True)
        assert len(events) == n
        # recovery resets the episode
        track(_Ctl(), isvc, dep, False)
        assert dep.degraded_since is None and not dep.degraded_escalated
        replacement.stop()

    def test_min_degree_floors_the_shrink(self):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import GangSpec, InferenceService
        from kubeflow_tpu.serving.controller import (
            InferenceServiceController,
            _Deployment,
            _Revision,
        )

        isvc = InferenceService(metadata=ObjectMeta(name="el2"))
        events = []

        class _Ctl:
            emit_event = staticmethod(
                lambda obj, reason, msg, type_="Normal":
                events.append(reason))
            _wire = staticmethod(lambda *_a, **_k: None)
            store = None

        class _DeadGang:
            gang = GangSpec(hosts=2, mesh_axes={"model": 8})
            ready = False

            def stop(self):
                raise AssertionError("must not re-place below min_degree")

        dep = _Deployment()
        dep.stable = _Revision(1, "fp", isvc.spec, None, {})
        dep.stable.predictors = [_DeadGang()]
        InferenceServiceController._escalate_shrink(
            _Ctl(), isvc, dep, {"min_degree": 8})
        assert events == ["ResizeSkipped"]
