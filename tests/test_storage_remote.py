"""gs:// and s3:// through the pluggable transport (r3 verdict item 8).

The reference's storage initializer downloads cloud URIs into /mnt/models
[upstream: kserve pkg/agent/storage]; this deployment has zero egress, so
the capability is carried by an injectable transport staged through the
same manifest-verified cache hf:// uses.  Real network stays refused.
"""

import os

import pytest

from kubeflow_tpu.serving import storage


@pytest.fixture(autouse=True)
def _clean_transports():
    yield
    storage.register_transport("gs://", None)
    storage.register_transport("s3://", None)


def _fake_transport(payload: dict, calls: list):
    def fetch(uri, dest_dir):
        calls.append(uri)
        for rel, content in payload.items():
            p = os.path.join(dest_dir, rel)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "w") as f:
                f.write(content)
    return fetch


class TestRemoteTransports:
    @pytest.mark.parametrize("scheme", ["gs", "s3"])
    def test_download_stages_through_manifest_cache(self, scheme, tmp_path):
        calls = []
        storage.register_transport(
            f"{scheme}://",
            _fake_transport({"config.json": "{}", "weights.msgpack": "W"},
                            calls))
        uri = f"{scheme}://bucket/models/demo"
        path = storage.download(uri, cache_dir=str(tmp_path / "cache"))
        assert sorted(os.listdir(path)) == ["config.json", "weights.msgpack"]
        # manifest exists and validates
        entry = os.path.dirname(path)
        assert storage.verify_manifest(entry)
        assert calls == [uri]

    def test_cache_hit_skips_transport(self, tmp_path):
        calls = []
        storage.register_transport(
            "gs://", _fake_transport({"m.bin": "data"}, calls))
        cache = str(tmp_path / "cache")
        p1 = storage.download("gs://b/m", cache_dir=cache)
        p2 = storage.download("gs://b/m", cache_dir=cache)
        assert p1 == p2 and calls == ["gs://b/m"]  # one fetch, two serves

    def test_corrupted_entry_refetches(self, tmp_path):
        calls = []
        storage.register_transport(
            "gs://", _fake_transport({"m.bin": "data"}, calls))
        cache = str(tmp_path / "cache")
        p1 = storage.download("gs://b/m", cache_dir=cache)
        with open(os.path.join(p1, "m.bin"), "w") as f:
            f.write("CORRUPTED")
        p2 = storage.download("gs://b/m", cache_dir=cache)
        assert len(calls) == 2
        with open(os.path.join(p2, "m.bin")) as f:
            assert f.read() == "data"

    def test_no_transport_no_tool_raises_zero_egress(self, tmp_path,
                                                     monkeypatch):
        # guarantee the CLI-tool fallbacks are absent
        monkeypatch.setenv("PATH", str(tmp_path))
        with pytest.raises(storage.StorageError, match="egress"):
            storage.download("gs://bucket/model")
        with pytest.raises(storage.StorageError, match="egress"):
            storage.download("s3://bucket/model")

    def test_transport_failure_surfaces(self, tmp_path):
        def broken(uri, dest):
            raise storage.StorageError(f"{uri}: access denied")
        storage.register_transport("gs://", broken)
        with pytest.raises(storage.StorageError, match="access denied"):
            storage.download("gs://b/m", cache_dir=str(tmp_path / "c"))

    def test_empty_fetch_rejected(self, tmp_path):
        storage.register_transport("gs://", lambda uri, dest: None)
        with pytest.raises(storage.StorageError, match="no files"):
            storage.download("gs://b/empty", cache_dir=str(tmp_path / "c"))
