"""AOT program-artifact cache (serving/programs.py) — ISSUE 17.

The compile wall behind cold start, scale-from-zero and resize is paid
once per (model, degree, rung) cluster-wide: warmed programs persist as
manifest-verified on-disk artifacts and later boots load them instead
of compiling.  Pinned here:

- the STORE: atomic publish (payload fsync -> manifest fsync -> rename),
  size+sha256 verification on load, torn/corrupt entries detected,
  counted, deleted and degraded to a normal compile — never a crash;
- PARITY: greedy decode is bit-identical cache-off vs cache-on-cold vs
  cache-on-warm across engine variants, with ``jit_recompiles_total ==
  0`` and a clean block ledger on the warm path;
- seeded CHAOS: ``FaultPlan.spill_torn`` tears a just-published
  artifact; the next boot detects it at load and recompiles;
- the CONF-FREEZE contract: bad ``aot:`` knobs are ONE Failed status
  (the PR 4/7/9 convention), validated by ``validate_aot``;
- the warmup TRACE: ``engine.warmup`` phase with per-family
  compile/artifact-load spans on /traces, ``kft_aot_cache_*`` counters
  on /metrics, and a promtool-lint-clean scrape;
- the autoscaler's warm-path cold-start EWMA (``note_cold_start``
  tagged with the cache outcome).
"""

import json
import os
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.chaos import FaultPlan
from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.programs import (
    ARTIFACT_MANIFEST,
    PAYLOAD_NAME,
    ProgramArtifactCache,
    build_program_cache,
    cache_key_base,
    model_fingerprint,
    validate_aot,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 2)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("block_size", 16)
    return ContinuousEngine(cfg, params, **kw)


# -- keys -----------------------------------------------------------------


class TestKeys:
    def test_fingerprint_ignores_weight_values(self, tiny_llama):
        """Two checkpoints of one architecture share a program ladder:
        weights are runtime inputs to the executable, not HLO."""
        cfg, params = tiny_llama
        doubled = jax.tree_util.tree_map(lambda x: x * 2, params)
        assert model_fingerprint(cfg, params) == \
            model_fingerprint(cfg, doubled)

    def test_fingerprint_sees_architecture(self, tiny_llama):
        cfg, params = tiny_llama
        cfg2 = llamalib.tiny(num_heads=8, num_kv_heads=8)
        params2 = llamalib.Llama(cfg2).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        assert model_fingerprint(cfg, params) != \
            model_fingerprint(cfg2, params2)

    def test_key_base_varies_with_program_shaping_knobs(self, tiny_llama):
        cfg, params = tiny_llama
        a = cache_key_base(cfg, params, chunk=1)
        b = cache_key_base(cfg, params, chunk=2)
        assert a != b
        assert jax.__version__ in a  # a jax upgrade invalidates cleanly

    def test_entry_key_separates_families_and_sigs(self):
        k = ProgramArtifactCache.entry_key
        assert k("b", "decode", "s1") != k("b", "prefill", "s1")
        assert k("b", "decode", "s1") != k("b", "decode", "s2")
        assert k("b", "decode", "s1") == k("b", "decode", "s1")


# -- the store ------------------------------------------------------------


class TestArtifactStore:
    def test_publish_verify_load_roundtrip(self, tmp_path):
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        assert c.load(key) is None  # empty: no entry, no failure count
        payload = os.urandom(4096)
        assert c.publish(key, payload, meta={"family": "decode"})
        assert c.verify(key)
        assert c.load(key) == payload
        st = c.stats()
        assert st["aot_cache_published_total"] == 1
        assert st["aot_cache_entries"] == 1
        assert st["aot_cache_bytes"] == 4096
        assert st["aot_cache_bytes_written_total"] == 4096
        assert st["aot_cache_bytes_read_total"] == 4096
        assert st["aot_cache_load_failures_total"] == 0

    def test_duplicate_publish_is_idempotent(self, tmp_path):
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        assert c.publish(key, b"x" * 64)
        assert c.publish(key, b"x" * 64)  # first writer already won
        assert c.stats()["aot_cache_published_total"] == 1
        assert c.stats()["aot_cache_entries"] == 1

    def test_torn_payload_detected_counted_removed(self, tmp_path):
        """The acceptance bar verbatim: a torn entry is DETECTED and
        falls back to normal compile (load -> None), never a crash —
        and the deleted entry is republishable."""
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        payload = os.urandom(1024)
        assert c.publish(key, payload)
        with open(os.path.join(str(tmp_path), key, PAYLOAD_NAME),
                  "r+b") as f:
            f.truncate(1024 - 7)
        assert c.load(key) is None
        assert c.stats()["aot_cache_load_failures_total"] == 1
        assert not c.verify(key)  # the offending entry was removed
        assert c.publish(key, payload)  # and can be replaced
        assert c.load(key) == payload

    def test_corrupt_payload_bytes_detected(self, tmp_path):
        """Right size, wrong bytes: the sha256 check catches silent
        corruption the size check cannot."""
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        assert c.publish(key, b"a" * 256)
        with open(os.path.join(str(tmp_path), key, PAYLOAD_NAME),
                  "r+b") as f:
            f.seek(100)
            f.write(b"Z")
        assert c.load(key) is None
        assert c.stats()["aot_cache_load_failures_total"] == 1

    def test_corrupt_manifest_detected(self, tmp_path):
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        assert c.publish(key, b"y" * 128)
        with open(os.path.join(str(tmp_path), key, ARTIFACT_MANIFEST),
                  "w") as f:
            f.write("{not json")
        assert c.load(key) is None
        assert not c.verify(key)

    def test_stale_staging_swept_fresh_kept(self, tmp_path):
        """A crashed publisher's staging dir is garbage-collected at
        the next publish of the same key; a LIVE publisher's staging
        dir (recent mtime) survives the sweep."""
        c = ProgramArtifactCache(str(tmp_path))
        key = c.entry_key("base", "decode", "sig")
        stale = tmp_path / f".staging-{key}-999-deadbeef"
        fresh = tmp_path / f".staging-{key}-998-cafecafe"
        stale.mkdir()
        fresh.mkdir()
        old = time.time() - 7200.0
        os.utime(str(stale), (old, old))
        assert c.publish(key, b"z" * 32)
        assert not stale.exists()
        assert fresh.exists()
        assert c.entries() == [key]  # dot-dirs never listed as entries

    def test_chaos_torn_seam_fires_on_publish(self, tmp_path):
        """The KvSpillStore seam, one tier up: ``spill_torn`` tears the
        just-published artifact's tail, so the entry exists with an
        intact manifest but a payload that no longer verifies."""
        plan = FaultPlan(seed=7).spill_torn(64)
        c = ProgramArtifactCache(str(tmp_path), chaos=plan)
        key = c.entry_key("base", "decode", "sig")
        assert c.publish(key, os.urandom(512))
        assert os.path.exists(
            os.path.join(str(tmp_path), key, ARTIFACT_MANIFEST))
        assert c.load(key) is None  # detected, counted, removed
        assert c.stats()["aot_cache_load_failures_total"] == 1


# -- conf-freeze ----------------------------------------------------------


class TestValidateAot:
    def test_good_specs_pass(self, tmp_path):
        validate_aot({"root": str(tmp_path)})
        validate_aot({"root": str(tmp_path), "fsync": False})

    @pytest.mark.parametrize("spec,needle", [
        (["/tmp/x"], "mapping"),
        ({"root": ""}, "root"),
        ({"root": str, "fsync": True}, "root"),
        ({"root": "/tmp/x", "fsync": "yes"}, "fsync"),
        ({"root": "/tmp/x", "rot": "/tmp/y"}, "unknown"),
    ])
    def test_bad_knobs_raise_with_the_knob_named(self, spec, needle):
        with pytest.raises((TypeError, ValueError), match=needle):
            validate_aot(spec)

    def test_build_program_cache_seam(self, tmp_path):
        assert build_program_cache(None) is None
        assert build_program_cache({}) is None
        c = build_program_cache({"aot": {"root": str(tmp_path),
                                         "fsync": False}})
        assert isinstance(c, ProgramArtifactCache)
        assert c.fsync is False
        with pytest.raises(ValueError):
            build_program_cache({"aot": {"root": 3}})

    def test_bad_aot_knobs_are_one_failed_status(self):
        """The conf-freeze contract end-to-end: a bad ``aot:`` block is
        ONE Failed status with the knob named, not a replica exploding
        at load (the PR 4/7/9 convention)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        cases = {
            "bad-aot-type": {"aot": ["/cache"]},
            "bad-aot-root": {"aot": {"root": ""}},
            "bad-aot-fsync": {"aot": {"root": "/cache", "fsync": 1}},
            "bad-aot-key": {"aot": {"root": "/cache", "roots": "/x"}},
        }
        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            for name, cfg in cases.items():
                cluster.store.create(InferenceService(
                    metadata=ObjectMeta(name=name),
                    spec=InferenceServiceSpec(predictor=ComponentSpec(
                        model_format=ModelFormat(name="llama-continuous"),
                        config={"params_ref": "mem://never-fetched",
                                **cfg}))))
            for name in cases:
                deadline = time.time() + 20
                isvc = None
                while time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    (name, isvc.status)
                assert "aot" in (isvc.status.message or ""), \
                    (name, isvc.status.message)


# -- engine parity --------------------------------------------------------


VARIANTS = {
    # chunked prefill + paged pool: the serving default
    "chunked_paged": dict(decode_chunk=2, block_size=16),
    # speculative decode rides the verify/fused-verify rungs
    "spec": dict(decode_chunk=1, block_size=16, spec_k=2),
}


class TestEngineParity:
    @pytest.mark.parametrize("variant", sorted(VARIANTS))
    def test_warm_boot_bit_identical_with_zero_recompiles(
            self, tiny_llama, tmp_path, variant):
        """The headline parity bar: greedy output is bit-identical
        cache-off vs cache-on-cold (publishes) vs cache-on-warm (loads
        everything), the warm boot is all hits / zero misses, and the
        recompiles==0 + zero-leak ledgers hold throughout."""
        kw = VARIANTS[variant]
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8]]

        off = make_engine(tiny_llama, **kw)
        try:
            off.warmup()
            want = [off.generate(p, max_new_tokens=6) for p in prompts]
            st = off.stats()
            # cache-off engines still expose the counter family (all
            # zero) so dashboards never see a hole
            assert st["aot_cache_hits_total"] == 0
            assert st["aot_cache_misses_total"] == 0
            assert st["jit_recompiles_total"] == 0
        finally:
            off.stop()

        cold_cache = ProgramArtifactCache(str(tmp_path), fsync=False)
        cold = make_engine(tiny_llama, program_cache=cold_cache, **kw)
        try:
            cold.warmup()
            got_cold = [cold.generate(p, max_new_tokens=6)
                        for p in prompts]
            st = cold.stats()
            assert st["aot_cache_misses_total"] > 0
            assert st["aot_cache_published_total"] \
                == st["aot_cache_misses_total"]
            assert st["aot_cache_hits_total"] == 0
            assert st["jit_recompiles_total"] == 0
            assert st["kv_blocks_leaked_total"] == 0
        finally:
            cold.stop()

        warm_cache = ProgramArtifactCache(str(tmp_path), fsync=False)
        warm = make_engine(tiny_llama, program_cache=warm_cache, **kw)
        try:
            warm.warmup()
            got_warm = [warm.generate(p, max_new_tokens=6)
                        for p in prompts]
            st = warm.stats()
            assert st["aot_cache_hits_total"] > 0
            assert st["aot_cache_misses_total"] == 0, st
            assert st["jit_recompiles_total"] == 0
            assert st["kv_blocks_leaked_total"] == 0
        finally:
            warm.stop()

        assert got_cold == want, variant
        assert got_warm == want, variant

    def test_tp_warm_boot_matches_cold(self, tmp_path):
        """Gang parity, in-process: a TP=2 engine warmed from the
        artifacts a prior TP=2 engine published produces bit-identical
        greedy output with zero misses — exactly what gang followers do
        against the leader's shared root."""
        cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
        params = llamalib.Llama(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        kw = dict(num_slots=2, decode_chunk=2, prefix_cache=False,
                  block_size=16, seq_buckets=[32],
                  mesh_axes={"model": 2})
        leader = ContinuousEngine(
            cfg, params,
            program_cache=ProgramArtifactCache(str(tmp_path),
                                               fsync=False), **kw)
        try:
            leader.warmup()
            want = leader.generate([1, 2, 3], max_new_tokens=6)
        finally:
            leader.stop()

        follower = ContinuousEngine(
            cfg, params,
            program_cache=ProgramArtifactCache(str(tmp_path),
                                               fsync=False), **kw)
        try:
            follower.warmup()
            st = follower.stats()
            assert st["aot_cache_hits_total"] > 0
            assert st["aot_cache_misses_total"] == 0, st
            assert follower.generate([1, 2, 3], max_new_tokens=6) == want
            assert follower.stats()["jit_recompiles_total"] == 0
        finally:
            follower.stop()

    def test_torn_artifact_degrades_to_compile(self, tiny_llama,
                                               tmp_path):
        """Seeded chaos end-to-end: a publish-time tear (spill_torn)
        leaves one artifact torn on disk; the next boot DETECTS it at
        load, recompiles that rung, republishes, and serves identical
        tokens — never a crash."""
        kw = dict(decode_chunk=2, block_size=16)
        plan = FaultPlan(seed=3).spill_torn()
        seeder_cache = ProgramArtifactCache(str(tmp_path), fsync=False,
                                            chaos=plan)
        seeder = make_engine(tiny_llama, program_cache=seeder_cache,
                             **kw)
        try:
            seeder.warmup()
            want = seeder.generate([1, 2, 3], max_new_tokens=6)
            published = seeder_cache.stats()[
                "aot_cache_published_total"]
            assert published > 0
        finally:
            seeder.stop()

        cache = ProgramArtifactCache(str(tmp_path), fsync=False)
        eng = make_engine(tiny_llama, program_cache=cache, **kw)
        try:
            eng.warmup()
            st = eng.stats()
            # exactly one rung was torn: detected + recompiled, the
            # rest loaded clean
            assert st["aot_cache_load_failures_total"] == 1, st
            assert st["aot_cache_misses_total"] == 1, st
            assert st["aot_cache_hits_total"] == published - 1, st
            assert st["aot_cache_published_total"] == 1  # replaced
            assert eng.generate([1, 2, 3], max_new_tokens=6) == want
            assert eng.stats()["jit_recompiles_total"] == 0
            assert eng.stats()["kv_blocks_leaked_total"] == 0
        finally:
            eng.stop()


# -- warmup trace + /metrics exposition -----------------------------------


def _get(url: str, timeout: float = 30.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


class TestWarmupObservability:
    def test_warmup_trace_and_aot_metrics_on_server(self, tiny_llama,
                                                    tmp_path):
        """Satellite 2 + the exposition lint: the ``engine.warmup``
        trace (per-family compile/artifact-load spans) lands on
        /traces, its phase feeds ``kft_phase_seconds``, and the
        ``kft_aot_cache_*`` counters ride /metrics promtool-clean."""
        from tests.test_observability import prom_lint

        from kubeflow_tpu.serving.server import ModelServer
        from kubeflow_tpu.serving.storage import register_mem
        from kubeflow_tpu.serving.text import TextGenerator

        ref = register_mem("aot-observability", tiny_llama)
        srv = ModelServer()
        srv.register(TextGenerator("m", {
            "params_ref": ref, "tokenizer": "bytes",
            "num_slots": 2, "decode_chunk": 2, "block_size": 16,
            "max_new_tokens": 4,
            "aot": {"root": str(tmp_path), "fsync": False},
            "tracing": {"sample": 1.0, "ring": 8},
        }))
        srv.start()
        try:
            deadline = time.time() + 10
            rows = []
            while time.time() < deadline and not rows:
                rows = [json.loads(ln) for ln in _get(
                    srv.url + "/traces").splitlines()]
                time.sleep(0.05)
            warm = [r for r in rows
                    if r.get("root", {}).get("name") == "warmup"]
            assert warm, rows
            tr = warm[0]
            assert [p["name"] for p in tr["phases"]] == ["engine.warmup"]
            # per-family rung spans: every span is a compile or an
            # artifact load, tagged with its program family
            assert tr["spans"], tr
            assert all(s["name"] in ("warmup.compile", "warmup.aot.load")
                       for s in tr["spans"])
            assert all(s["attrs"].get("family") for s in tr["spans"])
            # cold root: every rung compiled + published
            assert tr["meta"]["aot_misses"] > 0
            assert tr["meta"]["aot_hits"] == 0

            text = _get(srv.url + "/metrics")
            assert 'kft_aot_cache_misses_total{model="m"}' in text
            assert 'kft_aot_cache_hits_total{model="m"} 0' in text
            assert 'kft_aot_cache_bytes{model="m"}' in text
            assert ('kft_phase_seconds_count{model="m",'
                    'phase="engine.warmup"} 1') in text
            assert prom_lint(text) == [], prom_lint(text)[:5]
        finally:
            srv.stop()


# -- the autoscaler's warm-path budget ------------------------------------


class TestColdStartWarmEwma:
    def test_warm_samples_feed_their_own_ewma(self):
        from kubeflow_tpu.serving.autoscale import (
            AutoscalePolicy,
            ClusterAutoscaler,
        )

        auto = ClusterAutoscaler(AutoscalePolicy(), sensors=dict)
        auto.note_cold_start(10.0)
        assert auto.cold_start_s == pytest.approx(10.0)
        assert auto.cold_start_warm_s == 0.0  # untouched by cold builds
        auto.note_cold_start(2.0, warm=True)
        # the warm sample feeds BOTH: the blended EWMA stays the
        # worst-case ledger, the warm EWMA becomes the gate's budget
        assert auto.cold_start_warm_s == pytest.approx(2.0)
        assert auto.cold_start_s < 10.0
        s = auto.stats()
        assert s["autoscale_cold_start_warm_s"] == pytest.approx(2.0)
        assert any(ln.startswith("kft_autoscale_cold_start_warm_s")
                   for ln in auto.metrics_lines())

    def test_gate_prefers_the_warm_budget_once_measured(self, monkeypatch):
        """Scale-to-zero is held to the budget the next wake will
        actually pay: after one warm-tagged sample, ``tick`` fills the
        cold_start_s signal from the warm EWMA.  tick() copies the
        sensor dict, so observe the signal decide() actually sees."""
        from kubeflow_tpu.serving import autoscale as asl

        seen = []
        real_decide = asl.decide

        def spy(sig, policy):
            seen.append(dict(sig))
            return real_decide(sig, policy)

        monkeypatch.setattr(asl, "decide", spy)
        sensors = lambda: {"replicas": 1, "min_replicas": 0,
                           "max_replicas": 2, "util": 1.0}
        auto = asl.ClusterAutoscaler(asl.AutoscalePolicy(), sensors=sensors)
        auto.note_cold_start(30.0)
        auto.tick(now=1.0)
        assert seen[-1]["cold_start_s"] == pytest.approx(30.0)
        auto.note_cold_start(2.0, warm=True)
        auto.tick(now=2.0)
        assert seen[-1]["cold_start_s"] == pytest.approx(2.0)

    def test_controller_wake_warm_derivation(self):
        """``_wake_was_warm``: warm iff every engine that exposes the
        cache counters booted all-hits/no-misses; cache-off fleets and
        any compiling replica stay on the cold budget."""
        from kubeflow_tpu.serving.controller import (
            InferenceServiceController,
        )

        class _Eng:
            def __init__(self, st):
                self._st = st

            def stats(self):
                return self._st

        class _Srv:
            def __init__(self, *stats):
                self._e = {f"m{i}": _Eng(s)
                           for i, s in enumerate(stats)}

            def engines(self):
                return self._e

        warm = _Srv({"aot_cache_hits_total": 5,
                     "aot_cache_misses_total": 0})
        cold = _Srv({"aot_cache_hits_total": 0,
                     "aot_cache_misses_total": 5})
        mixed = _Srv({"aot_cache_hits_total": 3,
                      "aot_cache_misses_total": 2})
        nocache = _Srv({"jit_recompiles_total": 0})
        fn = InferenceServiceController._wake_was_warm
        assert fn([warm]) is True
        assert fn([cold]) is False
        assert fn([mixed]) is False
        assert fn([nocache]) is False  # no cache anywhere: cold budget
        assert fn([warm, cold]) is False  # one compiling replica gates
