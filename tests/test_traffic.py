"""Traffic plane (ISSUE 9): prefix-affinity routing, per-tenant QoS,
priority preemption, overload shedding.

Four layers, matching the tentpole:

- UNITS: token bucket, chained block-content keys, the affinity map,
  and the plane's admission decisions (rate shed / queue_full shed /
  bounded queue wait) — host-side stdlib, no model;
- ENGINE: priority-sorted admission and the PREEMPT-AND-REQUEUE parity
  satellite — a preempted-then-resumed sequence emits bit-identical
  greedy tokens vs never-preempted across plain/chunked/spec paged
  variants, with ``jit_recompiles_total == 0`` and zero leaked blocks;
- HTTP DOOR: ModelServer sheds with explicit 429 + ``Retry-After`` + a
  structured reason, /metrics exports the plane's gauges, and the
  Router answers empty pools with 503 + ``Retry-After`` (satellite),
  exposes per-backend counters, and routes shared prefixes to the
  replica already holding their blocks;
- CONTROL PLANE + CHAOS: bad ``qos`` is ONE Failed status at ISvc
  conf-freeze and on the Profile (PR 4/7 convention), and a seeded
  replica kill mid-storm (``FaultPlan.replica_kill_mid_storm``) leaves
  every request terminal (429/5xx, never a hang) with affinity
  re-routed to the survivors.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.serving.continuous import ContinuousEngine
from kubeflow_tpu.serving.paged import block_keys
from kubeflow_tpu.serving.traffic import (
    PrefixAffinity,
    TokenBucket,
    TrafficPlane,
    priority_tier,
    validate_qos,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    return cfg, params["params"]


LONG = list(range(1, 65))  # 64 tokens = 4 blocks at block_size 16
HIGH = [9, 8, 7]


def post(url: str, payload: dict, headers=None, timeout: float = 60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, dict(e.headers), body


# -- units ---------------------------------------------------------------


class TestQosValidation:
    def test_tiers_and_classes(self):
        classes = validate_qos({
            "gold": {"rate": 10, "priority": "high", "max_concurrent": 4},
            "bulk": {"priority": "low", "queue_depth": 2},
        })
        assert classes["gold"].priority == 0
        assert classes["bulk"].priority == 2
        assert priority_tier("normal") == 1 and priority_tier(2) == 2

    @pytest.mark.parametrize("bad", [
        {"x": {"rate": -1}},                  # negative rate
        {"x": {"priority": "urgent"}},        # unknown tier
        {"x": {"priority": 7}},               # out-of-range tier int
        {"x": {"max_concurrent": -2}},
        {"x": {"queue_depth": -1}},
        {"x": {"burst": 0}},
        {"x": {"bogus_field": 1}},
        {"x": {"rate": None}},                # wrong TYPE, not just
        {"x": {"priority": [1]}},             # wrong value: must be
        {"x": {"max_concurrent": "lots"}},    # ValueError, never a
        {"x": "not-a-mapping"},               # TypeError escaping to
        "not-a-mapping",                      # the reconcile loop
    ])
    def test_rejections(self, bad):
        with pytest.raises(ValueError):
            validate_qos(bad)


class TestTokenBucket:
    def test_deplete_and_refill(self):
        b = TokenBucket(rate=50, burst=2)
        assert b.try_take() == 0.0 and b.try_take() == 0.0
        wait = b.try_take()
        assert 0 < wait <= 0.02 + 1e-3
        time.sleep(wait + 0.005)
        assert b.try_take() == 0.0

    def test_zero_rate_is_unlimited(self):
        b = TokenBucket(rate=0, burst=1)
        assert all(b.try_take() == 0.0 for _ in range(100))


class TestBlockKeys:
    def test_chained_content_identity(self):
        a = block_keys(list(range(64)), 16)
        b = block_keys(list(range(64)), 16)
        c = block_keys(list(range(32)) + [999] * 32, 16)
        assert a == b and len(a) == 4
        # chains agree exactly through the shared prefix blocks
        assert a[:2] == c[:2] and a[2] != c[2]
        # partial trailing block contributes no key
        assert len(block_keys(list(range(17)), 16)) == 1

    def test_affinity_deepest_first_and_forget(self):
        aff = PrefixAffinity()
        a = block_keys(list(range(64)), 16)
        aff.observe(a, "r1")
        backend, depth = aff.best(a, ["r1", "r2"])
        assert (backend, depth) == ("r1", 4)
        # a diverged branch still matches its shared chain prefix
        c = block_keys(list(range(32)) + [999] * 32, 16)
        assert aff.best(c, ["r1", "r2"]) == ("r1", 2)
        aff.forget("r1")
        assert aff.best(a, ["r1", "r2"]) == (None, 0)


class TestSessionAffinity:
    """ISSUE 12 units: the durable-session map and its routing rank."""

    def test_observe_best_forget(self):
        from kubeflow_tpu.serving.traffic import SessionAffinity

        sa = SessionAffinity(capacity=2)
        sa.observe("a", "b1")
        sa.observe("b", "b2")
        assert sa.best("a", ["b1", "b2"]) == "b1"
        assert sa.best("a", ["b2"]) is None  # dead candidate filtered
        sa.observe("c", "b1")  # capacity 2: oldest OBSERVATION evicts
        assert sa.best("a", ["b1", "b2"]) is None  # "a" rolled off
        assert sa.best("b", ["b1", "b2"]) == "b2"
        sa.forget("b2")
        assert sa.best("b", ["b1", "b2"]) is None
        assert sa.best("", ["b1"]) is None  # no session id: no claim

    def test_route_session_outranks_prefix_affinity(self):
        plane = TrafficPlane({})
        keys = block_keys(LONG, 32)
        # prefix affinity learned b2; the session lives on b1
        plane.affinity.observe(keys, "b2")
        plane.sessions.observe("s", "b1")
        b, _ = plane.route(keys, ["b1", "b2"], load=lambda x: 0,
                           session="s")
        assert b == "b1"
        # the session route TEACHES the prefix map: its KV (prompt
        # prefix included) now lives where the session resumed, so
        # sessionless same-prefix traffic follows it there
        b2, _ = plane.route(keys, ["b1", "b2"], load=lambda x: 0)
        assert b2 == "b1"

    def test_route_learns_session_on_first_sight(self):
        plane = TrafficPlane({})
        b, _ = plane.route([], ["u1", "u2"],
                           load=lambda x: {"u1": 3, "u2": 0}[x],
                           session="fresh")
        assert b == "u2"  # least-loaded on the miss
        b2, _ = plane.route([], ["u1", "u2"],
                            load=lambda x: {"u1": 0, "u2": 9}[x],
                            session="fresh")
        assert b2 == "u2"  # sticky even when busier: a thaw costs more


class TestPlaneDoor:
    def test_rate_shed_carries_retry_after(self):
        plane = TrafficPlane({"t": {"rate": 1, "burst": 1}})
        assert plane.acquire("t").ok
        shed = plane.acquire("t")
        assert not shed.ok and shed.reason == "rate_limited"
        assert shed.retry_after > 0

    def test_charge_rate_false_skips_bucket(self):
        plane = TrafficPlane({"t": {"rate": 1, "burst": 1}})
        assert plane.acquire("t").ok
        # the router already charged this tenant's bucket upstream
        assert plane.acquire("t", charge_rate=False).ok

    def test_bounded_queue_waits_then_sheds(self):
        plane = TrafficPlane(
            {"t": {"max_concurrent": 1, "queue_depth": 1}})
        first = plane.acquire("t")
        assert first.ok
        # queue_depth 1: one waiter allowed; a release lets it through
        got = []

        def waiter():
            got.append(plane.acquire("t", wait_timeout=10.0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        deadline = time.monotonic() + 5
        while plane.stats()["classes"]["t"]["qos_waiting"] != 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # the queue is FULL now: the next acquire sheds immediately
        shed = plane.acquire("t", wait_timeout=0.0)
        assert not shed.ok and shed.reason == "queue_full"
        plane.release(first)
        th.join(timeout=5)
        assert got and got[0].ok
        # and a waiter that never gets a slot times out with a shed
        timed = plane.acquire("t", wait_timeout=0.05)
        assert not timed.ok and timed.reason == "queue_timeout"

    def test_freed_slot_goes_to_the_queued_waiter_first(self):
        """FIFO fairness: a fresh arrival must not snipe a freed slot
        from a waiter already queued for it (under sustained arrivals
        the waiters would otherwise starve to queue_timeout)."""
        plane = TrafficPlane(
            {"t": {"max_concurrent": 1, "queue_depth": 4}})
        first = plane.acquire("t")
        assert first.ok
        got = []

        def waiter():
            got.append(plane.acquire("t", wait_timeout=10.0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        deadline = time.monotonic() + 5
        while plane.stats()["classes"]["t"]["qos_waiting"] != 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        plane.release(first)
        sniper = plane.acquire("t", wait_timeout=0.0)
        assert not sniper.ok  # the queued waiter owns the freed slot
        th.join(timeout=5)
        assert got and got[0].ok

    def test_concurrency_shed_refunds_rate_token(self):
        """A queue_full/timeout shed did no work: the rate token it
        took must come back, or rejected requests drain the tenant's
        contracted admitted throughput."""
        plane = TrafficPlane({"t": {"rate": 0.5, "burst": 2,
                                    "max_concurrent": 1,
                                    "queue_depth": 0}})
        first = plane.acquire("t")
        assert first.ok  # bucket 2 -> 1, the one slot held
        shed = plane.acquire("t", wait_timeout=0.0)
        assert not shed.ok and shed.reason == "queue_full"
        plane.release(first)
        again = plane.acquire("t")  # the refunded token admits it
        assert again.ok, again.reason
        # and the bucket really is empty now (no over-refund)
        empty = plane.acquire("t")
        assert not empty.ok and empty.reason == "rate_limited"

    def test_affinity_overload_falls_through_at_two_replicas(self):
        """The hot-replica guard compares against the PEERS' mean —
        with the chosen backend's own load in the mean it could never
        fire at exactly 2 replicas."""
        plane = TrafficPlane({})
        keys = plane.prefix_keys(list(b"shared prefix " * 8))
        loads = {"r1": 0, "r2": 0}
        be, _ = plane.route(keys, ["r1", "r2"], load=loads.get)
        assert be == "r1"
        loads["r1"] = 10  # r1 melting, r2 idle
        be2, d2 = plane.route(keys, ["r1", "r2"], load=loads.get)
        assert be2 == "r2" and d2 == 0

    def test_unknown_tenant_falls_to_default_class(self):
        plane = TrafficPlane({"default": {"priority": "low"}})
        t = plane.acquire("whoever")
        assert t.ok and t.cls.name == "default" and t.priority == 2
        # no default class -> unlimited passthrough
        open_plane = TrafficPlane({"vip": {"priority": "high"}})
        assert open_plane.acquire("whoever").ok

    def test_credentialed_tenant_claim_requires_bearer(self):
        """A tenant whose Profile carries api_token must prove its
        claim — otherwise any client adopts a privileged class's rate
        and priority by naming it."""
        plane = TrafficPlane({"gold": {"priority": "high"}},
                             tenant_tokens={"gold": "s3cret"})
        assert not plane.authenticate("gold", None)
        assert not plane.authenticate("gold", "Bearer wrong")
        assert plane.authenticate("gold", "Bearer s3cret")
        assert plane.authenticate("anon", None)  # open tenant

    def test_prom_label_escaping(self):
        from kubeflow_tpu.serving.traffic import prom_label

        assert prom_label('team"a\\b\nc') == 'team\\"a\\\\b\\nc'


# -- engine layer --------------------------------------------------------


def make_engine(tiny_llama, **kw):
    cfg, params = tiny_llama
    kw.setdefault("num_slots", 4)
    kw.setdefault("decode_chunk", 2)
    kw.setdefault("prefix_cache", False)
    kw.setdefault("block_size", 16)
    return ContinuousEngine(cfg, params, **kw)


@pytest.fixture(scope="module")
def oracle(tiny_llama):
    eng = make_engine(tiny_llama)
    try:
        return {
            "long200": eng.generate(LONG, max_new_tokens=200),
            "high8": eng.generate(HIGH, max_new_tokens=8),
        }
    finally:
        eng.stop()


class TestPriorityAdmission:
    def test_high_tier_admits_before_queued_low(self, tiny_llama):
        """A saturated pool with queued low-priority work admits a
        later high-priority request first (stable sort: FIFO holds
        within a tier)."""
        eng = make_engine(tiny_llama, num_slots=1)
        try:
            hog = eng.submit(LONG, max_new_tokens=60, priority=1)
            lows = [eng.submit(LONG, max_new_tokens=4, priority=2)
                    for _ in range(2)]
            high = eng.submit(HIGH, max_new_tokens=4, priority=0)
            high.wait(120)
            # the high request finished while at least one low was
            # still queued behind it
            assert any(r.admitted_step < 0 or not r.done.is_set()
                       for r in lows)
            hog.wait(120)
            for r in lows:
                r.wait(120)
        finally:
            eng.stop()


class TestPreemptAndRequeueParity:
    """Satellite: preempted-then-resumed == never-preempted, across
    plain/chunked/spec paged variants, zero recompiles, zero leaks."""

    VARIANTS = {
        "plain": dict(),
        "chunked": dict(prefill_budget=16, decode_chunk=1),
        "spec": dict(spec_k=4, decode_chunk=1),
    }

    @pytest.mark.parametrize("variant", sorted(VARIANTS))
    def test_bit_identical_after_preemption(self, tiny_llama, variant):
        kw = dict(self.VARIANTS[variant])
        ref = make_engine(tiny_llama, **kw)
        try:
            want_long = ref.generate(LONG, max_new_tokens=200)
            want_high = ref.generate(HIGH, max_new_tokens=8)
        finally:
            ref.stop()
        nb = -(-(len(LONG) + 200) // 16)
        eng = make_engine(tiny_llama, num_slots=1, num_blocks=nb, **kw)
        eng.warmup()
        plane = TrafficPlane({})
        pre = plane.attach_engine(eng, preempt_after_s=0.01,
                                  poll_s=0.002)
        try:
            low = eng.submit(LONG, max_new_tokens=200, priority=2)
            deadline = time.time() + 120
            while len(low.tokens) < 4:
                assert time.time() < deadline, "victim never started"
                time.sleep(0.002)
            high = eng.submit(HIGH, max_new_tokens=8, priority=0)
            assert high.wait(240) == want_high
            assert low.wait(600) == want_long
            assert pre.preemptions_total >= 1, "preemption never fired"
            assert pre.resumes_total >= 1
            assert eng.stats()["jit_recompiles_total"] == 0
            # zero leaked blocks: the whole pool returns to free
            deadline = time.time() + 10
            while eng.stats()["kv_blocks_free"] != nb:
                assert time.time() < deadline, eng.stats()
                time.sleep(0.01)
        finally:
            plane.stop()
            eng.stop()

    def test_cancel_while_parked_resolves_and_frees(self, tiny_llama):
        nb = -(-(len(LONG) + 200) // 16)
        eng = make_engine(tiny_llama, num_slots=1, num_blocks=nb)
        eng.warmup()
        plane = TrafficPlane({})
        pre = plane.attach_engine(eng, preempt_after_s=0.01,
                                  poll_s=0.002)
        try:
            low = eng.submit(LONG, max_new_tokens=200, priority=2)
            deadline = time.time() + 120
            while len(low.tokens) < 4:
                assert time.time() < deadline
                time.sleep(0.002)
            high = eng.submit(HIGH, max_new_tokens=60, priority=0)
            deadline = time.time() + 120
            while pre.preemptions_total < 1:
                assert time.time() < deadline, "preemption never fired"
                time.sleep(0.005)
            low.cancel()  # client disconnects while parked
            high.wait(240)
            deadline = time.time() + 30
            while pre.parked() or eng.stats()["kv_blocks_free"] != nb:
                assert time.time() < deadline, (pre.parked(), eng.stats())
                time.sleep(0.01)
        finally:
            plane.stop()
            eng.stop()


# -- HTTP door -----------------------------------------------------------


@pytest.fixture(scope="module")
def text_ref(tiny_llama):
    from kubeflow_tpu.serving.storage import register_mem

    return register_mem("traffic-tests", tiny_llama)


def _server(text_ref, **cfg):
    from kubeflow_tpu.serving.server import ModelServer
    from kubeflow_tpu.serving.text import TextGenerator

    base = dict(params_ref=text_ref, tokenizer="bytes", num_slots=4,
                decode_chunk=2, block_size=16, prefix_cache=False,
                max_new_tokens=8, warmup_groups=[])
    base.update(cfg)
    srv = ModelServer()
    srv.register(TextGenerator("m", base))
    srv.start()
    return srv


class TestServerDoor:
    def test_shed_is_429_with_retry_after_and_reason(self, text_ref):
        srv = _server(
            text_ref,
            qos={"default": {"max_concurrent": 1, "queue_depth": 0}})
        try:
            url = srv.url + "/openai/v1/completions"
            release = threading.Event()
            statuses = []

            def slow():
                statuses.append(post(url, {
                    "model": "m", "prompt": "hello there friend",
                    "max_tokens": 128})[0])
                release.set()

            th = threading.Thread(target=slow, daemon=True)
            th.start()
            # wait until the slow request holds the one slot
            model = srv.models()["m"]
            deadline = time.time() + 30
            while model.traffic.stats()[
                    "classes"]["default"]["qos_live"] != 1:
                assert time.time() < deadline
                time.sleep(0.005)
            code, headers, body = post(
                url, {"model": "m", "prompt": "x", "max_tokens": 2})
            assert code == 429
            assert int(headers["Retry-After"]) >= 1
            assert body["reason"] in ("queue_full", "queue_timeout")
            assert body["qos_class"] == "default"
            th.join(timeout=120)
            assert statuses == [200]
            # the sheds are visible on /metrics — per-class counters
            # carry the class as a LABEL (tenant names are arbitrary
            # strings; in the metric name they'd break the exposition)
            with urllib.request.urlopen(srv.url + "/metrics") as r:
                text = r.read().decode()
            assert ('kft_traffic_qos_shed_total'
                    '{model="m",class="default"} 1') in text
            assert 'kft_traffic_qos_admitted_total{model="m"' in text
        finally:
            srv.stop()

    def test_rate_limit_shed(self, text_ref):
        srv = _server(text_ref,
                      qos={"default": {"rate": 0.001, "burst": 1}})
        try:
            url = srv.url + "/openai/v1/completions"
            assert post(url, {"model": "m", "prompt": "a",
                              "max_tokens": 2})[0] == 200
            code, headers, body = post(
                url, {"model": "m", "prompt": "b", "max_tokens": 2})
            assert code == 429 and body["reason"] == "rate_limited"
            assert body["retry_after"] > 0
        finally:
            srv.stop()

    def test_client_cannot_outrank_its_class(self, text_ref):
        """The class tier is the contract: a low-class tenant asking
        for "priority": "high" in the payload must reach the engine at
        its CLASS tier (self-demotion ok, self-promotion never —
        otherwise bulk traffic admits ahead of and preempts on behalf
        of gold)."""
        srv = _server(text_ref, qos={"bulk": {"priority": "low"}},
                      qos_preempt=False)
        try:
            model = srv.models()["m"]
            seen = []
            orig = model.engine.submit

            def spy(*a, **kw):
                seen.append(kw.get("priority"))
                return orig(*a, **kw)

            model.engine.submit = spy
            url = srv.url + "/openai/v1/completions"
            code, _, _ = post(url, {"model": "m", "prompt": "sneaky",
                                    "max_tokens": 2, "user": "bulk",
                                    "priority": "high"})
            assert code == 200
            assert seen == [2], seen  # the class's low tier won
            # a tenant the QoS door can NOT classify is capped at
            # normal — anonymous callers must not outrank the classed
            code, _, _ = post(url, {"model": "m", "prompt": "anon",
                                    "max_tokens": 2, "user": "nobody",
                                    "priority": "high"})
            assert code == 200
            assert seen[-1] == 1, seen
            # an invalid priority VALUE is a 400 client error, never a
            # mid-generation 500 inflating backend-error counters
            code, _, body = post(url, {"model": "m", "prompt": "x",
                                       "max_tokens": 2,
                                       "priority": "urgent"})
            assert code == 400 and "urgent" in body["error"]
        finally:
            srv.stop()

    def test_replica_door_enforces_tenant_credential(self, text_ref):
        """The Bearer contract holds at the replica door too — the
        class claim must not hinge on which door a client picked."""
        srv = _server(text_ref, qos={"gold": {"priority": "high"}},
                      qos_tenant_tokens={"gold": "tok"},
                      qos_preempt=False)
        try:
            url = srv.url + "/openai/v1/completions"
            code, _, body = post(url, {"model": "m", "prompt": "x",
                                       "max_tokens": 2, "user": "gold"})
            assert code == 401
            assert body["reason"] == "bad_tenant_credential"
            code2, _, _ = post(
                url, {"model": "m", "prompt": "x", "max_tokens": 2,
                      "user": "gold"},
                headers={"Authorization": "Bearer tok"})
            assert code2 == 200
        finally:
            srv.stop()

    def test_tenant_class_sets_engine_priority(self, text_ref):
        """The door's class priority reaches the engine request (the
        payload priority injection path)."""
        srv = _server(text_ref, qos={"vip": {"priority": "high"}})
        try:
            url = srv.url + "/openai/v1/completions"
            code, _, _ = post(url, {"model": "m", "prompt": "hi",
                                    "max_tokens": 2, "user": "vip"})
            assert code == 200
            eng = srv.models()["m"].engine
            # the engine's stats don't expose per-request priority;
            # assert through the plane's accounting instead
            assert srv.models()["m"].traffic.stats()[
                "classes"]["vip"]["qos_admitted_total"] == 1
            assert eng.stats()["jit_recompiles_total"] == 0
        finally:
            srv.stop()


class TestRouterDoor:
    def test_empty_backends_503_with_retry_after(self):
        from kubeflow_tpu.serving.controller import Router

        import kubeflow_tpu.serving.controller as ctl

        old = ctl.ACTIVATION_TIMEOUT
        ctl.ACTIVATION_TIMEOUT = 0.2
        router = Router(activate=lambda: None)
        try:
            code, headers, body = post(
                router.url + "/openai/v1/completions",
                {"model": "m", "prompt": "x"}, timeout=30)
            assert code == 503
            # jittered, load-aware Retry-After (ISSUE 16): no longer
            # the synchronized constant "1" — the header is a bounded
            # ceil of the jittered hint, the body carries the float
            assert 1 <= int(headers["Retry-After"]) <= 30
            assert body["retry_after"] > 0
            assert body["reason"] == "no_ready_replicas"
            # the failure is countable
            with urllib.request.urlopen(router.url + "/metrics") as r:
                text = r.read().decode()
            assert "kft_router_no_backend_total 1" in text
        finally:
            router.stop()
            ctl.ACTIVATION_TIMEOUT = old

    def test_credentialed_tenant_claim_401_at_router(self, text_ref):
        from kubeflow_tpu.serving.controller import Router

        srv = _server(text_ref)
        router = Router(activate=lambda: None)
        router.set_backends([srv.url])
        router.set_traffic(TrafficPlane(
            {"gold": {"priority": "high"}},
            tenant_tokens={"gold": "s3cret"}))
        try:
            url = router.url + "/openai/v1/completions"
            code, _, body = post(url, {"model": "m", "prompt": "x",
                                       "max_tokens": 2, "user": "gold"})
            assert code == 401
            assert body["reason"] == "bad_tenant_credential"
            code2, _, _ = post(
                url, {"model": "m", "prompt": "x", "max_tokens": 2,
                      "user": "gold"},
                headers={"Authorization": "Bearer s3cret"})
            assert code2 == 200
            # open tenants stay open
            code3, _, _ = post(url, {"model": "m", "prompt": "y",
                                     "max_tokens": 2})
            assert code3 == 200
        finally:
            router.stop()
            srv.stop()

    def test_session_affinity_sticks_and_survives_replica_death(
            self, text_ref):
        """ISSUE 12: a durable session's requests stick to one replica
        (warm KV) and, when that replica dies, re-route to a survivor
        instead of hanging — the storage tier makes ANY replica a valid
        thaw target, so the affinity is latency-only."""
        from kubeflow_tpu.serving.controller import Router

        s1 = _server(text_ref)
        s2 = _server(text_ref)
        router = Router(activate=lambda: None)
        router.set_backends([s1.url, s2.url])
        router.set_traffic(TrafficPlane({}))
        try:
            for i in range(3):
                code, _, _ = post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": f"turn {i}",
                     "max_tokens": 2, "session": "conv-77"})
                assert code == 200
            stats = router.backend_stats()
            assert [st["requests"] for st in stats.values()] == [3], stats
            assert router.traffic.sessions.hits_total >= 2
            # the sticky replica dies: the session re-routes, no hang
            sticky = next(iter(stats))
            victim = s1 if s1.url == sticky else s2
            survivor = s2 if victim is s1 else s1
            victim.stop()
            code, _, _ = post(
                router.url + "/openai/v1/completions",
                {"model": "m", "prompt": "turn 3", "max_tokens": 2,
                 "session": "conv-77"}, timeout=30)
            assert code == 200
            assert router.backend_stats()[survivor.url]["requests"] == 1
            # and the map now points at the survivor
            assert router.traffic.sessions.best(
                "conv-77", [s1.url, s2.url]) == survivor.url
        finally:
            router.stop()
            for srv in (s1, s2):
                try:
                    srv.stop()
                except Exception:
                    pass

    def test_session_header_routes_too(self, text_ref):
        """X-KFT-Session is the header spelling of the payload field."""
        from kubeflow_tpu.serving.controller import Router

        s1 = _server(text_ref)
        s2 = _server(text_ref)
        router = Router(activate=lambda: None)
        router.set_backends([s1.url, s2.url])
        router.set_traffic(TrafficPlane({}))
        try:
            for i in range(3):
                code, _, _ = post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": f"t {i}", "max_tokens": 2},
                    headers={"X-KFT-Session": "conv-h"})
                assert code == 200
            stats = router.backend_stats()
            assert [st["requests"] for st in stats.values()] == [3], stats
        finally:
            router.stop()
            s1.stop()
            s2.stop()

    def test_affinity_routes_shared_prefix_to_same_replica(
            self, text_ref):
        from kubeflow_tpu.serving.controller import Router

        s1 = _server(text_ref, prefix_cache=True, min_prefix=16)
        s2 = _server(text_ref, prefix_cache=True, min_prefix=16)
        router = Router(activate=lambda: None)
        router.set_backends([s1.url, s2.url])
        router.set_traffic(TrafficPlane({}, affinity_block=16))
        try:
            prefix = "shared system prompt " * 4  # > 4 blocks of 16
            for i in range(4):
                code, _, _ = post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": prefix + f"tail {i}",
                     "max_tokens": 2})
                assert code == 200
            stats = router.backend_stats()
            # all four same-prefix requests stuck to ONE replica (the
            # untouched peer never even gets a stats entry)
            assert [st["requests"] for st in stats.values()] == [4], stats
            assert router.traffic.affinity.hits_total >= 3
            # and the replica's block economy saw the prefix hits
            hits = sum(
                e.stats()["prefix_block_hits_total"]
                for srv in (s1, s2) for e in srv.engines().values())
            assert hits > 0
            # router /metrics carries the per-backend counters
            with urllib.request.urlopen(router.url + "/metrics") as r:
                text = r.read().decode()
            assert "kft_router_backend_requests" in text
            assert "kft_router_qos_affinity_hits_total" in text
        finally:
            router.stop()
            s1.stop()
            s2.stop()


# -- control plane: conf-freeze + Profile validation ---------------------


class TestConfFreeze:
    def test_bad_qos_is_one_failed_status(self):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServicePhase,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        cases = {
            "bad-rate": {"qos": {"gold": {"rate": -5}}},
            "bad-tier": {"qos": {"gold": {"priority": "urgent"}}},
            "bad-tenants": {"qos": {"gold": {"rate": 1}},
                            "qos_tenants": {"team": 7}},
            "bad-affinity": {"affinity_block": 0},
            # hierarchical-KV / durable-session knobs (ISSUE 12)
            "bad-hib-shape": {"hibernation": {"fsync": True}},
            "bad-hib-paged": {"hibernation": {"root": "/tmp/kvspill"}},
            "bad-host-wm": {"block_size": 16, "host_watermark": 2.5},
            "bad-host-paged": {"host_blocks": 8},
        }
        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            for name, cfg in cases.items():
                cluster.store.create(InferenceService(
                    metadata=ObjectMeta(name=name),
                    spec=InferenceServiceSpec(predictor=ComponentSpec(
                        model_format=ModelFormat(name="llama-continuous"),
                        config={"params_ref": "mem://never-fetched",
                                **cfg}))))
            for name in cases:
                deadline = time.time() + 20
                isvc = None
                while time.time() < deadline:
                    isvc = cluster.store.try_get("InferenceService", name)
                    if (isvc is not None and isvc.status.phase
                            == InferenceServicePhase.FAILED):
                        break
                    time.sleep(0.05)
                assert isvc is not None
                assert isvc.status.phase == InferenceServicePhase.FAILED, \
                    (name, isvc.status)
                needle = ("qos_tenants" if name == "bad-tenants"
                          else "affinity_block" if name == "bad-affinity"
                          else "hibernation" if name.startswith("bad-hib")
                          else "host_watermark" if name == "bad-host-wm"
                          else "host_blocks" if name == "bad-host-paged"
                          else "gold")
                assert needle in (isvc.status.message or ""), \
                    (name, isvc.status.message)

    def test_affinity_only_config_installs_plane(self, text_ref):
        """`affinity_block` with no qos classes is the affinity-only
        opt-in: the controller must still install a traffic plane on
        the router (regression: a phantom `prefix_affinity` knob once
        gated this and nothing ever set it)."""
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.inference import (
            ComponentSpec,
            InferenceService,
            InferenceServiceSpec,
            ModelFormat,
        )
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.add_tpu_slice("slice-0", 1, 4)
            cluster.enable_serving()
            cluster.store.create(InferenceService(
                metadata=ObjectMeta(name="affonly"),
                spec=InferenceServiceSpec(predictor=ComponentSpec(
                    model_format=ModelFormat(name="text-llm"),
                    config={"params_ref": text_ref, "tokenizer": "bytes",
                            "block_size": 16, "prefix_cache": True,
                            "min_prefix": 16, "affinity_block": 16,
                            "max_new_tokens": 4,
                            "warmup_groups": []}))))
            deadline = time.time() + 60
            isvc = None
            while time.time() < deadline:
                isvc = cluster.store.try_get("InferenceService", "affonly")
                if (isvc is not None and isvc.status.url
                        and isvc.status.phase.value == "Ready"):
                    break
                time.sleep(0.05)
            assert isvc is not None and isvc.status.url, isvc and isvc.status
            url = isvc.status.url
            prefix = "one shared prefix for the opt-in check " * 2
            for i in range(2):
                code, _, body = post(
                    url + "/openai/v1/completions",
                    {"model": "affonly", "prompt": prefix + str(i),
                     "max_tokens": 2}, timeout=120)
                assert code == 200, (code, body)
            with urllib.request.urlopen(url + "/metrics") as r:
                text = r.read().decode()
            # plane gauges present on the router == the plane installed
            assert "kft_router_qos_affinity_hits_total" in text
            hits = [ln for ln in text.splitlines()
                    if ln.startswith("kft_router_qos_affinity_hits_total")]
            assert int(hits[0].split()[-1]) >= 1, hits

    def test_profile_bad_qos_fails_profile_status(self):
        from kubeflow_tpu.api.common import ObjectMeta
        from kubeflow_tpu.api.platform import Profile, ProfileSpec
        from kubeflow_tpu.controlplane.cluster import Cluster

        with Cluster() as cluster:
            cluster.enable_platform_ux()
            cluster.store.create(Profile(
                metadata=ObjectMeta(name="team-bad"),
                spec=ProfileSpec(owner="x@corp",
                                 qos={"rate": -1})))
            cluster.store.create(Profile(
                metadata=ObjectMeta(name="team-good"),
                spec=ProfileSpec(owner="y@corp",
                                 qos={"rate": 5, "priority": "high"})))
            deadline = time.time() + 20
            bad = good = None
            while time.time() < deadline:
                bad = cluster.store.try_get("Profile", "team-bad")
                good = cluster.store.try_get("Profile", "team-good")
                if (bad and bad.status.phase == "Failed"
                        and good and good.status.phase == "Ready"):
                    break
                time.sleep(0.05)
            assert bad is not None and bad.status.phase == "Failed"
            assert "rate" in bad.status.message
            assert good is not None and good.status.phase == "Ready"


# -- seeded chaos: replica kill mid-storm --------------------------------


class TestReplicaKillMidStorm:
    def test_sheds_explicit_and_affinity_reroutes(self, text_ref):
        """Satellite: a seeded replica kill mid-storm — every request
        resolves (429 sheds stay explicit 429s, in-flight work on the
        corpse surfaces as a bounded error, nothing hangs) and
        same-prefix traffic re-routes to the survivor."""
        from kubeflow_tpu.chaos import FaultPlan
        from kubeflow_tpu.serving.controller import Router

        servers = [_server(text_ref, prefix_cache=True, min_prefix=16)
                   for _ in range(2)]
        # prime both replicas (first-request compile would otherwise
        # hold the door's 2 slots for seconds and shed the whole storm)
        for s in servers:
            code, _, _ = post(s.url + "/openai/v1/completions",
                              {"model": "m", "prompt": "warm",
                               "max_tokens": 2}, timeout=120)
            assert code == 200
        router = Router(activate=lambda: None)
        router.set_backends([s.url for s in servers])
        router.set_traffic(TrafficPlane(
            {"default": {"max_concurrent": 2, "queue_depth": 4}},
            affinity_block=16))
        plan = FaultPlan(seed=23).replica_kill_mid_storm(world=2, at=0.0)
        prefix = "the shared conversation prefix " * 3
        results = []
        lock = threading.Lock()
        try:
            plan.activate()
            threads = []

            def one(i):
                code, _, _ = post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": prefix + f"q{i}",
                     "max_tokens": 4}, timeout=120)
                with lock:
                    results.append((i, code, time.perf_counter()))

            killed = []
            kill_t = [None]
            for i in range(16):
                if i == 6:
                    for idx in plan.due_replica_kills():
                        servers[idx].stop()  # abrupt mid-storm death
                        killed.append(idx)
                    kill_t[0] = time.perf_counter()
                th = threading.Thread(target=one, args=(i,), daemon=True)
                th.start()
                threads.append(th)
                time.sleep(0.05)
            hung = 0
            for th in threads:
                th.join(timeout=120)
                hung += int(th.is_alive())
            assert hung == 0, "a request hung through the replica kill"
            assert len(killed) == 1  # the seeded member choice fired
            assert len(results) == 16
            # every outcome is explicit: 200s, QoS sheds (429), or a
            # bounded error from in-flight work on the corpse — and
            # the storm kept being SERVED after the kill: successes
            # keep completing past the kill instant (the survivor
            # carries queued + re-routed work; arrival index alone
            # would re-test queue fairness, not the re-route)
            codes = [c for _, c, _ in results]
            assert all(c in (0, 200, 429, 500, 502, 503)
                       for c in codes), results
            assert sum(1 for _, c, t in results
                       if c == 200 and t > kill_t[0]) >= 2, results
            # the survivor took the re-routed traffic
            survivor = servers[1 - killed[0]]
            stats = router.backend_stats()
            assert stats[survivor.url]["requests"] >= 4
            # affinity forgot the corpse: the dead url no longer wins
            keys = router.traffic.prefix_keys(list(prefix.encode()))
            best, _ = router.traffic.affinity.best(
                keys, [s.url for s in servers])
            assert best != servers[killed[0]].url
        finally:
            router.stop()
            for i, s in enumerate(servers):
                if i not in killed:
                    s.stop()
