"""Control-plane tests: store semantics, gang scheduling, the JaxJob
reconcile lifecycle (create -> gang admit -> run -> succeed/fail/restart).

The envtest-tier analog (SURVEY.md §4b): real store + real reconcilers +
scripted kubelet, no real processes.
"""

import re
import time
import urllib.request

import pytest

from kubeflow_tpu.api import JaxJob, ObjectMeta, ReplicaSpec, Container, Resources
from kubeflow_tpu.api.common import JobConditionType, RestartPolicy, has_condition
from kubeflow_tpu.api.jaxjob import KIND_JAXJOB
from kubeflow_tpu.controlplane import (
    Cluster,
    Conflict,
    FakeKubelet,
    KIND_POD,
    KIND_PODGROUP,
    PodGroupPhase,
    PodScript,
    Rejected,
    Store,
    events_for,
)
from kubeflow_tpu.controlplane.store import AlreadyExists, NotFound
from kubeflow_tpu.controlplane.objects import LABEL_JOB_NAME, Pod, PodPhase


def wait_for(fn, timeout=10.0, interval=0.02, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def make_job(name="job", replicas=2, tpu=0, **run_policy):
    return JaxJob(
        metadata=ObjectMeta(name=name),
        spec={
            "replica_specs": {
                "worker": ReplicaSpec(
                    replicas=replicas,
                    template=Container(resources=Resources(cpu=1, memory_gb=1, tpu=tpu)),
                )
            },
            "run_policy": run_policy,
        },
    )


class TestStore:
    def test_optimistic_concurrency(self):
        s = Store()
        job = s.create(make_job())
        stale = s.get(KIND_JAXJOB, "job")
        job.status.restart_count = 1  # real change: bumps rv
        s.update(job)
        stale.status.restart_count = 2
        with pytest.raises(Conflict):
            s.update(stale)

    def test_noop_update_does_not_bump_rv(self):
        """apiserver parity: an unchanged write is suppressed, so reconcile
        loops that rewrite identical status don't self-requeue forever."""
        s = Store()
        job = s.create(make_job())
        w = s.watch([KIND_JAXJOB])
        out = s.update(job)
        assert out.metadata.resource_version == job.metadata.resource_version
        assert w.q.qsize() == 0

    def test_watch_sees_lifecycle(self):
        s = Store()
        w = s.watch([KIND_JAXJOB])
        s.create(make_job())
        ev = w.q.get(timeout=1)
        assert ev.type == "ADDED" and ev.obj.metadata.name == "job"

    def test_admission_rejection(self):
        c = Cluster()
        bad = make_job(replicas=0)
        with pytest.raises(Rejected):
            c.store.create(bad)

    def test_admission_defaults_applied(self):
        c = Cluster()
        job = c.store.create(make_job(replicas=3))
        assert job.spec.run_policy.scheduling_policy.min_available == 3


class TestWorkQueue:
    def test_inflight_dedup_serializes_key(self):
        """client-go semantics: a key handed to a worker is not handed to
        a second worker until done(); adds meanwhile park and re-queue at
        done() — one failure can never be double-reconciled."""
        from kubeflow_tpu.controlplane import WorkQueue

        q = WorkQueue()
        q.add("ns/a")
        assert q.get(timeout=0.1) == "ns/a"
        q.add("ns/a")  # arrives while processing: parked, not handed out
        assert q.get(timeout=0.1) is None
        q.done("ns/a")  # flushes the parked add
        assert q.get(timeout=0.3) == "ns/a"
        q.done("ns/a")
        assert q.get(timeout=0.05) is None

    def test_done_without_dirty_is_noop(self):
        from kubeflow_tpu.controlplane import WorkQueue

        q = WorkQueue()
        q.add("ns/b")
        assert q.get(timeout=0.1) == "ns/b"
        q.done("ns/b")
        assert q.get(timeout=0.05) is None


class TestGangScheduler:
    def test_all_or_nothing(self):
        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=1, chips_per_host=4)  # capacity: 4 chips
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                # needs 2 pods x 4 chips = 8 chips > 4 available: nothing binds
                c.store.create(make_job(name="big", replicas=2, tpu=4))
                time.sleep(0.4)
                pods = c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "big"})
                assert len(pods) == 2
                assert all(p.spec.node_name is None for p in pods)
                pg = c.store.get(KIND_PODGROUP, "big")
                assert pg.status.phase == PodGroupPhase.PENDING
                # grow the cluster; the whole gang should now bind
                c.add_tpu_slice("s1", num_hosts=1, chips_per_host=4)
                wait_for(
                    lambda: all(
                        p.spec.node_name
                        for p in c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "big"})
                    ),
                    desc="gang bound",
                )
                pg = c.store.get(KIND_PODGROUP, "big")
                assert pg.status.phase == PodGroupPhase.RUNNING
                assert pg.status.admitted_time is not None
            finally:
                kubelet.stop()

    def test_slice_first_packing(self):
        c = Cluster()
        c.add_tpu_slice("sa", num_hosts=2, chips_per_host=4)
        c.add_tpu_slice("sb", num_hosts=2, chips_per_host=4)
        with c:
            c.store.create(make_job(name="packed", replicas=2, tpu=4))
            pods = wait_for(
                lambda: (
                    ps := c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "packed"})
                )
                and all(p.spec.node_name for p in ps)
                and ps,
                desc="pods bound",
            )
            # both pods should land on the SAME slice (ICI before DCN)
            slices = {p.spec.node_name.rsplit("-host-", 1)[0] for p in pods}
            assert len(slices) == 1


class TestJaxJobLifecycle:
    def run_cluster(self, script=None):
        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=4, chips_per_host=4)
        kubelet = FakeKubelet(c.store, script)
        return c, kubelet

    def _await_terminal(self, c, name, timeout=10.0):
        def check():
            job = c.store.try_get(KIND_JAXJOB, name)
            if job and (
                has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
                or has_condition(job.status.conditions, JobConditionType.FAILED)
            ):
                return job
            return None

        return wait_for(check, timeout=timeout, desc=f"{name} terminal")

    def test_happy_path_succeeds_with_gang_metric(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="ok", replicas=4, tpu=4))
                job = self._await_terminal(c, "ok")
                assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
                assert job.status.gang_startup_seconds is not None
                assert 0 <= job.status.gang_startup_seconds < 10
                assert job.status.replica_statuses["worker"].succeeded == 4
                reasons = [e.reason for e in events_for(c.store, KIND_JAXJOB, "ok")]
                assert "PodGroupCreated" in reasons and "JobSucceeded" in reasons
            finally:
                kubelet.stop()

    def test_env_injection(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="envs", replicas=2))
                pods = wait_for(
                    lambda: (
                        ps := c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "envs"})
                    )
                    and len(ps) == 2
                    and ps,
                    desc="pods created",
                )
                envs = {p.metadata.name: p.spec.container.env for p in pods}
                e0 = envs["envs-worker-0"]
                # default coordinator_port=0 -> controller allocates at gang
                # bind time and records the choice in status (r1 weak #6)
                job = c.store.get(KIND_JAXJOB, "envs")
                port = job.status.coordinator_port
                assert port and 0 < port < 65536
                assert (
                    e0["JAX_COORDINATOR_ADDRESS"]
                    == f"envs-worker-0.default.svc:{port}"
                )
                assert e0["JAX_NUM_PROCESSES"] == "2"
                assert e0["JAX_PROCESS_ID"] == "0"
                assert envs["envs-worker-1"]["JAX_PROCESS_ID"] == "1"
            finally:
                kubelet.stop()

    def test_nonworker_role_stays_out_of_collective(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                job = make_job(name="hetero", replicas=2)
                job.spec.replica_specs["dataset"] = ReplicaSpec(replicas=1)
                c.store.create(job)
                pods = wait_for(
                    lambda: (
                        ps := c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "hetero"})
                    )
                    and len(ps) == 3
                    and ps,
                    desc="pods created",
                )
                aux = next(p for p in pods if "dataset" in p.metadata.name)
                assert "JAX_NUM_PROCESSES" not in aux.spec.container.env
                assert "JAX_PROCESS_ID" not in aux.spec.container.env
            finally:
                kubelet.stop()

    def test_recreated_gang_member_schedules(self):
        """A single replacement pod of an already-admitted gang must bind
        even though it alone is smaller than min_member."""
        c, kubelet = self.run_cluster(lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="heal", replicas=3))
                wait_for(
                    lambda: all(
                        p.spec.node_name
                        for p in c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "heal"})
                    )
                    and len(c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "heal"})) == 3,
                    desc="gang bound",
                )
                c.store.delete(KIND_POD, "heal-worker-1")
                wait_for(
                    lambda: (
                        p := c.store.try_get(KIND_POD, "heal-worker-1")
                    )
                    and p.spec.node_name,
                    desc="replacement pod bound",
                )
            finally:
                kubelet.stop()

    def test_nonretryable_failure_fails_job(self):
        c, kubelet = self.run_cluster(
            lambda pod: PodScript(run_seconds=0.05, exit_code=1)
        )
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="boom", replicas=2))
                job = self._await_terminal(c, "boom")
                assert has_condition(job.status.conditions, JobConditionType.FAILED)
            finally:
                kubelet.stop()

    def test_retryable_failure_restarts_then_succeeds(self):
        fails = {"n": 0}

        def script(pod: Pod) -> PodScript:
            # first generation of worker-0 dies with a retryable code
            if pod.metadata.labels["replica-index"] == "0" and fails["n"] == 0:
                fails["n"] += 1
                return PodScript(run_seconds=0.05, exit_code=137)
            return PodScript(run_seconds=0.05)

        c, kubelet = self.run_cluster(script)
        with c:
            kubelet.start()
            try:
                job = make_job(name="retry", replicas=2, backoff_limit=2)
                job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
                c.store.create(job)
                job = self._await_terminal(c, "retry")
                assert has_condition(job.status.conditions, JobConditionType.SUCCEEDED)
                assert job.status.restart_count == 1
            finally:
                kubelet.stop()

    def test_restart_backoff_holds_pod_recreation(self):
        """A gang restart waits out the jittered backoff window before the
        new incarnation's pods exist — no fixed 0.05 s restart storm
        (ISSUE 1).  base=1.0 s with jitter in [0.5, 1.5) means no new pod
        sooner than 0.5 s after the restart decision."""
        fails = {"n": 0}

        def script(pod: Pod) -> PodScript:
            if pod.metadata.labels["replica-index"] == "0" and fails["n"] == 0:
                fails["n"] += 1
                return PodScript(run_seconds=0.05, exit_code=137)
            return PodScript(hang=True)

        c, kubelet = self.run_cluster(script)
        with c:
            kubelet.start()
            try:
                job = make_job(name="paced", replicas=2, backoff_limit=2,
                               restart_backoff_seconds=1.0)
                job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
                c.store.create(job)
                job = wait_for(
                    lambda: (j := c.store.get(KIND_JAXJOB, "paced"))
                    and j.status.last_restart_time and j,
                    desc="restart decided",
                )
                # inside the hold window: the old pods are gone and no new
                # incarnation exists yet
                time.sleep(0.25)
                assert not c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "paced"})
                pods = wait_for(
                    lambda: (
                        ps := c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "paced"})
                    )
                    and len(ps) == 2 and ps,
                    desc="new incarnation",
                )
                earliest = min(p.metadata.creation_timestamp for p in pods)
                assert earliest - job.status.last_restart_time >= 0.5
            finally:
                kubelet.stop()

    def test_backoff_limit_exhaustion(self):
        c, kubelet = self.run_cluster(
            lambda pod: PodScript(run_seconds=0.03, exit_code=137)
        )
        with c:
            kubelet.start()
            try:
                job = make_job(name="flappy", replicas=1, backoff_limit=1)
                job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
                c.store.create(job)
                job = self._await_terminal(c, "flappy")
                assert has_condition(job.status.conditions, JobConditionType.FAILED)
                assert job.status.restart_count == 1
            finally:
                kubelet.stop()

    def test_gang_schedule_timeout(self):
        c = Cluster()  # no nodes at all
        kubelet = FakeKubelet(c.store)
        with c:
            kubelet.start()
            try:
                job = make_job(name="stuck", replicas=2, tpu=4)
                job.spec.run_policy.scheduling_policy = None  # let defaulting fill it
                c.store.create(job)

                def set_timeout():
                    j = c.store.get(KIND_JAXJOB, "stuck")
                    j.spec.run_policy.scheduling_policy.schedule_timeout_seconds = 0.2
                    c.store.update(j)

                set_timeout()
                job = self._await_terminal(c, "stuck", timeout=10)
                failed = has_condition(job.status.conditions, JobConditionType.FAILED)
                assert failed
            finally:
                kubelet.stop()

    def test_suspend_deletes_pods(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="pause", replicas=2))
                wait_for(
                    lambda: len(c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "pause"})) == 2,
                    desc="pods up",
                )

                def suspend():
                    j = c.store.get(KIND_JAXJOB, "pause")
                    j.spec.run_policy.suspend = True
                    c.store.update(j)

                suspend()
                wait_for(
                    lambda: len(c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "pause"})) == 0,
                    desc="pods gone",
                )
                job = c.store.get(KIND_JAXJOB, "pause")
                assert has_condition(job.status.conditions, JobConditionType.SUSPENDED)
            finally:
                kubelet.stop()

    def test_ttl_deletes_job(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(run_seconds=0.02))
        with c:
            kubelet.start()
            try:
                job = make_job(name="ephemeral", replicas=1, ttl_seconds_after_finished=0.2)
                c.store.create(job)
                wait_for(
                    lambda: c.store.try_get(KIND_JAXJOB, "ephemeral") is None,
                    desc="job gc'd",
                )
            finally:
                kubelet.stop()

    def test_job_deletion_cleans_owned_objects(self):
        c, kubelet = self.run_cluster(lambda pod: PodScript(hang=True))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="gone", replicas=2))
                wait_for(
                    lambda: len(c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "gone"})) == 2,
                    desc="pods up",
                )
                c.store.delete(KIND_JAXJOB, "gone")
                wait_for(
                    lambda: not c.store.list(KIND_POD, labels={LABEL_JOB_NAME: "gone"})
                    and c.store.try_get(KIND_PODGROUP, "gone") is None,
                    desc="owned objects gc'd",
                )
            finally:
                kubelet.stop()


class TestReconcileMetrics:
    def test_metrics_exposed_after_reconciles(self):
        """SURVEY §5 tracing row: reconcile durations + queue depth are
        exported Prometheus-style per controller."""
        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=2, chips_per_host=4)
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(run_seconds=0.05))
        with c:
            kubelet.start()
            try:
                c.store.create(make_job(name="metered", replicas=2))
                job = c.store.try_get(KIND_JAXJOB, "metered")
                deadline = time.time() + 10
                while time.time() < deadline:
                    job = c.store.try_get(KIND_JAXJOB, "metered")
                    if job and has_condition(
                        job.status.conditions, JobConditionType.SUCCEEDED
                    ):
                        break
                    time.sleep(0.05)
                text = c.metrics_text()
                assert 'kft_reconcile_total{controller="JaxJob"}' in text
                total = int(re.search(
                    r'kft_reconcile_total\{controller="JaxJob"\} (\d+)', text
                ).group(1))
                assert total >= 3  # created -> running -> succeeded at least
                assert 'kft_reconcile_time_seconds_bucket{controller="JaxJob",le="+Inf"}' in text
                assert 'kft_workqueue_depth{controller="JaxJob"}' in text
                # HTTP surface
                url = c.serve_metrics()
                with urllib.request.urlopen(url, timeout=5) as resp:
                    assert resp.status == 200
                    assert b"kft_reconcile_total" in resp.read()
            finally:
                kubelet.stop()


class TestConcurrencyProperties:
    """SURVEY §5 race detection: property-style tests over concurrent
    store mutations and reconcile interleavings (the go test -race +
    expectations-pattern tier of the reference)."""

    def test_concurrent_rmw_never_loses_updates(self):
        """N threads x M conflicting read-modify-writes: every successful
        update is reflected in the final count (optimistic concurrency +
        retry = lossless), and failures are loud, never silent."""
        import threading  # noqa: F401 — used below

        s = Store()
        s.create(make_job(name="ctr"))
        succeeded = []
        lock = threading.Lock()

        def bump(o):
            o.status.restart_count += 1

        def worker():
            ok = 0
            for _ in range(20):
                while True:
                    try:
                        s.update_with_retry(KIND_JAXJOB, "ctr", "default", bump)
                        ok += 1
                        break
                    except Conflict:
                        continue  # retry-budget exhausted under contention
            with lock:
                succeeded.append(ok)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        final = s.get(KIND_JAXJOB, "ctr").status.restart_count
        assert final == sum(succeeded) == 160

    def test_randomized_churn_converges_without_reconcile_errors(self):
        """Seeded random interleaving of create/suspend/resume/resize/delete
        against live reconcilers + scheduler + kubelet: the system must
        converge (every surviving job terminal or consistently running,
        no orphaned pods) with zero reconcile exceptions."""
        import random

        rng = random.Random(1234)
        c = Cluster()
        c.add_tpu_slice("s0", num_hosts=4, chips_per_host=4)
        kubelet = FakeKubelet(c.store, lambda pod: PodScript(run_seconds=0.3))
        names = [f"churn-{i}" for i in range(5)]
        with c:
            kubelet.start()
            try:
                for name in names:
                    c.store.create(make_job(name=name, replicas=2))
                for _ in range(60):
                    name = rng.choice(names)
                    op = rng.choice(
                        ["suspend", "resume", "resize", "delete", "recreate", "noop"])
                    try:
                        if op == "delete":
                            c.store.try_delete(KIND_JAXJOB, name)
                        elif op == "recreate":
                            if c.store.try_get(KIND_JAXJOB, name) is None:
                                c.store.create(make_job(name=name, replicas=2))
                        elif op == "suspend":
                            c.store.update_with_retry(
                                KIND_JAXJOB, name, "default",
                                lambda o: setattr(o.spec.run_policy, "suspend", True))
                        elif op == "resume":
                            c.store.update_with_retry(
                                KIND_JAXJOB, name, "default",
                                lambda o: setattr(o.spec.run_policy, "suspend", False))
                        elif op == "resize":
                            n = rng.choice([1, 2, 3])
                            c.store.update_with_retry(
                                KIND_JAXJOB, name, "default",
                                lambda o: setattr(
                                    o.spec.replica_specs["worker"], "replicas", n))
                    except (Conflict, Rejected, AlreadyExists, NotFound):
                        pass  # racing an admission/terminal transition is fine
                    time.sleep(rng.uniform(0, 0.02))
                # resume everything and let the system settle
                for name in names:
                    try:
                        c.store.update_with_retry(
                            KIND_JAXJOB, name, "default",
                            lambda o: setattr(o.spec.run_policy, "suspend", False))
                    except (Conflict, Rejected, NotFound):
                        pass  # deleted mid-churn and never recreated

                def settled():
                    jobs = [c.store.try_get(KIND_JAXJOB, n) for n in names]
                    for j in jobs:
                        if j is None:
                            continue  # deleted mid-churn and never recreated
                        if not (
                            has_condition(j.status.conditions, JobConditionType.SUCCEEDED)
                            or has_condition(j.status.conditions, JobConditionType.FAILED)
                        ):
                            return None
                    return jobs

                jobs = wait_for(settled, timeout=60, desc="churned jobs terminal")
                # no reconcile exception escaped during the whole run
                jaxjob_ctrl = next(
                    ctl for ctl in c.controllers if ctl.kind == KIND_JAXJOB)
                assert jaxjob_ctrl.metrics.errors == 0
                # no orphaned pods: every pod's owner job still exists
                for p in c.store.list(KIND_POD):
                    owners = {r.name for r in p.metadata.owner_references}
                    assert owners & set(names), p.metadata.name
            finally:
                kubelet.stop()
