"""Run the five BASELINE workload examples end-to-end on the local
platform (the reference's stock-config parity demonstration).

Usage: JAX_PLATFORMS=cpu python examples/run_all.py [mnist resnet bert bo llm]
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.api.common import has_condition  # noqa: E402
from kubeflow_tpu.runtime.platform import LocalPlatform  # noqa: E402
from kubeflow_tpu.sdk import TrainingClient  # noqa: E402
from kubeflow_tpu.sdk.katib import KatibClient  # noqa: E402
from kubeflow_tpu.sdk.kserve import KServeClient  # noqa: E402


def run_job(platform, path):
    client = TrainingClient(platform)
    with open(path) as f:
        job = client.create_job(f.read())
    name = job.metadata.name
    job = client.wait_for_job_conditions(name, timeout=300)
    ok = has_condition(job.status.conditions, "Succeeded")
    print(f"  {name}: {'Succeeded' if ok else job.status.conditions[-1].type} "
          f"(gang startup {job.status.gang_startup_seconds:.2f}s)")
    assert ok


def run_bert(platform, path):
    from kubeflow_tpu.models import bert as bertlib
    from kubeflow_tpu.serving.storage import register_mem

    cfg = bertlib.tiny(num_classes=2)
    model = bertlib.BertClassifier(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    register_mem("examples-bert", (cfg, params))
    client = KServeClient(platform.cluster)
    with open(path) as f:
        client.create(f.read())
    client.wait_isvc_ready("bert-clf", timeout=120)
    probs = client.predict("bert-clf", [[1, 2, 3, 4]])[0]
    print(f"  bert-clf: Ready, P(classes)={[round(p, 3) for p in probs]}")


def run_bo(platform, path):
    from kubeflow_tpu.api.yaml_io import load_yaml_file

    client = KatibClient(platform)
    (exp,) = load_yaml_file(path)
    platform.store.create(exp)
    done = client.wait_for_experiment(exp.metadata.name, timeout=600)
    best = client.get_optimal_hyperparameters(exp.metadata.name)
    print(f"  {exp.metadata.name}: {done.status.trials_succeeded} trials, "
          f"best lr={float(best['assignments']['lr']):.4g} "
          f"score={best['value']:.4f}")


STEPS = {
    "mnist": ("01-jaxjob-mnist.yaml", run_job),
    "resnet": ("02-jaxjob-resnet-ddp.yaml", run_job),
    "bert": ("03-isvc-bert.yaml", run_bert),
    "bo": ("04-experiment-bo.yaml", run_bo),
    "llm": ("05-jaxjob-llm.yaml", run_job),
}


def main() -> None:
    want = sys.argv[1:] or list(STEPS)
    with LocalPlatform(num_hosts=1, chips_per_host=4) as p:
        for key in want:
            path, fn = STEPS[key]
            print(f"[{key}] {path}")
            fn(p, os.path.join(HERE, path))
    print("ALL EXAMPLES PASSED")


if __name__ == "__main__":
    main()
