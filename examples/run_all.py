"""Run the five BASELINE workload examples end-to-end on the local
platform (the reference's stock-config parity demonstration).

Usage: JAX_PLATFORMS=cpu python examples/run_all.py [mnist resnet bert bo
llm lora gang]
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import jax.numpy as jnp  # noqa: E402

from kubeflow_tpu.api.common import has_condition  # noqa: E402
from kubeflow_tpu.runtime.platform import LocalPlatform  # noqa: E402
from kubeflow_tpu.sdk import TrainingClient  # noqa: E402
from kubeflow_tpu.sdk.katib import KatibClient  # noqa: E402
from kubeflow_tpu.sdk.kserve import KServeClient  # noqa: E402


def run_job(platform, path):
    client = TrainingClient(platform)
    with open(path) as f:
        job = client.create_job(f.read())
    name = job.metadata.name
    job = client.wait_for_job_conditions(name, timeout=300)
    ok = has_condition(job.status.conditions, "Succeeded")
    print(f"  {name}: {'Succeeded' if ok else job.status.conditions[-1].type} "
          f"(gang startup {job.status.gang_startup_seconds:.2f}s)")
    assert ok


def run_bert(platform, path):
    from kubeflow_tpu.models import bert as bertlib
    from kubeflow_tpu.serving.storage import register_mem

    cfg = bertlib.tiny(num_classes=2)
    model = bertlib.BertClassifier(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    register_mem("examples-bert", (cfg, params))
    client = KServeClient(platform.cluster)
    with open(path) as f:
        client.create(f.read())
    client.wait_isvc_ready("bert-clf", timeout=120)
    probs = client.predict("bert-clf", [[1, 2, 3, 4]])[0]
    print(f"  bert-clf: Ready, P(classes)={[round(p, 3) for p in probs]}")


def run_bo(platform, path):
    from kubeflow_tpu.api.yaml_io import load_yaml_file

    client = KatibClient(platform)
    (exp,) = load_yaml_file(path)
    platform.store.create(exp)
    done = client.wait_for_experiment(exp.metadata.name, timeout=600)
    best = client.get_optimal_hyperparameters(exp.metadata.name)
    print(f"  {exp.metadata.name}: {done.status.trials_succeeded} trials, "
          f"best lr={float(best['assignments']['lr']):.4g} "
          f"score={best['value']:.4f}")


def run_lora(platform, _path):
    """r5 UX: fine-tune a published snapshot with LoRA adapters on a
    2-worker gang, publish the MB-scale adapter, serve base + adapter
    merged — the reference's peft train() -> serve loop."""
    import tempfile

    from flax import linen as nn

    from kubeflow_tpu.models import llama as llamalib

    root = tempfile.mkdtemp(prefix="lora-demo-")
    cfg = llamalib.tiny()
    params = nn.meta.unbox(llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    base = os.path.join(root, "base")
    llamalib.save_pretrained(base, cfg, params)
    adapter = os.path.join(root, "adapter")

    client = TrainingClient(platform)
    job = client.train(
        name="lora-demo", entrypoint="kubeflow_tpu.train.llm:train_main",
        num_workers=2, model=f"file://{base}", lora_rank=8,
        publish_to=adapter,
        env={"KFT_STEPS": "3", "KFT_BATCH": "8", "KFT_SEQ_LEN": "16",
             "KFT_LOG_EVERY": "1"},
        timeout=300)
    assert has_condition(job.status.conditions, "Succeeded")
    kb = os.path.getsize(os.path.join(adapter, "adapter.msgpack")) / 1024
    print(f"  lora-demo: Succeeded, adapter artifact {kb:.0f} KiB")

    ks = KServeClient(platform.cluster)
    ks.create(f"""
kind: InferenceService
metadata:
  name: lora-serve
spec:
  predictor:
    handler: kubeflow_tpu.serving.continuous:ContinuousLlamaGenerator
    storage_uri: file://{base}
    config:
      adapter_path: {adapter}
      num_slots: 2
      decode_chunk: 2
      max_new_tokens: 4
      warmup_groups: []
""")
    ks.wait_isvc_ready("lora-serve", timeout=180)
    toks = ks.predict("lora-serve", [[1, 2, 3]])[0]
    print(f"  lora-serve: Ready (base+adapter merged), tokens={toks}")


def run_gang(platform, _path):
    """r5: a tensor-parallel predictor spanning TWO host processes,
    placed and restarted as a JaxJob (predictor.gang)."""
    import tempfile

    from flax import linen as nn

    from kubeflow_tpu.models import llama as llamalib

    cfg = llamalib.tiny(num_heads=8, num_kv_heads=8)
    params = nn.meta.unbox(llamalib.Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"])
    snap = os.path.join(tempfile.mkdtemp(prefix="gang-demo-"), "snap")
    llamalib.save_pretrained(snap, cfg, params)
    ks = KServeClient(platform.cluster)
    ks.create(f"""
kind: InferenceService
metadata:
  name: gang-serve
spec:
  predictor:
    handler: kubeflow_tpu.serving.continuous:ContinuousLlamaGenerator
    storage_uri: file://{snap}
    gang:
      hosts: 2
      mesh_axes: {{model: 8}}
      chips_per_host: 4
    config:
      num_slots: 2
      decode_chunk: 2
      max_new_tokens: 4
      seq_buckets: [32]
      prefix_cache: false
      warmup_groups: [[1, 32]]
""")
    ks.wait_isvc_ready("gang-serve", timeout=300)
    toks = ks.predict("gang-serve", [[1, 2, 3]])[0]
    print(f"  gang-serve: Ready (TP=8 across 2 host processes), "
          f"tokens={toks}")


STEPS = {
    "mnist": ("01-jaxjob-mnist.yaml", run_job),
    "resnet": ("02-jaxjob-resnet-ddp.yaml", run_job),
    "bert": ("03-isvc-bert.yaml", run_bert),
    "bo": ("04-experiment-bo.yaml", run_bo),
    "llm": ("05-jaxjob-llm.yaml", run_job),
    "lora": (None, run_lora),
    "gang": (None, run_gang),
}


def main() -> None:
    want = sys.argv[1:] or list(STEPS)
    with LocalPlatform(num_hosts=2, chips_per_host=4) as p:
        for key in want:
            path, fn = STEPS[key]
            print(f"[{key}] {path or fn.__name__}")
            fn(p, os.path.join(HERE, path) if path else None)
    print("ALL EXAMPLES PASSED")


if __name__ == "__main__":
    main()
