"""Tracing overhead on the serving storm: p50 ITL at sample {0, 0.1, 1}.

The ISSUE 13 acceptance bar: ``sample=0`` must add no measurable
overhead (and allocate nothing on the dispatch path — the unit test
pins the no-spans half), and full sampling (``sample=1.0``) must stay
under ~3% on storm p50 inter-token latency.  This bench measures it
the way the serving storm benches do: a closed-loop burst of
mixed-length conversations on one paged engine (tiny-model CPU
stand-in — ratios, not absolutes; re-validate on chip per the ROADMAP
rule), per-token arrival times sampled by a poller thread, one JSON
row per sample rate plus a summary row with the overhead ratios.

Usage: python scripts/trace_bench.py [streams] [new_tokens] [seed]
"""

from __future__ import annotations

import json
import sys
import threading
import time

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models import llama as llamalib  # noqa: E402
from kubeflow_tpu.serving.continuous import ContinuousEngine  # noqa: E402
from kubeflow_tpu.serving.trace import Tracer  # noqa: E402


from kubeflow_tpu.utils.stats import pct as _pct  # noqa: E402


def _storm(eng, tracer, streams: int, new_tokens: int, seed: int):
    """One closed-loop burst; returns per-token ITLs (ms) across all
    streams (token arrivals sampled by a poller, chunk-normalized)."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, eng.cfg.vocab_size,
                            size=24 + int(rng.integers(0, 40))).tolist()
               for _ in range(streams)]
    reqs = []
    for p in prompts:
        tr = tracer.start() if tracer is not None else None
        reqs.append(eng.submit(p, max_new_tokens=new_tokens, trace=tr))
    itls: list[float] = []
    counts = [0] * len(reqs)
    stamps: list[tuple[int, float]] = []
    last = [None] * len(reqs)
    deadline = time.time() + 300
    while not all(r.done.is_set() for r in reqs):
        if time.time() > deadline:
            raise TimeoutError("storm did not complete")
        now = time.perf_counter()
        for i, r in enumerate(reqs):
            n = len(r.tokens)
            if n > counts[i]:
                if last[i] is not None:
                    # chunk-normalized: k tokens landed since the last
                    # observation -> k ITL samples of (dt / k)
                    dt_ms = (now - last[i]) * 1e3 / (n - counts[i])
                    itls.extend([dt_ms] * (n - counts[i]))
                counts[i] = n
                last[i] = now
        stamps.append((sum(counts), now))
        time.sleep(0.002)
    for r in reqs:
        r.wait(5)
        if tracer is not None and r.trace is not None:
            tracer.finish(r.trace)
    return itls


def main() -> None:
    streams = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    new_tokens = int(sys.argv[2]) if len(sys.argv) > 2 else 48
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    cfg = llamalib.tiny()
    model = llamalib.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.ones((1, 8), jnp.int32))["params"]
    # ONE engine for every rate, storms INTERLEAVED round-robin: an
    # engine instance can settle into a 2x-different host-loop steady
    # state on the 1-core container, which dwarfs the effect being
    # measured — comparing rates within one instance removes it
    eng = ContinuousEngine(cfg, params, num_slots=4, decode_chunk=2,
                           block_size=16, prefill_budget=16,
                           prefix_cache=False)
    tracer = Tracer(sample=0.0, ring=256)
    eng.tracer = tracer
    rates = (0.0, 0.1, 1.0)
    trials: dict[float, list] = {r: [] for r in rates}
    try:
        _storm(eng, None, streams, new_tokens, seed)  # warm the rungs
        for rep in range(3):
            for r in rates:
                tracer.sample = r
                trials[r].append(_storm(
                    eng, tracer, streams, new_tokens,
                    seed + 1 + rep * len(rates)))
        rows = {}
        for r in rates:
            itls = min(trials[r], key=lambda xs: _pct(xs, 0.5))
            rows[r] = {
                "metric": "trace_overhead_itl", "sample": r,
                "streams": streams, "new_tokens": new_tokens,
                "itl_p50_ms": round(_pct(itls, 0.5), 3),
                "itl_p99_ms": round(_pct(itls, 0.99), 3),
                "itl_p50_trials_ms": [round(_pct(xs, 0.5), 3)
                                      for xs in trials[r]],
                "recompiles": eng.stats()["jit_recompiles_total"],
            }
            print(json.dumps(rows[r]), flush=True)
        base = rows[0.0]["itl_p50_ms"] or 1e-9
        print(json.dumps({
            "metric": "trace_overhead_summary",
            "traces_finished":
                tracer.sink.stats()["traces_finished_total"],
            "itl_p50_ratio_sample01": round(
                rows[0.1]["itl_p50_ms"] / base, 4),
            "itl_p50_ratio_sample1": round(
                rows[1.0]["itl_p50_ms"] / base, 4),
            "note": ("ratios vs sample=0 on the same engine; "
                     "tiny-model CPU stand-in (1-core container): "
                     "treat as upper bounds, re-validate on chip"),
        }), flush=True)
    finally:
        eng.stop()


if __name__ == "__main__":
    main()
