"""AOT-compile the Llama-7B SERVING programs for a v5e-16 topology.

The r3 verdict's top gap: the platform could train the north-star model but
not serve it — 7B bf16 weights are ~13 GiB = 81% of one 16 GiB v5e chip
before any KV pool exists.  The r4 sharded serving data plane
(serving/sharded.py) closes this with tensor parallelism; this script is
the no-hardware proof, exactly like scripts/aot_7b_v5e16.py is for
training: the continuous-batching engine's REAL prefill and chunked-decode
programs (serving/continuous.py make_prefill_program/make_decode_program —
the same functions the live engine dispatches) lower and compile against
abstract v5e chips with the real TP shardings, and XLA's memory analysis
records the per-chip HBM breakdown: weight shard + KV slot-pool shard +
temps.

Also records an honest per-mesh decode roofline: decode is HBM-bound —
every emitted token streams the full weight shard plus the attended KV
from HBM — so tokens/s/chip bounds differ per (TP degree, pool size),
unlike a constant-MFU projection.

Usage:  python scripts/aot_7b_serving.py [--fast]
Writes: artifacts/aot_7b_serving_v5e16.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")  # host side traces on CPU

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from flax import linen as nn  # noqa: E402
from jax.experimental import topologies  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.models import llama  # noqa: E402
from kubeflow_tpu.serving import continuous as contlib  # noqa: E402
from kubeflow_tpu.serving import sharded as shardedlib  # noqa: E402

V5E_HBM_BYTES = 16 * 1024**3
V5E_HBM_BW = 819e9  # bytes/s per chip


def abstract_params(cfg, mesh):
    """ShapeDtypeStructs with the serving shardings attached."""
    boxed = jax.eval_shape(
        llama.Llama(cfg).init,
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((1, 8), jnp.int32),
    )["params"]
    shardings = shardedlib.llama_param_shardings(cfg, mesh)
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        nn.meta.unbox(boxed), shardings)


def compile_candidate(devs, cfg, *, tp, num_slots, decode_chunk=16,
                      prompt_bucket=2048, quant=False):
    if quant:
        # int8 serving (llama.quantize_for_serving flags): weights AND KV
        # stored int8 — the abstract init emits the int8+scale param tree
        import dataclasses as _dc

        cfg = _dc.replace(cfg, quant_weights=True, quant_kv=True,
                          param_dtype=jnp.float32)
    mesh = shardedlib.build_serving_mesh({"model": tp}, devices=devs)
    params = abstract_params(cfg, mesh)
    pool_shapes = contlib.cache_shapes(cfg, num_slots)
    pool = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        pool_shapes, shardedlib.cache_shardings(pool_shapes, mesh))
    logits = jax.ShapeDtypeStruct(
        (num_slots, cfg.vocab_size), cfg.dtype,
        sharding=jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, "model")))
    positions = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    active = jax.ShapeDtypeStruct((num_slots,), jnp.bool_)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)

    out = {"mesh_axes": {"model": tp}, "num_slots": num_slots,
           "decode_chunk": decode_chunk, "prompt_bucket": prompt_bucket,
           "max_seq_len": cfg.max_seq_len,
           "quant": "int8-weights+int8-kv" if quant else None}

    # -- decode: the steady-state program (full attend window = worst case)
    t0 = time.perf_counter()
    decode = contlib.make_decode_program(
        cfg, cfg.max_seq_len, decode_chunk, mesh)
    temps = jax.ShapeDtypeStruct((num_slots,), jnp.float32)
    top_ps = jax.ShapeDtypeStruct((num_slots,), jnp.float32)
    top_ks = jax.ShapeDtypeStruct((num_slots,), jnp.int32)
    compiled = decode.lower(params, pool, logits, positions, active,
                            temps, top_ps, top_ks, key).compile()
    out["decode_compile_seconds"] = round(time.perf_counter() - t0, 1)
    mem = compiled.memory_analysis()
    # donated pool aliases its output; live set = arguments + temps
    peak = mem.argument_size_in_bytes + mem.temp_size_in_bytes
    out["decode_argument_bytes_per_chip"] = mem.argument_size_in_bytes
    out["decode_temp_bytes_per_chip"] = mem.temp_size_in_bytes
    out["decode_peak_live_bytes_per_chip"] = peak
    out["fits_hbm"] = bool(peak <= V5E_HBM_BYTES)
    out["hbm_utilization"] = round(peak / V5E_HBM_BYTES, 3)

    # -- prefill: one admission row at the prompt bucket
    t0 = time.perf_counter()
    prefill = contlib.make_prefill_program(cfg, prompt_bucket, mesh)
    prompt = jax.ShapeDtypeStruct((1, prompt_bucket), jnp.int32)
    lengths = jax.ShapeDtypeStruct((1,), jnp.int32)
    pcomp = prefill.lower(params, prompt, lengths).compile()
    out["prefill_compile_seconds"] = round(time.perf_counter() - t0, 1)
    pmem = pcomp.memory_analysis()
    ppeak = (pmem.argument_size_in_bytes + pmem.temp_size_in_bytes
             + pmem.output_size_in_bytes)
    out["prefill_peak_live_bytes_per_chip"] = ppeak
    out["prefill_fits_alongside_pool"] = bool(
        ppeak + peak - mem.argument_size_in_bytes <= V5E_HBM_BYTES)

    # -- analytic breakdown + per-mesh decode roofline -------------------
    # int8: projection kernels/unembedding are 1 byte (+ per-channel f32
    # scales, <0.1%); int8 KV adds a per-(pos, kv_head) f32 scale pair
    w_itemsize = 1 if quant else jnp.dtype(cfg.param_dtype).itemsize
    kv_itemsize = 1 if quant else jnp.dtype(cfg.dtype).itemsize
    param_bytes = llama.num_params(cfg) * w_itemsize
    kv_slot_bytes = (2 * cfg.num_layers * cfg.max_seq_len * cfg.num_kv_heads
                     * cfg.head_dim * kv_itemsize)
    if quant:
        kv_slot_bytes += (2 * cfg.num_layers * cfg.max_seq_len
                          * cfg.num_kv_heads * 4)
    out["weight_bytes_per_chip"] = int(param_bytes / tp)
    out["kv_pool_bytes_per_chip"] = int(kv_slot_bytes * num_slots / tp)
    # decode streams the weight shard once per token-step (batched over all
    # slots) + each live slot's attended KV; at full pool occupancy and
    # full-window attention (worst case):
    read_per_step = (param_bytes + kv_slot_bytes * num_slots) / tp
    step_s = read_per_step / V5E_HBM_BW
    out["decode_roofline_tokens_per_sec_per_chip"] = round(
        num_slots / (step_s * tp), 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true")
    ap.add_argument("--topology", default="v5e:4x4")
    args = ap.parse_args()

    # serving dtype: bf16 weights (decode is HBM-bound on weight reads;
    # the LlamaGenerator weights_dtype lever) — param_dtype is what lands
    # in HBM at serve time
    cfg = llama.llama2_7b(param_dtype=jnp.bfloat16, remat=False)
    print(f"params {llama.num_params(cfg)/1e9:.2f}B bf16", file=sys.stderr)

    # each TP degree gets an exactly-sized abstract topology: XLA's TPU
    # lowering hard-crashes (device_id RET_CHECK) when collectives span a
    # proper subset of the topology's chips — a replica sub-pod IS its own
    # topology on real metal anyway (the controller packs one serving
    # replica per sub-slice)
    topo_for = {16: "v5e:4x4", 8: "v5e:2x4", 4: "v5e:2x2"}
    candidates = [
        dict(tp=16, num_slots=32),
        dict(tp=16, num_slots=64),
        dict(tp=8, num_slots=16),
        dict(tp=4, num_slots=8),
        # int8 rows (r4 verdict missing #3): weight bytes halve and KV
        # slots double per GiB -> the same mesh holds 2x the pool, and
        # the HBM-bound decode roofline roughly doubles
        dict(tp=16, num_slots=64, quant=True),
        dict(tp=8, num_slots=32, quant=True),
        dict(tp=4, num_slots=16, quant=True),
    ]
    if args.fast:
        candidates = candidates[:1]

    results = []
    for cand in candidates:
        print(f"compiling {cand} ...", file=sys.stderr)
        devs = list(topologies.get_topology_desc(
            topo_for[cand["tp"]], platform="tpu").devices)
        try:
            r = compile_candidate(devs, cfg, **cand)
            r["topology"] = topo_for[cand["tp"]]
        except Exception as e:  # noqa: BLE001 — keep the sweep going;
            # the failure is recorded in the result row, not swallowed
            r = {**cand, "error": f"{type(e).__name__}: {e}"}
        results.append(r)
        print(json.dumps(r), file=sys.stderr)

    out = {
        "topology": "per-candidate (v5e sub-pods)",
        "model": "llama2_7b",
        "n_params": llama.num_params(cfg),
        "weights_dtype": "bfloat16",
        "results": results,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "aot_7b_serving_v5e16.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "metric": "aot_7b_serving_fits_hbm",
        "value": sum(1 for r in results if r.get("fits_hbm")),
        "unit": f"of {len(results)} serving shardings",
    }))


if __name__ == "__main__":
    main()
