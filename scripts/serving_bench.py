"""Serving-plane benchmark: KV-cache decode throughput + BERT classify
latency on the local chip.

Covers BASELINE config 3's serving side with measured numbers: the
LlamaGenerator runtime's per-token decode rate (the TPU serving split:
prefill + jitted single-token steps) and BertClassifierModel's padded-
batch classify latency.  Prints one JSON line per row.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from __graft_entry__ import _bench_model  # noqa: E402
from kubeflow_tpu.models import bert as bertlib  # noqa: E402
from kubeflow_tpu.models import llama as llamalib  # noqa: E402
from kubeflow_tpu.serving.runtimes import (  # noqa: E402
    BertClassifierModel,
    LlamaGenerator,
)
from kubeflow_tpu.serving.storage import register_mem  # noqa: E402


from kubeflow_tpu.utils.stats import pct as _pct  # noqa: E402


def bench_decode(batch: int, prompt_len: int, new_tokens: int) -> dict:
    cfg = _bench_model()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    ref = register_mem("bench-llama", (cfg, params))
    g = LlamaGenerator("gen", {"params_ref": ref, "max_new_tokens": new_tokens})
    g.start()
    prompts = np.random.default_rng(0).integers(
        1, cfg.vocab_size, size=(batch, prompt_len)).tolist()
    g.predict_batch(prompts)  # compile prefill + decode
    t0 = time.perf_counter()
    out = g.predict_batch(prompts)
    dt = time.perf_counter() - t0
    assert len(out) == batch and all(len(o) == new_tokens for o in out)
    return {
        "metric": "llama_decode_tokens_per_sec",
        "model": "271M", "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "value": round(batch * new_tokens / dt, 1),
        "ms_per_token": round(dt / new_tokens * 1e3, 2),
    }


def bench_bert(batch: int, seq: int) -> dict:
    cfg = bertlib.bert_base(num_classes=2)
    model = bertlib.BertClassifier(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))
    ref = register_mem("bench-bert", (cfg, params))
    m = BertClassifierModel(
        "bert", {"params_ref": ref, "buckets": (batch,), "seq_buckets": (seq,)})
    m.start()
    rows = np.random.default_rng(0).integers(
        1, cfg.vocab_size, size=(batch, seq)).tolist()
    m.predict_batch(rows)  # compile
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        m.predict_batch(rows)
    dt = (time.perf_counter() - t0) / reps
    return {
        "metric": "bert_base_classify",
        "batch": batch, "seq": seq,
        "ms_per_batch": round(dt * 1e3, 2),
        "sequences_per_sec": round(batch / dt, 1),
    }


def bench_continuous(batch: int, prompt_len: int, new_tokens: int,
                     decode_chunk: int, quant: bool = False,
                     moe: bool = False) -> dict:
    """Continuous-batching load probe: all requests submitted concurrently
    (the equal-batch comparison against bench_decode) plus one straggler
    arriving mid-decode to measure admission latency + TTFT.  ``quant``
    runs the int8 weights+KV engine (llama.quantize_for_serving) — the
    same programs with int8 HBM residents."""
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    cfg = _bench_model()
    if moe:
        # Mixtral-shape-in-miniature: the 271M dense trunk with 8 experts
        # top-2, dropless dispatch (the serving-exact path)
        import dataclasses as _dc

        cfg = _dc.replace(cfg, moe_experts=8, moe_top_k=2,
                          moe_dispatch="ragged")
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    if quant:
        cfg, params = llamalib.quantize_for_serving(cfg, params)
    # one slot beyond the burst so the straggler measures MID-DECODE
    # admission (with num_slots == batch it would measure queue-wait
    # behind the full burst — batch-drain latency, not admission)
    # prefix_cache off: this row measures chunked-decode throughput with
    # grouped admission; with it on, the prime round's KV would turn the
    # identical-prompt burst into per-request prefix admissions and the
    # row would measure the prefix path instead (which has its own row)
    eng = ContinuousEngine(
        cfg, params, num_slots=batch + 1, decode_chunk=decode_chunk,
        pipeline_depth=3, prefix_cache=False)
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, cfg.vocab_size, size=(batch, prompt_len)).tolist()
    # load-time AOT: the burst admits as one batched prefill (group=batch)
    # and the straggler as group=1
    eng.warmup([(batch, prompt_len), (1, prompt_len)])
    # prime with one real traffic round: the first execution of each
    # loaded program on the tunnel backend pays device-side setup that a
    # steady-state throughput number should not include
    prime = [eng.submit(p, max_new_tokens=decode_chunk) for p in prompts]
    for r in prime:
        r.wait(300)
    try:
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
        # straggler arrives ~1/3 into the decode: continuous batching admits
        # it at the next chunk boundary; batch-mode would queue it behind
        # the whole running batch
        time.sleep(new_tokens / (3 * 80.0))  # ~1/3 of decode at 80 tok/s/row
        straggler = eng.submit(prompts[0], max_new_tokens=new_tokens)
        outs = [r.wait(300) for r in reqs]
        # burst throughput: equal-batch comparison vs bench_decode (the
        # straggler's lonely tail after the burst drains is excluded — it
        # measures admission, not steady-state throughput)
        dt_burst = time.perf_counter() - t0
        straggler.wait(300)
        assert all(len(o) == new_tokens for o in outs)
        ttfts = sorted(r.ttft_s for r in reqs + [straggler])
        metric = "llama_continuous_decode_tokens_per_sec"
        if quant:
            metric = "llama_continuous_int8_decode_tokens_per_sec"
        if moe:
            metric = "moe_continuous_decode_tokens_per_sec"
        return {
            "metric": metric,
            "model": "271M", "slots": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, "decode_chunk": decode_chunk,
            "value": round(batch * new_tokens / dt_burst, 1),
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1e3, 1),
            "straggler_ttft_ms": round(straggler.ttft_s * 1e3, 1),
            "straggler_admit_steps": straggler.admitted_step - straggler.submitted_step,
        }
    finally:
        eng.stop()


def bench_prefix_cache(prompt_len: int, new_tokens: int) -> dict:
    """Repeated-prefix workload (r3 verdict item 7): the same long prompt
    submitted repeatedly — admission drops from a full prefill to an
    on-device prefix copy + 1-token suffix prefill.  Reports admission
    (submit -> first token) with the cache cold vs warm."""
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    cfg = _bench_model()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(1)
    prompt = rng.integers(1, cfg.vocab_size, size=prompt_len).tolist()

    def run(prefix_cache: bool) -> float:
        eng = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=new_tokens,
            prefix_cache=prefix_cache, min_prefix=32)
        try:
            eng.warmup([(1, prompt_len)])
            eng.generate(prompt, max_new_tokens=new_tokens)  # seeds the KV
            # compile the prefix-admit program outside the timed window
            if prefix_cache:
                eng.generate(prompt, max_new_tokens=new_tokens)
            t0 = time.perf_counter()
            eng.generate(prompt, max_new_tokens=new_tokens)
            dt = time.perf_counter() - t0
            if prefix_cache:
                assert eng.prefix_hits >= 1, "prefix cache never hit"
        finally:
            eng.stop()
        return dt

    cold = run(False)
    warm = run(True)
    return {
        "metric": "llama_prefix_cache_generate_ms",
        "model": "271M", "prompt_len": prompt_len, "new_tokens": new_tokens,
        "full_prefill_ms": round(cold * 1e3, 1),
        "prefix_hit_ms": round(warm * 1e3, 1),
        "speedup": round(cold / warm, 2),
    }


def bench_shared_prefix(n_requests: int = 6, prefix_len: int = 896,
                        new_tokens: int = 16) -> dict:
    """Refcounted shared-prefix segments (r5): N concurrent requests with
    one long system prompt hold ONE segment + N SHORT suffix slots.  The
    capacity row is analytic (pool bytes per concurrent request, from the
    actual cache trees); the wall-clock row is measured on both engines
    at equal concurrency."""
    import dataclasses as _dc

    from kubeflow_tpu.serving.continuous import (
        ContinuousEngine,
        cache_shapes,
    )

    cfg = _bench_model()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(5)

    def burst_prompts(seed):
        r = np.random.default_rng(seed)
        system = r.integers(1, cfg.vocab_size, size=prefix_len).tolist()
        return [system + r.integers(1, cfg.vocab_size, size=8).tolist()
                for _ in range(n_requests)]

    def run(engine) -> tuple[float, float]:
        """(cold_s, warm_s): prime compiles with one throwaway burst;
        cold = a NEVER-SEEN system prompt's burst (requests 2..N benefit
        from the segment request 1 created); warm = the same burst again
        (pure segment hits / repeat traffic)."""
        try:
            for r in [engine.submit(p, max_new_tokens=new_tokens)
                      for p in burst_prompts(11)]:
                r.wait(600)
            fresh = burst_prompts(12)
            t0 = time.perf_counter()
            for r in [engine.submit(p, max_new_tokens=new_tokens)
                      for p in fresh]:
                r.wait(600)
            cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in [engine.submit(p, max_new_tokens=new_tokens)
                      for p in fresh]:
                r.wait(600)
            return cold, time.perf_counter() - t0
        finally:
            engine.stop()

    legacy_cold, legacy_warm = run(ContinuousEngine(
        cfg, params, num_slots=n_requests + 1, decode_chunk=8,
        prefix_cache=False))
    suffix_cfg = _dc.replace(cfg, max_seq_len=128)
    shared_cold, shared_warm = run(ContinuousEngine(
        suffix_cfg, params, num_slots=n_requests + 1, decode_chunk=8,
        prefix_cache=False, prefix_segments=3, segment_len=cfg.max_seq_len,
        min_prefix=64))

    def nbytes(c, rows):
        return sum(
            int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
            for s in jax.tree.leaves(cache_shapes(c, rows)))

    legacy_bytes = nbytes(cfg, n_requests)
    shared_bytes = nbytes(suffix_cfg, n_requests) + nbytes(cfg, 1)
    return {
        "metric": "shared_prefix_kv_bytes_per_request",
        "model": "271M", "n_requests": n_requests,
        "prefix_len": prefix_len, "new_tokens": new_tokens,
        "full_slot_bytes_per_req": legacy_bytes // n_requests,
        "shared_bytes_per_req": shared_bytes // n_requests,
        "capacity_gain": round(legacy_bytes / shared_bytes, 2),
        "legacy_cold_s": round(legacy_cold, 2),
        "shared_cold_s": round(shared_cold, 2),
        "legacy_warm_s": round(legacy_warm, 2),
        "shared_warm_s": round(shared_warm, 2),
    }


def bench_chunked_prefill_stall(prompt_len: int = 896,
                                prefill_budget: int = 64,
                                decode_chunk: int = 4,
                                cfg=None) -> dict:
    """ISSUE 2's headline number: decode inter-token latency for a LIVE
    request WHILE a long prompt admits — legacy whole-prompt admission
    (one [1, bucket] prefill dispatch freezes the decode stream for the
    whole prompt) vs Sarathi-style chunked prefill fused into the decode
    dispatches (stall bounded by ``prefill_budget`` tokens of prefill
    per dispatch).  A victim request decodes continuously; its token
    arrivals are timestamped on the host; the long prompt is submitted
    mid-stream and the ITL distribution over the admission window is
    reported (p50/p99/max, per token — arrivals land in decode_chunk
    granularity, so each gap is spread over the tokens it delivered).
    """
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    if cfg is None:
        cfg = _bench_model()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(3)
    long_prompt = rng.integers(1, cfg.vocab_size, size=prompt_len).tolist()
    victim_prompt = rng.integers(1, cfg.vocab_size, size=32).tolist()
    victim_new = 256

    def run(budget: int) -> tuple[list[float], float]:
        """(per-token ITLs in ms over the admission window, stall gauge)."""
        eng = ContinuousEngine(
            cfg, params, num_slots=4, decode_chunk=decode_chunk,
            pipeline_depth=2, prefix_cache=False, prefill_budget=budget)
        try:
            eng.warmup([(1, 32), (1, prompt_len)])
            # prime: first execution pays device-side setup
            eng.generate(victim_prompt, max_new_tokens=decode_chunk)
            victim = eng.submit(victim_prompt, max_new_tokens=victim_new)
            arrivals: list[tuple[float, int]] = []  # (t, tokens so far)
            seen = 0
            submitted = None
            long_req = None
            while not victim.done.is_set():
                n = len(victim.tokens)
                if n > seen:
                    arrivals.append((time.perf_counter(), n))
                    seen = n
                if submitted is None and seen >= 4 * decode_chunk:
                    long_req = eng.submit(long_prompt, max_new_tokens=4)
                    submitted = time.perf_counter()
                time.sleep(0.0005)
            victim.wait(600)
            if long_req is not None:
                long_req.wait(600)
            window_end = (long_req.first_token_at
                          or time.perf_counter()) if long_req else None
            itls = []
            for (t0, n0), (t1, n1) in zip(arrivals, arrivals[1:]):
                if submitted is None or t1 < submitted or (
                        window_end and t0 > window_end):
                    continue  # outside the admission window
                itls.extend([(t1 - t0) / (n1 - n0) * 1e3] * (n1 - n0))
            return itls, eng.stats()["decode_stall_ms_total"]
        finally:
            eng.stop()

    legacy, legacy_stall = run(0)
    chunked, chunked_stall = run(prefill_budget)
    return {
        "metric": "decode_itl_during_long_prompt_admission_ms",
        "model": f"{llamalib.num_params(cfg) / 1e6:.0f}M",
        "long_prompt": prompt_len,
        "prefill_budget": prefill_budget, "decode_chunk": decode_chunk,
        "legacy_p50_ms": round(_pct(legacy, 0.5), 2),
        "legacy_p99_ms": round(_pct(legacy, 0.99), 2),
        "legacy_max_ms": round(max(legacy, default=0.0), 2),
        "chunked_p50_ms": round(_pct(chunked, 0.5), 2),
        "chunked_p99_ms": round(_pct(chunked, 0.99), 2),
        "chunked_max_ms": round(max(chunked, default=0.0), 2),
        "p99_speedup": round(
            _pct(legacy, 0.99) / max(_pct(chunked, 0.99), 1e-9), 2),
        "legacy_stall_gauge_ms": round(legacy_stall, 1),
        "chunked_stall_gauge_ms": round(chunked_stall, 1),
    }


def _spec_stand_in(vocab_size: int = 8192) -> "llamalib.LlamaConfig":
    """~34M-param stand-in for the speculative rows: big enough that a
    (k+1)-wide verify forward costs real compute relative to dispatch
    overhead, small enough that 256-token greedy completions finish in
    seconds on the CPU backend.  Measured on this box: a spec_k=8
    verify dispatch costs 1.11x a single-token decode dispatch — the
    forward is weight-stream/overhead bound, the same width-independent
    cost structure as the TPU's HBM byte bill."""
    return llamalib.LlamaConfig(
        vocab_size=vocab_size, hidden_size=512, intermediate_size=1408,
        num_layers=8, num_heads=8, num_kv_heads=8, head_dim=64,
        max_seq_len=1024, remat=False, scan_layers=True,
        dtype=jnp.float32)


def _spec_repetitive_params(model, seed: int = 6):
    """Stand-in weights for the REPETITIVE row: random init with the
    attention/MLP block-output projections (wo, w_down) zeroed, so the
    residual stream is exactly the token embedding and greedy decode is
    a position-free token-level Markov map.  A random map on 512 states
    falls into a short cycle fast (seed 6: every orbit reaches a
    period-10 or period-17 cycle within ~30 tokens) — the token-stream
    shape of highly templated/repetitive output, constructed explicitly
    rather than smuggled in via a lucky weight seed.  The forward pass
    keeps the FULL stand-in cost: every GEMM still executes (the zeros
    are dense f32 buffers XLA cannot see through), so the off/on ratio
    measures engine dispatch economics, not a smaller model."""
    params = model.init(
        jax.random.PRNGKey(seed), jnp.ones((1, 8), jnp.int32))["params"]

    def f(path, leaf):
        ks = jax.tree_util.keystr(path)
        return leaf * 0.0 if ("'wo'" in ks or "'w_down'" in ks) else leaf

    return jax.tree_util.tree_map_with_path(f, params)


def bench_speculative(spec_k: int = 6, spec_ngram: int = 3,
                      num_slots: int = 4, n_requests: int = 8,
                      new_tokens: int = 256) -> dict:
    """ISSUE 4's headline row: decode tok/s with speculation on vs off.

    REPETITIVE row: long greedy completions whose continuations repeat
    — the regime n-gram / prompt-lookup drafts exist for (code,
    templated output, quoting context back).  The stand-in makes that
    regime explicit (`_spec_repetitive_params`: greedy decode is a
    Markov map that falls into short cycles), so the proposer's drafts
    verify against genuinely accepted runs through the full engine.
    Requests outnumber slots (backlog) as in real serving — a slot that
    retires its request early admits the next one instead of idling on
    the pool's slowest stream.  ADVERSARIAL row: short completions on a
    full-vocab random-weight stand-in whose trajectories never revisit
    an n-gram — the proposer's guesses all reject, and the engine must
    ride its zero-accept backoff + plain-decode fallback at (near) full
    speed.

    Honest scope notes: the RATIO is the claim, absolute ms are the CPU
    backend (on TPU the verify's win is the amortized weight+KV HBM
    stream; here it is the amortized dispatch + a verify forward that
    measures 1.11x a decode dispatch at spec_k=8).  Acceptance is
    workload-dependent — the repetitive row is the favorable regime
    (acceptance ~1 by construction), the adversarial row the
    unfavorable one; real traffic sits between, and the acceptance rate
    is reported so the regime is visible, not assumed.
    """
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    rep_cfg = _spec_stand_in(vocab_size=512)
    rep_params = _spec_repetitive_params(llamalib.Llama(rep_cfg))
    adv_cfg = _spec_stand_in()
    adv_params = llamalib.Llama(adv_cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(7)
    rep_prompts = [rng.integers(1, rep_cfg.vocab_size, size=6).tolist()
                   for _ in range(n_requests)]
    adv_prompts = [rng.integers(1, adv_cfg.vocab_size, size=64).tolist()
                   for _ in range(n_requests)]

    def run(cfg, params, k: int, prompts, toks_per: int):
        eng = ContinuousEngine(
            cfg, params, num_slots=num_slots, decode_chunk=1,
            prefix_cache=False, spec_k=k, spec_ngram=spec_ngram)
        try:
            # warm every attend rung the timed run will CLIMB: positions
            # reach prompt + toks_per (+ the verify span), and a group
            # entry at seq bucket A//2 puts attend bucket A in the warm
            # set — otherwise both rows pay compile stalls inside the
            # timed window, and the spec-on row pays ~2x as many (verify
            # rungs on top of decode), skewing the reported ratio
            final = max(map(len, prompts)) + toks_per + k + 1
            groups = [(1, 64), (num_slots, 64)]
            groups += [(num_slots, a // 2) for a in eng.attend_buckets
                       if 64 < a // 2 <= final]
            eng.warmup(groups)
            # prime: first execution pays device-side setup, and the
            # speculative engine's verify program joins steady state
            eng.submit(prompts[0], max_new_tokens=8).wait(600)
            t0 = time.perf_counter()
            reqs = [eng.submit(p, max_new_tokens=toks_per)
                    for p in prompts]
            outs = [r.wait(1200) for r in reqs]
            dt = time.perf_counter() - t0
            assert all(len(o) == toks_per for o in outs)
            return len(prompts) * toks_per / dt, eng.stats()
        finally:
            eng.stop()

    rep_off, _ = run(rep_cfg, rep_params, 0, rep_prompts, new_tokens)
    rep_on, rep_stats = run(rep_cfg, rep_params, spec_k, rep_prompts,
                            new_tokens)
    adv_off, _ = run(adv_cfg, adv_params, 0, adv_prompts, 32)
    adv_on, adv_stats = run(adv_cfg, adv_params, spec_k, adv_prompts, 32)
    return {
        "metric": "speculative_decode_tokens_per_sec",
        "model": f"{llamalib.num_params(adv_cfg) / 1e6:.0f}M",
        "spec_k": spec_k, "spec_ngram": spec_ngram,
        "decode_chunk": 1, "slots": num_slots, "requests": n_requests,
        "repetitive_new_tokens": new_tokens,
        "repetitive_off_tok_s": round(rep_off, 1),
        "repetitive_on_tok_s": round(rep_on, 1),
        "repetitive_speedup": round(rep_on / rep_off, 2),
        "repetitive_acceptance_rate": rep_stats["spec_acceptance_rate"],
        "repetitive_verify_dispatches": rep_stats["spec_dispatches_total"],
        "adversarial_off_tok_s": round(adv_off, 1),
        "adversarial_on_tok_s": round(adv_on, 1),
        "adversarial_ratio": round(adv_on / adv_off, 3),
        "adversarial_acceptance_rate": adv_stats["spec_acceptance_rate"],
        "adversarial_verify_dispatches":
            adv_stats["spec_dispatches_total"],
    }


def bench_tiered_admission(new_tokens: int = 16) -> dict:
    """r3 weak #4, re-anchored by the paged pool (ISSUE 6): the tier
    ladder is now an admission POLICY over one paged pool — per-tier KV
    pools are deleted, the memory reason for them gone (a request's KV
    bill is its block count, not max_seq_len).  What the policy still
    guarantees is ADMISSION: a burst of long conversations saturating
    the pool must not starve short requests.  A single unpoliced pool
    fills every slot with longs and shorts queue behind whole
    conversations; the tiered policy's short-class quota keeps slots
    reserved, so shorts admit at the next boundary."""
    from kubeflow_tpu.serving.continuous import ContinuousEngine, TieredEngine

    cfg = _bench_model()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(2)
    longs = [rng.integers(1, cfg.vocab_size, size=96).tolist()
             for _ in range(8)]
    shorts = [rng.integers(1, cfg.vocab_size, size=24).tolist()
              for _ in range(4)]

    def run(engine) -> float:
        try:
            engine.generate(shorts[0], max_new_tokens=new_tokens)
            backlog = [engine.submit(p, max_new_tokens=256)
                       for p in longs]  # > num_slots long conversations
            time.sleep(0.3)
            lats = []
            for p in shorts:
                t0 = time.perf_counter()
                engine.generate(p, max_new_tokens=new_tokens, timeout=600)
                lats.append(time.perf_counter() - t0)
            for r in backlog:
                r.wait(600)
            lats.sort()
            return lats[len(lats) // 2]
        finally:
            engine.stop()

    single = run(ContinuousEngine(
        cfg, params, num_slots=4, decode_chunk=8, prefix_cache=False,
        block_size=32))
    tiered = run(TieredEngine(
        cfg, params, num_slots=4, tier_lens=[64], tier_slots=[2],
        decode_chunk=8, prefix_cache=False, block_size=32))
    return {
        "metric": "short_request_latency_vs_long_backlog_ms",
        "model": "271M", "short_prompt": 24, "new_tokens": new_tokens,
        "long_prompt": 96, "long_new": 256, "long_backlog": 8,
        "unpoliced_pool_p50_ms": round(single * 1e3, 1),
        "tiered_policy_p50_ms": round(tiered * 1e3, 1),
        "speedup": round(single / tiered, 2),
    }


PROBE_TIMEOUT_S = 120.0


def _migration_workload(prompt_len: int, storm: int):
    cfg = _paged_stand_in()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(11)
    long_prompts = [rng.integers(1, cfg.vocab_size, size=prompt_len).tolist()
                    for _ in range(storm)]
    victim_prompt = rng.integers(1, cfg.vocab_size, size=32).tolist()
    return cfg, params, long_prompts, victim_prompt


def _migration_child(spec_json: str) -> None:
    """Prefill-tier subprocess of bench_migration: build a prefill-role
    engine (same deterministic params), signal READY, wait for GO, then
    re-nice to the lowest priority and chunk-prefill the storm — every
    finished sequence streams to the parent's KvMigrationServer over
    the kv_migrate wire.  The self-nice is the bench's stand-in for
    "prefill runs on its own chips": on this 1-core container the OS
    would otherwise timeslice the tiers 50/50, which measures the
    container, not the disaggregation."""
    import os

    from kubeflow_tpu.serving.continuous import ContinuousEngine
    from kubeflow_tpu.serving.gang import migrate_sequence

    spec = json.loads(spec_json)
    cfg, params, long_prompts, _victim = _migration_workload(
        spec["prompt_len"], spec["storm"])
    eng = ContinuousEngine(cfg, params, role="prefill", **spec["kw"])
    done: list = []
    eng.on_prefilled = done.append
    eng.warmup([(1, 32), (1, spec["prompt_len"])])
    print("READY", flush=True)
    assert sys.stdin.readline().strip() == "GO"
    os.nice(19)  # prefill tier yields the core to the decode tier
    try:
        reqs = [eng.submit(p, max_new_tokens=8) for p in long_prompts]
        sent = set()
        while len(sent) < len(reqs):
            for req in [r for r in list(done) if id(r) not in sent]:
                snap = eng.export_sequence(req)
                if snap is not None and migrate_sequence(
                        snap, "127.0.0.1", spec["port"],
                        token=spec["token"]):
                    eng.release_sequence(req)
                else:
                    eng.resume_sequence(req)
                sent.add(id(req))
            time.sleep(0.01)
        print("HANDED_OFF", flush=True)
    finally:
        eng.stop()


def bench_migration(prompt_len: int = 192, prefill_budget: int = 64,
                    decode_chunk: int = 2, storm: int = 6,
                    block_size: int = 32) -> dict:
    """ISSUE 8's headline row: decode ITL for a LIVE conversation while
    an admission STORM of long prompts lands (the PR 2 workload), one
    mixed replica vs a disaggregated prefill+decode pair.

    A MIXED replica must pick an admission mode, and both tax decode:
    monolithic admission (``prefill_budget=0``, the max-throughput
    config) freezes the victim for whole-prompt prefills; chunked
    (Sarathi, PR 2) bounds each stall at ``prefill_budget`` tokens but
    taxes EVERY dispatch for the storm's duration.  The DISAGGREGATED
    pair escapes the choice: its prefill tier runs monolithic (nothing
    to protect there), its decode tier pays only a bounded per-sequence
    import stall (a fixed ~2-dispatch constant, independent of prompt
    length).  Both mixed baselines are reported; the headline ratio is
    against the monolithic (throughput-equivalent) config, the chunked
    comparison is reported alongside — on THIS 1-core container the
    import stall and the bounded chunk tax are the same order, while on
    separate chips the gather/scatter is HBM-cheap and the wire is the
    only tax (CPU stand-in ratio, per the ROADMAP re-anchor note;
    re-validate on chip).

    The disaggregated victim decodes on a decode-role engine in THIS
    process; the storm prefills in a SEPARATE nice(19) process (the
    prefill tier) and each finished sequence arrives over the
    authenticated kv_migrate wire — the subprocess is the 1-core
    stand-in for the tiers owning separate chips (threads would share
    one XLA pool and measure core contention, not the design).

    A second phase measures the handoff itself on idle engines —
    export -> destination-ack wall latency p50/p99, the once-per-
    sequence price of keeping prefill off the decode path."""
    import subprocess

    from kubeflow_tpu.serving.continuous import ContinuousEngine
    from kubeflow_tpu.serving.gang import KvMigrationServer

    cfg, params, long_prompts, victim_prompt = _migration_workload(
        prompt_len, storm)
    victim_new = 192
    # pool sized to the workload (not worst-case derivation): smaller
    # block pools keep the CPU stand-in's per-dispatch gather/scatter
    # bytes representative instead of dominated by empty capacity
    kw = dict(num_slots=2 + storm, decode_chunk=decode_chunk,
              pipeline_depth=2, prefix_cache=False,
              prefill_budget=0, block_size=block_size,
              num_blocks=(2 + storm) * (-(-(prompt_len + 64)
                                          // block_size)),
              seq_buckets=None)

    def victim_itls(engine, start_storm, storm_done) -> list[float]:
        """Victim per-token ITLs (ms) over the storm window."""
        engine.generate(victim_prompt, max_new_tokens=decode_chunk)
        victim = engine.submit(victim_prompt, max_new_tokens=victim_new)
        arrivals: list[tuple[float, int]] = []
        seen = 0
        submitted = None
        while not victim.done.is_set():
            n = len(victim.tokens)
            if n > seen:
                arrivals.append((time.perf_counter(), n))
                seen = n
            if submitted is None and seen >= 4 * decode_chunk:
                start_storm()
                submitted = time.perf_counter()
            time.sleep(0.0005)
        victim.wait(600)
        window_end = storm_done()
        itls: list[float] = []
        for (t0, n0), (t1, n1) in zip(arrivals, arrivals[1:]):
            if submitted is None or t1 < submitted or (
                    window_end and t0 > window_end):
                continue
            itls.extend([(t1 - t0) / (n1 - n0) * 1e3] * (n1 - n0))
        return itls

    # -- mixed replica, both admission modes: the storm lands in the
    # victim's own dispatch stream either way --
    def run_mixed(budget: int) -> list[float]:
        import threading

        eng = ContinuousEngine(cfg, params,
                               **{**kw, "prefill_budget": budget})
        try:
            eng.warmup([(1, 32), (1, prompt_len)])
            storm_reqs: list = []
            drained: list = []

            def start():
                storm_reqs.extend(
                    eng.submit(p, max_new_tokens=8)
                    for p in long_prompts)

                def watch():
                    # window = the whole storm episode: admission AND
                    # the admitted conversations' own short decode —
                    # symmetric with the disaggregated run, where
                    # imports land mid-window and decode alongside
                    # the victim
                    for r in storm_reqs:
                        r.wait(600)
                    drained.append(time.perf_counter())

                threading.Thread(target=watch, daemon=True).start()

            def done():
                deadline = time.monotonic() + 600
                while not drained and time.monotonic() < deadline:
                    time.sleep(0.01)
                return drained[0] if drained else None

            return victim_itls(eng, start, done)
        finally:
            eng.stop()

    mixed_mono = run_mixed(0)
    mixed_chunked = run_mixed(prefill_budget)

    # -- disaggregated pair: decode tier here, prefill tier nice(19) --
    dec = ContinuousEngine(cfg, params, role="decode", **kw)
    srv = KvMigrationServer(dec, token="bench")
    spec = json.dumps({"prompt_len": prompt_len, "storm": storm,
                       "port": srv.port, "token": "bench", "kw": kw})
    child = subprocess.Popen(
        [sys.executable, __file__, "migration-child", spec],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        import threading

        assert child.stdout.readline().strip() == "READY"
        # full ladder: imported storm sequences resume at ~prompt_len
        # positions, and the victim climbs rungs mid-window — every
        # attend bucket must be compiled before the measurement
        dec.warmup([(1, 32), (1, prompt_len)])
        drained: list = []

        def start():
            child.stdin.write("GO\n")
            child.stdin.flush()

            def watch():
                # symmetric window: every storm sequence imported AND
                # finished its decode on this tier
                deadline = time.monotonic() + 600
                while (srv.imports_total < storm
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                while (dec.stats()["slots_live"] > 1
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                drained.append(time.perf_counter())

            threading.Thread(target=watch, daemon=True).start()

        def done():
            deadline = time.monotonic() + 600
            while not drained and time.monotonic() < deadline:
                time.sleep(0.01)
            return drained[0] if drained else None

        disagg = victim_itls(dec, start, done)
        child.wait(timeout=120)
    finally:
        if child.poll() is None:
            child.kill()
        srv.close()
        dec.stop()

    # -- handoff latency on idle engines (the per-sequence price) --
    src = ContinuousEngine(cfg, params, **kw)
    dst = ContinuousEngine(cfg, params, **kw)
    try:
        src.warmup([(1, 32), (1, prompt_len)])
        dst.warmup([(1, 32)])
        lats: list[float] = []
        for p in long_prompts + long_prompts:
            req = src.submit(p, max_new_tokens=64)
            while len(req.tokens) < 2:
                time.sleep(0.001)
            t0 = time.perf_counter()
            snap = src.export_sequence(req)
            if snap is None:
                continue
            dst.import_sequence(snap, req=req)
            src.release_sequence(req)
            lats.append((time.perf_counter() - t0) * 1e3)
            req.cancel()
            req.wait(120)
    finally:
        src.stop()
        dst.stop()

    return {
        "metric": "disaggregated_decode_itl_under_admission_storm_ms",
        "model": f"{llamalib.num_params(cfg) / 1e6:.0f}M",
        "long_prompt": prompt_len, "storm": storm,
        "prefill_budget": prefill_budget, "decode_chunk": decode_chunk,
        "block_size": block_size,
        "mixed_monolithic_p50_ms": round(_pct(mixed_mono, 0.5), 2),
        "mixed_monolithic_p99_ms": round(_pct(mixed_mono, 0.99), 2),
        "mixed_chunked_p50_ms": round(_pct(mixed_chunked, 0.5), 2),
        "mixed_chunked_p99_ms": round(_pct(mixed_chunked, 0.99), 2),
        "disagg_p50_ms": round(_pct(disagg, 0.5), 2),
        "disagg_p99_ms": round(_pct(disagg, 0.99), 2),
        "itl_p99_ratio": round(
            _pct(disagg, 0.99) / max(_pct(mixed_mono, 0.99), 1e-9), 3),
        "itl_p99_ratio_vs_chunked": round(
            _pct(disagg, 0.99) / max(_pct(mixed_chunked, 0.99), 1e-9), 3),
        "migrations": len(lats),
        "handoff_p50_ms": round(_pct(lats, 0.5), 2),
        "handoff_p99_ms": round(_pct(lats, 0.99), 2),
        "unit": ("victim per-token ITL over the storm window; mixed "
                 "baselines = monolithic (throughput-equivalent, the "
                 "headline ratio) and chunked admission; prefill tier "
                 "= nice(19) subprocess (separate-chip stand-in on a "
                 "1-core container)"),
    }


def _http_post(url: str, payload: dict, timeout: float = 120.0):
    """(status, body dict|None) for one JSON POST — 4xx/5xx are DATA
    for the traffic rows (sheds are explicit 429s), never exceptions."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = None
        return e.code, body
    except OSError:
        return 0, None  # connection-level failure (a killed replica)


def _stream_itls(engine, prompt, new_tokens: int, priority=None,
                 window=None) -> list[float]:
    """Per-token ITLs (ms) of one live stream submitted at ``priority``
    — the victim measurement the PR 2/6/8 benches share, with the
    storm window optionally bounding which gaps count."""
    victim = engine.submit(prompt, max_new_tokens=new_tokens,
                           priority=priority)
    arrivals: list[tuple[float, int]] = []
    seen = 0
    while not victim.done.is_set():
        n = len(victim.tokens)
        if n > seen:
            arrivals.append((time.perf_counter(), n))
            seen = n
        time.sleep(0.0005)
    victim.wait(600)
    itls: list[float] = []
    for (t0, n0), (t1, n1) in zip(arrivals, arrivals[1:]):
        if window is not None and (t1 < window[0] or t0 > window[1]):
            continue
        itls.extend([(t1 - t0) / (n1 - n0) * 1e3] * (n1 - n0))
    return itls


def bench_traffic_storm(storm_seconds: float = 8.0,
                        overload: float = 2.0,
                        gold_new_tokens: int = 160,
                        bulk_new_tokens: int = 16,
                        seed: int = 13) -> dict:
    """ISSUE 9's headline row: per-tenant QoS under an OPEN-LOOP storm.

    Arrivals are an arrival process (seeded exponential inter-arrival
    gaps at ``overload`` x the measured closed-loop capacity), NOT a
    closed loop — a closed-loop client self-throttles when the server
    slows, which hides exactly the overload behavior this subsystem
    exists for.  A ``gold`` (priority=high) victim stream decodes
    throughout; ``bulk`` traffic storms the OpenAI HTTP door.

    QOS ON: bulk is capped (max_concurrent + a bounded admission
    queue), the surplus sheds with explicit 429 + Retry-After, and the
    engine's priority admission + the preemptor keep the gold stream's
    ITL at its uncontended baseline.  QOS OFF (the control): every
    arrival queues unboundedly in the engine and the victim's tail
    absorbs the whole storm.  Reported: gold ITL p99 uncontended /
    storm-with-qos / storm-without, bulk goodput + shed counts, and
    the engine's preemption/queue gauges.  CPU stand-in ratios (the
    ROADMAP re-anchor note applies; re-validate on chip)."""
    import threading

    from kubeflow_tpu.serving.server import ModelServer
    from kubeflow_tpu.serving.storage import register_mem
    from kubeflow_tpu.serving.text import TextGenerator

    cfg = _paged_stand_in()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    ref = register_mem("bench-traffic", (cfg, params))
    rng = np.random.default_rng(seed)
    gold_prompt = rng.integers(1, 255, size=24).tolist()
    bulk_prompts = ["bulk request %04d tail " % i + "x" * 24
                    for i in range(4096)]

    base_cfg = dict(
        params_ref=ref, tokenizer="bytes", num_slots=6, decode_chunk=2,
        prefill_budget=16, block_size=32, num_blocks=48,
        max_new_tokens=bulk_new_tokens, prefix_cache=False,
        # warm every attend rung the gold stream climbs (prompt + 160
        # tokens) INCLUDING the fused chunk+decode programs bulk
        # admissions dispatch at those rungs — an unwarmed rung is a
        # compile stall inside the measured window (the r7 lesson)
        warmup_groups=[[1, 32], [6, 32],
                       [1, 24 + gold_new_tokens + 8]])
    # the QoS sizing IS the policy: bulk gets 2 of 6 slots + a 2-deep
    # door queue; the surplus sheds.  A looser cap trades gold tail
    # latency for bulk goodput — that dial belongs to the operator.
    qos = {"gold": {"priority": "high"},
           "bulk": {"priority": "low", "max_concurrent": 2,
                    "queue_depth": 2}}

    def serve(with_qos: bool):
        c = dict(base_cfg)
        if with_qos:
            c["qos"] = qos
        srv = ModelServer()
        gen = TextGenerator("m", c)
        srv.register(gen)
        srv.start()
        # prime the full HTTP + engine path once (first-execution
        # device setup; the attend rungs are already warm via
        # warmup_groups)
        gen.engine.generate(gold_prompt, max_new_tokens=4)
        _http_post(srv.url + "/openai/v1/completions", {
            "model": "m", "prompt": bulk_prompts[0],
            "max_tokens": bulk_new_tokens})
        return srv, gen

    def storm(srv, gen, rate_hz: float, duration: float):
        """Open-loop bulk arrivals against the HTTP door; returns
        (ok, shed, failed, bulk_tokens, arrivals, bulk latencies,
        max engine queue depth observed)."""
        url = srv.url + "/openai/v1/completions"
        results: list[tuple[int, int, float]] = []
        lock = threading.Lock()
        threads: list[threading.Thread] = []
        peak_q = [0]
        sampling = threading.Event()

        def sample_queue():
            # "no unbounded queue growth" is the acceptance bar: track
            # the engine's queue depth through the storm — bounded
            # admission keeps it at the class's queue/slot budget, the
            # unpoliced engine's grows with every surplus arrival
            while not sampling.is_set():
                peak_q[0] = max(peak_q[0],
                                gen.engine.stats()["queue_depth"])
                sampling.wait(0.05)

        def one(i: int):
            t0 = time.perf_counter()
            st, body = _http_post(url, {
                "model": "m", "prompt": bulk_prompts[i % len(bulk_prompts)],
                "max_tokens": bulk_new_tokens, "user": "bulk"},
                timeout=max(120.0, duration * 6))
            lat = time.perf_counter() - t0
            toks = (body or {}).get("usage", {}).get(
                "completion_tokens", 0) if st == 200 else 0
            with lock:
                results.append((st, toks, lat))

        sampler = threading.Thread(target=sample_queue, daemon=True)
        sampler.start()
        r = np.random.default_rng(seed + 1)
        t_end = time.perf_counter() + duration
        i = 0
        while time.perf_counter() < t_end:
            th = threading.Thread(target=one, args=(i,), daemon=True)
            th.start()
            threads.append(th)
            i += 1
            time.sleep(float(r.exponential(1.0 / rate_hz)))
        for th in threads:
            th.join(timeout=600)
        sampling.set()
        sampler.join(timeout=2)
        hung = sum(1 for th in threads if th.is_alive())
        ok = sum(1 for st, _, _ in results if st == 200)
        shed = sum(1 for st, _, _ in results if st == 429)
        failed = len(results) - ok - shed
        toks = sum(t for _, t, _ in results)
        lats = [lt for st, _, lt in results if st == 200]
        return ok, shed, failed + hung, toks, i, lats, peak_q[0]

    # -- capacity probe: closed-loop bulk throughput on a fresh server --
    srv, gen = serve(False)
    try:
        t0 = time.perf_counter()
        done = 0
        done_lock = threading.Lock()
        deadline = t0 + 4.0
        workers = []

        def closed_loop():
            nonlocal done
            k = 0
            while time.perf_counter() < deadline:
                _http_post(srv.url + "/openai/v1/completions", {
                    "model": "m", "prompt": bulk_prompts[k],
                    "max_tokens": bulk_new_tokens})
                k += 1
                with done_lock:  # += across threads loses increments
                    done += 1

        for _ in range(3):
            w = threading.Thread(target=closed_loop, daemon=True)
            w.start()
            workers.append(w)
        for w in workers:
            w.join(timeout=120)
        capacity_hz = done / (time.perf_counter() - t0)
        # -- uncontended gold baseline on the same engine --
        base_itls = _stream_itls(gen.engine, gold_prompt,
                                 gold_new_tokens, priority=0)
    finally:
        srv.stop()
    rate = max(overload * capacity_hz, 1.0)

    def run_storm(with_qos: bool):
        srv, gen = serve(with_qos)
        try:
            out: dict = {}

            def drive():
                out["storm"] = storm(srv, gen, rate, storm_seconds)

            w0 = time.perf_counter()
            th = threading.Thread(target=drive, daemon=True)
            th.start()
            itls = _stream_itls(gen.engine, gold_prompt, gold_new_tokens,
                                priority=0,
                                window=(w0, w0 + storm_seconds))
            th.join(timeout=900)
            stats = gen.traffic.stats() if gen.traffic else {}
            return itls, out.get("storm", (0, 0, 0, 0, 0, [], 0)), stats
        finally:
            srv.stop()

    on_itls, (on_ok, on_shed, on_fail, on_toks, on_n, on_lats,
              on_peak_q), on_stats = run_storm(True)
    off_itls, (off_ok, off_shed, off_fail, off_toks, off_n, off_lats,
               off_peak_q), _ = run_storm(False)

    return {
        "metric": "qos_storm_gold_itl_p99_ms",
        "model": f"{llamalib.num_params(cfg) / 1e6:.0f}M",
        "overload_x": overload, "storm_seconds": storm_seconds,
        "capacity_req_s": round(capacity_hz, 2),
        "arrival_rate_req_s": round(rate, 2),
        "gold_new_tokens": gold_new_tokens,
        "bulk_new_tokens": bulk_new_tokens,
        "gold_itl_p99_uncontended_ms": round(_pct(base_itls, 0.99), 2),
        "gold_itl_p99_qos_ms": round(_pct(on_itls, 0.99), 2),
        "gold_itl_p99_noqos_ms": round(_pct(off_itls, 0.99), 2),
        "gold_p99_vs_uncontended_qos": round(
            _pct(on_itls, 0.99) / max(_pct(base_itls, 0.99), 1e-9), 3),
        "gold_p99_vs_uncontended_noqos": round(
            _pct(off_itls, 0.99) / max(_pct(base_itls, 0.99), 1e-9), 3),
        "qos_bulk_arrivals": on_n, "qos_bulk_ok": on_ok,
        "qos_bulk_shed_429": on_shed, "qos_bulk_failed": on_fail,
        "qos_bulk_goodput_tok_s": round(on_toks / storm_seconds, 1),
        "qos_bulk_latency_p99_s": round(_pct(on_lats, 0.99), 2),
        "qos_peak_engine_queue": on_peak_q,
        "noqos_bulk_arrivals": off_n, "noqos_bulk_ok": off_ok,
        "noqos_bulk_shed_429": off_shed,
        "noqos_bulk_goodput_tok_s": round(off_toks / storm_seconds, 1),
        "noqos_bulk_latency_p99_s": round(_pct(off_lats, 0.99), 2),
        "noqos_peak_engine_queue": off_peak_q,
        "qos_preemptions": int(on_stats.get("qos_preemptions_total", 0)),
        "unit": ("victim per-token ITL over the storm window; open-loop "
                 "seeded-exponential arrivals at overload_x the measured "
                 "closed-loop capacity; CPU stand-in ratios"),
    }


def bench_prefix_affinity(families: int = 5, per_family: int = 4,
                          prefix_bytes: int = 192,
                          seed: int = 17) -> dict:
    """Prefix-affinity routing vs smooth-WRR on a shared-prefix
    workload, 2 replicas behind the Router: the replica prefix caches
    (block registry, PR 6) only pay off when the router sends a
    request WHERE its prefix lives.  Reported: summed
    ``prefix_block_hits_total`` and tokens saved, both routers, plus a
    seeded replica kill mid-run (chaos satellite): shed/failed
    requests stay explicit (never hang) and affinity re-routes the
    dead replica's families to the survivor."""
    import string
    import threading

    from kubeflow_tpu.chaos import FaultPlan
    from kubeflow_tpu.serving.controller import Router
    from kubeflow_tpu.serving.server import ModelServer
    from kubeflow_tpu.serving.storage import register_mem
    from kubeflow_tpu.serving.text import TextGenerator
    from kubeflow_tpu.serving.traffic import TrafficPlane

    cfg = _paged_stand_in()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    ref = register_mem("bench-affinity", (cfg, params))
    rng = np.random.default_rng(seed)
    letters = np.array(list(string.ascii_lowercase))
    fam_prefix = ["".join(rng.choice(letters, size=prefix_bytes))
                  for _ in range(families)]
    prompts = [fam_prefix[f] + f" tail {f}-{j} " + "y" * 8
               for j in range(per_family) for f in range(families)]
    # SHUFFLED arrival order: real shared-prefix traffic interleaves
    # tenants' sessions — an ordered sweep can alias family -> replica
    # under round-robin and hand WRR accidental affinity
    prompts = [prompts[i] for i in rng.permutation(len(prompts))]

    mcfg = dict(params_ref=ref, tokenizer="bytes", num_slots=4,
                decode_chunk=2, block_size=16, num_blocks=256,
                prefix_cache=True, min_prefix=16, max_new_tokens=8)

    def run(affinity: bool, chaos: bool = False):
        servers = []
        for i in range(2):
            srv = ModelServer()
            srv.register(TextGenerator("m", dict(mcfg)))
            srv.start()
            servers.append(srv)
        router = Router(activate=lambda: None)
        router.set_backends([s.url for s in servers])
        if affinity:
            router.set_traffic(TrafficPlane({}, affinity_block=16))
        plan = FaultPlan(seed).replica_kill_mid_storm(
            world=2, at=0.0) if chaos else None
        killed: list[int] = []
        statuses: list[int] = []
        lock = threading.Lock()
        try:
            if plan is not None:
                plan.activate()
            threads = []

            def one(p: str):
                st, _ = _http_post(
                    router.url + "/openai/v1/completions",
                    {"model": "m", "prompt": p, "max_tokens": 8},
                    timeout=120)
                with lock:
                    statuses.append(st)

            for k, p in enumerate(prompts):
                if plan is not None and k == len(prompts) // 3:
                    for idx in plan.due_replica_kills():
                        servers[idx].stop()  # abrupt: mid-run death
                        killed.append(idx)
                th = threading.Thread(target=one, args=(p,), daemon=True)
                th.start()
                threads.append(th)
                time.sleep(0.01)
            hung = 0
            for th in threads:
                th.join(timeout=300)
                hung += int(th.is_alive())
            hits = saved = 0
            for i, srv in enumerate(servers):
                if i in killed:
                    continue
                for eng in srv.engines().values():
                    hits += eng.stats()["prefix_block_hits_total"]
                    saved += eng.prefix_tokens_saved
            return hits, saved, statuses, hung, killed, router
        finally:
            router.stop()
            for i, srv in enumerate(servers):
                if i not in killed:
                    srv.stop()

    wrr_hits, wrr_saved, _, _, _, _ = run(affinity=False)
    aff_hits, aff_saved, _, _, _, _ = run(affinity=True)
    ch_hits, _ch_saved, ch_status, ch_hung, ch_killed, _ = run(
        affinity=True, chaos=True)
    ch_ok = sum(1 for s in ch_status if s == 200)
    return {
        "metric": "prefix_affinity_block_hits_vs_wrr",
        "model": f"{llamalib.num_params(cfg) / 1e6:.0f}M",
        "families": families, "per_family": per_family,
        "prefix_bytes": prefix_bytes, "replicas": 2,
        "wrr_prefix_block_hits": int(wrr_hits),
        "affinity_prefix_block_hits": int(aff_hits),
        "hit_ratio": round(aff_hits / max(wrr_hits, 1), 2),
        "wrr_prefix_tokens_saved": int(wrr_saved),
        "affinity_prefix_tokens_saved": int(aff_saved),
        "chaos_killed_replica": ch_killed,
        "chaos_ok": ch_ok,
        "chaos_non_200": len(ch_status) - ch_ok,
        "chaos_hung": ch_hung,
        "chaos_survivor_prefix_block_hits": int(ch_hits),
    }


def _backend_or_skip(metric: str) -> None:
    """PR 2 convention (bench.py::_devices_or_skip): probe the default
    backend in a BOUNDED subprocess so a registered-but-dead axon/TPU
    plugin costs a timeout, not a hang; fall back to CPU; and if even
    CPU is unusable, print ONE parseable skipped row in the driver's
    schema and exit 0 — a bench that cannot run records that fact, not
    a stack trace."""
    import os
    import subprocess

    err = "default backend probe failed"
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, timeout=PROBE_TIMEOUT_S, text=True)
            ok = probe.returncode == 0
            err = (probe.stderr or "").strip().splitlines()[-1:] or [err]
            err = err[0]
        except subprocess.TimeoutExpired:
            ok = False
            err = f"backend init exceeded {PROBE_TIMEOUT_S:.0f}s"
        if not ok:
            jax.config.update("jax_platforms", "cpu")
    try:
        jax.devices()
    except Exception:  # noqa: BLE001 — no backend at all
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": f"skipped: no usable jax backend ({err})"[:200],
            "skipped": True,
        }), flush=True)
        raise SystemExit(0)


def _paged_stand_in() -> "llamalib.LlamaConfig":
    """~30M-param CPU stand-in for the paged-capacity row: decode is
    weight-stream/dispatch bound at these widths (the TPU's HBM-bill
    cost structure), so widening the pool is nearly free while the KV
    MEMORY bill — the thing paging changes — stays the contended
    resource."""
    return llamalib.LlamaConfig(
        vocab_size=2048, hidden_size=512, intermediate_size=1408,
        num_layers=8, num_heads=8, num_kv_heads=8, head_dim=64,
        max_seq_len=512, remat=False, scan_layers=True,
        dtype=jnp.float32)


def bench_paged_capacity(n_conversations: int = 12, block_size: int = 32,
                         new_tokens: int = 32, decode_chunk: int = 8,
                         seed: int = 9) -> dict:
    """ISSUE 6's headline row: concurrent mixed-length conversations at
    EQUAL KV MEMORY, slot pool vs paged pool.

    The budget is fixed at 4 slots x max_seq_len tokens of KV.  The
    slot-pool baseline can host exactly 4 conversations regardless of
    their length — the rest queue behind whole conversations, and every
    mid-stream re-admission's monolithic prefill stalls the live decode
    (those spikes ARE its ITL p99).  The paged engine spends the same
    bytes as blocks: a mixed-length workload fits ~3x the conversations
    live, admission happens once up front, and steady decode runs
    uninterrupted.  Reported: max live conversations (sampled from
    slots_live) and per-token decode ITL p99 (first token per request
    excluded — queue wait is TTFT, not ITL).

    A second sub-row measures PREFIX SHARING on partially-overlapping
    prompts (three prompt families, members diverging mid-prefix):
    block-granular sharing serves every family from one pool (full
    blocks by refcount + COW forks), where whole-segment LCP is capped
    by its segment rows — fewer rows than families leaves whole
    families unshared."""
    from kubeflow_tpu.serving.continuous import ContinuousEngine

    cfg = _paged_stand_in()
    model = llamalib.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(seed)
    base_slots = 4  # the KV budget: base_slots * max_seq_len tokens
    budget_tokens = base_slots * cfg.max_seq_len
    lens = rng.integers(24, 64, size=n_conversations)
    prompts = [rng.integers(1, cfg.vocab_size, size=int(n)).tolist()
               for n in lens]

    def run(engine) -> tuple[int, list[float]]:
        """(max live slots, per-token decode ITLs in ms)."""
        try:
            engine.generate(prompts[0][:24], max_new_tokens=decode_chunk)
            reqs = [engine.submit(p, max_new_tokens=new_tokens)
                    for p in prompts]
            seen = [0] * len(reqs)
            arrivals: list[list[tuple[float, int]]] = [[] for _ in reqs]
            max_live = 0
            while not all(r.done.is_set() for r in reqs):
                now = time.perf_counter()
                for i, r in enumerate(reqs):
                    n = len(r.tokens)
                    if n > seen[i]:
                        arrivals[i].append((now, n))
                        seen[i] = n
                max_live = max(max_live,
                               engine.stats()["slots_live"])
                time.sleep(0.002)
            for r in reqs:
                r.wait(600)
            itls: list[float] = []
            for arr in arrivals:
                # first arrival = TTFT (queue wait + prefill): excluded
                for (t0, n0), (t1, n1) in zip(arr, arr[1:]):
                    itls.extend([(t1 - t0) / (n1 - n0) * 1e3]
                                * (n1 - n0))
            return max_live, itls
        finally:
            engine.stop()

    base_live, base_itls = run(ContinuousEngine(
        cfg, params, num_slots=base_slots, decode_chunk=decode_chunk,
        pipeline_depth=2, prefix_cache=False))
    paged_live, paged_itls = run(ContinuousEngine(
        cfg, params, num_slots=n_conversations,
        decode_chunk=decode_chunk, pipeline_depth=2, prefix_cache=False,
        block_size=block_size,
        num_blocks=budget_tokens // block_size))

    # -- prefix-sharing sub-row: partially-overlapping prompt families --
    # each family: a seed prompt, a BRANCH diverging mid-prefix, and a
    # CONTINUATION of the branch.  Whole-segment LCP shares only what a
    # segment row holds (the family prefix): the branch's own suffix
    # never becomes shareable, so the continuation re-prefills it.
    # Block sharing matches the branch's retired BLOCKS directly (full
    # blocks by refcount + a COW fork at the divergence), so the
    # continuation shares nearly the whole branch.
    import dataclasses as _dc

    families = 3
    shared_prompts = []
    for _ in range(families):
        prefix = rng.integers(1, cfg.vocab_size, size=96).tolist()
        branch = (prefix[:80]
                  + rng.integers(1, cfg.vocab_size, size=64).tolist())
        cont = branch + rng.integers(1, cfg.vocab_size, size=24).tolist()
        shared_prompts += [
            prefix + rng.integers(1, cfg.vocab_size, size=24).tolist(),
            branch, cont]

    paged_eng = ContinuousEngine(
        cfg, params, num_slots=4, decode_chunk=decode_chunk,
        prefix_cache=True, min_prefix=32, block_size=block_size,
        num_blocks=budget_tokens // block_size)
    try:
        for p in shared_prompts:
            paged_eng.generate(p, max_new_tokens=8, timeout=600)
        paged_saved = paged_eng.prefix_tokens_saved
        paged_block_hits = paged_eng.stats()["prefix_block_hits_total"]
        cow = paged_eng.stats()["kv_blocks_cow_copies_total"]
    finally:
        paged_eng.stop()
    # whole-segment LCP economy: 2 segment rows for 3 families — the
    # row limit the block pool does not have
    seg_eng = ContinuousEngine(
        _dc.replace(cfg, max_seq_len=192), params, num_slots=4,
        decode_chunk=decode_chunk, prefix_cache=False,
        prefix_segments=2, segment_len=256, min_prefix=32)
    try:
        for p in shared_prompts:
            seg_eng.generate(p, max_new_tokens=8, timeout=600)
        seg_shared = seg_eng.segment_tokens_shared
    finally:
        seg_eng.stop()

    return {
        "metric": "paged_kv_concurrent_capacity",
        "model": f"{llamalib.num_params(cfg) / 1e6:.0f}M",
        "kv_budget_tokens": budget_tokens, "block_size": block_size,
        "conversations": n_conversations, "new_tokens": new_tokens,
        "decode_chunk": decode_chunk,
        "slot_pool_max_live": base_live,
        "paged_max_live": paged_live,
        "concurrency_ratio": round(paged_live / max(base_live, 1), 2),
        "slot_pool_itl_p50_ms": round(_pct(base_itls, 0.5), 2),
        "slot_pool_itl_p99_ms": round(_pct(base_itls, 0.99), 2),
        "paged_itl_p50_ms": round(_pct(paged_itls, 0.5), 2),
        "paged_itl_p99_ms": round(_pct(paged_itls, 0.99), 2),
        "itl_p99_ratio": round(
            _pct(paged_itls, 0.99) / max(_pct(base_itls, 0.99), 1e-9), 3),
        "prefix_overlap_paged_tokens_saved": int(paged_saved),
        "prefix_overlap_paged_block_hits": int(paged_block_hits),
        "prefix_overlap_cow_copies": int(cow),
        "prefix_overlap_segment_tokens_shared": int(seg_shared),
        "prefix_share_ratio_vs_segments": round(
            paged_saved / max(seg_shared, 1), 2),
    }


def main() -> None:
    print(json.dumps(bench_decode(batch=8, prompt_len=128, new_tokens=64)),
          flush=True)
    for chunk in (8, 16, 32):
        print(json.dumps(bench_continuous(
            batch=8, prompt_len=128, new_tokens=64, decode_chunk=chunk)),
            flush=True)
    print(json.dumps(bench_continuous(
        batch=8, prompt_len=128, new_tokens=64, decode_chunk=16,
        quant=True)), flush=True)
    print(json.dumps(bench_continuous(
        batch=8, prompt_len=128, new_tokens=64, decode_chunk=16,
        moe=True)), flush=True)
    # long prompt + few new tokens isolates ADMISSION cost (what the
    # prefix cache removes); with many new tokens the row would mostly
    # measure decode, which prefix reuse cannot and should not change
    print(json.dumps(bench_prefix_cache(prompt_len=896, new_tokens=4)),
          flush=True)
    print(json.dumps(bench_shared_prefix()), flush=True)
    print(json.dumps(bench_chunked_prefill_stall()), flush=True)
    print(json.dumps(bench_speculative()), flush=True)
    print(json.dumps(bench_paged_capacity()), flush=True)
    print(json.dumps(bench_migration()), flush=True)
    print(json.dumps(bench_tiered_admission()), flush=True)
    print(json.dumps(bench_traffic_storm()), flush=True)
    print(json.dumps(bench_prefix_affinity()), flush=True)
    print(json.dumps(bench_bert(batch=8, seq=128)), flush=True)


if __name__ == "__main__":
    if "paged" in sys.argv[1:]:
        # standalone paged-capacity row with the PR 2 degradation
        # contract: bounded probe, CPU fallback, skipped row + rc 0
        _backend_or_skip("paged_kv_concurrent_capacity")
        print(json.dumps(bench_paged_capacity()), flush=True)
    elif "migration-child" in sys.argv[1:]:
        # the prefill-tier subprocess bench_migration spawns
        _migration_child(sys.argv[sys.argv.index("migration-child") + 1])
    elif "migration" in sys.argv[1:]:
        # standalone disaggregation row, same degradation contract
        _backend_or_skip("disaggregated_decode_itl_under_admission_storm_ms")
        print(json.dumps(bench_migration()), flush=True)
    elif "traffic" in sys.argv[1:]:
        # standalone traffic-plane rows (ISSUE 9), same contract
        _backend_or_skip("qos_storm_gold_itl_p99_ms")
        print(json.dumps(bench_traffic_storm()), flush=True)
        print(json.dumps(bench_prefix_affinity()), flush=True)
    else:
        main()
