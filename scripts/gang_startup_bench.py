"""Gang-startup latency p50 — the second headline BASELINE metric.

Launches N JaxJobs on a LocalPlatform, collects each job's
``status.gang_startup_seconds`` (apply -> every rank past its first global
collective, measured by the controller from per-pod barrier stamps), and
prints the percentile summary as one JSON line.

Usage: JAX_PLATFORMS=cpu python scripts/gang_startup_bench.py [N] [workers]
Record the p50 in BASELINE.md next to the throughput number.
"""

from __future__ import annotations

import json
import statistics
import sys
import tempfile

sys.path.insert(0, ".")


def main() -> None:
    n_jobs = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 2

    from kubeflow_tpu.runtime.platform import LocalPlatform
    from kubeflow_tpu.sdk.client import TrainingClient

    samples: list[float] = []
    with LocalPlatform(
        num_hosts=max(workers, 2), chips_per_host=4,
        root_dir=tempfile.mkdtemp(prefix="gangbench-"),
    ) as platform:
        client = TrainingClient(platform)
        for i in range(n_jobs):
            job = client.train(
                name=f"gang-{i}",
                entrypoint="kubeflow_tpu.models.mnist:train_main",
                num_workers=workers,
                env={"KFT_STEPS": "1", "KFT_BATCH": "8"},
                timeout=180,
            )
            gs = job.status.gang_startup_seconds
            assert gs is not None and gs > 0, job.status
            samples.append(gs)
            print(f"# job {i}: gang_startup={gs:.3f}s", file=sys.stderr)
            client.delete_job(f"gang-{i}")

    samples.sort()
    print(json.dumps({
        "metric": "gang_startup_p50_seconds",
        "value": round(statistics.median(samples), 3),
        "unit": f"s (n={n_jobs}, workers={workers}, local CPU runtime)",
        "p90": round(samples[int(0.9 * (len(samples) - 1))], 3),
        "min": round(samples[0], 3),
        "max": round(samples[-1], 3),
    }))


if __name__ == "__main__":
    main()
