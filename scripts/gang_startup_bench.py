"""Gang-startup latency p50 — the second headline BASELINE metric.

Three measurements (one JSON line each):

1. ``gang_startup_p50_seconds`` (cold): N JaxJobs, fresh compile every
   time — apply -> every rank past its first global collective.
2. ``gang_startup_warm_p50_seconds``: same jobs with a SHARED persistent
   XLA compilation cache (``KFT_COMPILE_CACHE`` -> runtime/bootstrap.py):
   job 0 fills the cache, jobs 1..N-1 measure the warm path — what every
   gang RESTART pays on a real slice, where a 7B compile is minutes.
3. ``restart_to_resume_p50_seconds``: SIGKILL a live worker of a
   checkpointing job (warm cache) and measure kill -> restarted gang's
   resume metric — the end-to-end recovery latency (BASELINE metric #2's
   missing warm path, r3 verdict item 5).

Usage: JAX_PLATFORMS=cpu python scripts/gang_startup_bench.py [N] [workers]
Record the p50s in BASELINE.md next to the throughput number.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, ".")

from kubeflow_tpu.utils.stats import percentiles as _percentiles  # noqa: E402


def measure_startups(client, n_jobs, workers, env, prefix) -> list[float]:
    samples = []
    for i in range(n_jobs):
        name = f"{prefix}-{i}"
        job = client.train(
            name=name,
            entrypoint="kubeflow_tpu.models.mnist:train_main",
            num_workers=workers,
            env={"KFT_STEPS": "1", "KFT_BATCH": "8", **env},
            timeout=180,
        )
        gs = job.status.gang_startup_seconds
        assert gs is not None and gs > 0, job.status
        samples.append(gs)
        print(f"# {name}: gang_startup={gs:.3f}s", file=sys.stderr)
        client.delete_job(name)
    return samples


def _resume_metric_ts(root: str, after: float) -> float:
    """Earliest metrics.jsonl ``resume_step`` > 0 stamped after ``after``
    anywhere under the platform root (the restarted coordinator's resume
    marker, train/llm.py)."""
    best = None
    for dirpath, _, names in os.walk(root):
        if "metrics.jsonl" not in names:
            continue
        with open(os.path.join(dirpath, "metrics.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (rec.get("name") == "resume_step" and rec.get("value", 0)
                        and rec.get("ts", 0) > after):
                    best = rec["ts"] if best is None else min(best, rec["ts"])
    return best


class _PodWatcher:
    """Polls pod statuses to timestamp the recovery phases: old-pod
    failure detection, teardown completion (old incarnation gone), new
    incarnation spawn + gang barrier."""

    def __init__(self, store, job_name):
        import threading

        self.store = store
        self.job = job_name
        self.failed_at = None      # first old pod observed FAILED
        self.gone_at = None        # all old pods deleted
        self.new_start = None      # first new pod start_time
        self.new_barrier = None    # last new pod barrier_time
        self._uids = {}
        for pod in store.list("Pod"):
            if pod.metadata.name.startswith(self.job + "-"):
                self._uids[pod.metadata.name] = pod.metadata.uid
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            now = time.time()
            seen = {}
            for pod in self.store.list("Pod"):
                if not pod.metadata.name.startswith(self.job + "-"):
                    continue
                seen[pod.metadata.name] = pod
            old_alive = False
            barriers = []
            for name, uid in self._uids.items():
                pod = seen.get(name)
                if pod is not None and pod.metadata.uid == uid:
                    old_alive = True
                    if (self.failed_at is None
                            and str(pod.status.phase) == "PodPhase.FAILED"):
                        self.failed_at = now
            if not old_alive and self.gone_at is None and self.failed_at:
                self.gone_at = now
            for name, pod in seen.items():
                if pod.metadata.uid == self._uids.get(name):
                    continue  # old incarnation
                if pod.status.start_time:
                    if (self.new_start is None
                            or pod.status.start_time < self.new_start):
                        self.new_start = pod.status.start_time
                if pod.status.barrier_time:
                    barriers.append(pod.status.barrier_time)
            if barriers and len(barriers) == len(self._uids):
                self.new_barrier = max(barriers)
            self._stop.wait(0.005)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)


def _first_loss_ts(root: str, after: float) -> float:
    best = None
    for dirpath, _, names in os.walk(root):
        if "metrics.jsonl" not in names:
            continue
        with open(os.path.join(dirpath, "metrics.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("name") == "loss" and rec.get("ts", 0) > after:
                    best = rec["ts"] if best is None else min(best, rec["ts"])
    return best


def measure_restart_resume(platform, client, n, workers, cache):
    samples = []
    phase_rows = []
    root = platform.root_dir
    for i in range(n):
        name = f"restart-{i}"
        ckpt = os.path.join(root, f"{name}-ckpt")
        client.train(
            name=name,
            entrypoint="kubeflow_tpu.train.llm:train_main",
            num_workers=workers,
            env={
                "KFT_STEPS": "30", "KFT_BATCH": "8", "KFT_SEQ_LEN": "16",
                "KFT_CKPT_DIR": ckpt, "KFT_SAVE_EVERY": "2",
                "KFT_LOG_EVERY": "2", "KFT_COMPILE_CACHE": cache,
            },
            backoff_limit=2, wait=False,
        )
        deadline = time.time() + 120
        while time.time() < deadline:
            steps = [d for d in (os.listdir(ckpt) if os.path.isdir(ckpt)
                                 else []) if d.isdigit()]
            if steps:
                break
            time.sleep(0.1)
        assert steps, "no checkpoint before the kill"
        watcher = _PodWatcher(platform.store, name)
        pod = platform.store.get("Pod", f"{name}-worker-{workers - 1}")
        t_kill = time.time()
        os.kill(pod.status.pid, signal.SIGKILL)
        client.wait_for_job_conditions(name, timeout=300)
        watcher.stop()
        ts = _resume_metric_ts(root, t_kill)
        assert ts is not None, "no resume marker after the kill"
        loss_ts = _first_loss_ts(root, t_kill)
        ph = {
            "detect_s": (watcher.failed_at or t_kill) - t_kill,
            "teardown_s": ((watcher.gone_at or t_kill)
                           - (watcher.failed_at or t_kill)),
            "respawn_s": ((watcher.new_start or 0)
                          - (watcher.gone_at or t_kill)
                          if watcher.new_start else None),
            "rendezvous_s": ((watcher.new_barrier - watcher.new_start)
                             if watcher.new_barrier and watcher.new_start
                             else None),
            "trainer_init_s": (ts - watcher.new_barrier
                               if watcher.new_barrier else None),
            "first_step_s": (loss_ts - ts) if loss_ts else None,
        }
        phase_rows.append(ph)
        samples.append(ts - t_kill)
        print(f"# {name}: restart_to_resume={ts - t_kill:.3f}s phases=" +
              json.dumps({k: (round(v, 3) if v is not None else None)
                          for k, v in ph.items()}),
              file=sys.stderr)
        client.delete_job(name)
    return samples, phase_rows


def main() -> None:
    n_jobs = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    workers = int(sys.argv[2]) if len(sys.argv) > 2 else 2

    from kubeflow_tpu.runtime.platform import LocalPlatform
    from kubeflow_tpu.sdk.client import TrainingClient

    root = tempfile.mkdtemp(prefix="gangbench-")
    cache = os.path.join(root, "compile-cache")
    with LocalPlatform(
        num_hosts=max(workers, 2), chips_per_host=4, root_dir=root,
    ) as platform:
        client = TrainingClient(platform)
        cold = measure_startups(client, n_jobs, workers, {}, "cold")
        # job warm-0 fills the shared cache; the rest ride it
        warm_all = measure_startups(
            client, n_jobs + 1, workers, {"KFT_COMPILE_CACHE": cache},
            "warm")
        warm = warm_all[1:]
        restart, phases = measure_restart_resume(
            platform, client, max(8, n_jobs // 3), workers, cache)

    base = f"(n={n_jobs}, workers={workers}, local CPU runtime)"
    print(json.dumps({
        "metric": "gang_startup_p50_seconds",
        "unit": f"s {base}", **_percentiles(cold)}))
    print(json.dumps({
        "metric": "gang_startup_warm_p50_seconds",
        "unit": f"s {base}, shared persistent compile cache",
        **_percentiles(warm)}))
    med_phase = {}
    for key in phases[0]:
        vals = sorted(v for p in phases for v in [p[key]] if v is not None)
        med_phase[key] = round(vals[len(vals) // 2], 3) if vals else None
    print(json.dumps({
        "metric": "restart_to_resume_p50_seconds",
        "unit": f"s (kill -> resume marker, workers={workers})",
        **_percentiles(restart),
        "phase_p50": med_phase}))


if __name__ == "__main__":
    main()
