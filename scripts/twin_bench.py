"""Fleet-scale digital twin runner (ISSUE 20): the seeded scenario
catalog from the command line.

Every scenario is a virtual-clock discrete-event run whose *decisions*
come from the real production policy objects (router pick + circuits +
retry budget, the QoS door, ``decide``/``tick``) and whose *physics*
(engine service time, network, cold starts) is modeled from the r17
phase calibration.  No jax, no threads, no wall-clock dependence: a
90-second 500-replica day replays in about a second of wall and two
runs with the same seed print byte-identical rows.

Prints one JSON row per scenario in the perf_sweep.py driver schema
(``metric``/``value`` + the full score dict) — the byte-stable
serialization of the score is the regression artifact: diff it across
commits to see a policy change's fleet-scale blast radius before it
ships.

PR 2 convention: a scenario that cannot run prints ONE parseable
skipped row and the bench still exits 0 — the driver records the fact,
not a stack trace.

Usage::

    python scripts/twin_bench.py                     # whole catalog
    python scripts/twin_bench.py --scenario chaos_fleet --seed 7
    python scripts/twin_bench.py --scenario diurnal --replicas 500
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

from kubeflow_tpu.sim import SCENARIOS, run_scenario, score_json  # noqa: E402


def bench_scenario(name: str, seed: int,
                   replicas: int | None) -> tuple[str, float]:
    t0 = time.perf_counter()
    score = run_scenario(name, seed=seed, replicas=replicas)
    wall = time.perf_counter() - t0
    slo = score.get("slo_attainment", {})
    row = {
        "metric": f"twin_{name}",
        "value": min(slo.values()) if slo else 0.0,
        "unit": "worst-class slo attainment",
        "seed": seed,
        "wall_s": round(wall, 3),
        "events_per_wall_s": round(score["events"] / max(wall, 1e-9)),
        "score": json.loads(score_json(score)),
    }
    return json.dumps(row, sort_keys=True), wall


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", default="all",
                    choices=["all", *sorted(SCENARIOS)],
                    help="one catalog row, or the whole catalog")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=None,
                    help="override the scenario's fleet scale")
    args = ap.parse_args()

    names = sorted(SCENARIOS) if args.scenario == "all" \
        else [args.scenario]
    total_wall = 0.0
    for name in names:
        try:
            row, wall = bench_scenario(name, args.seed, args.replicas)
            total_wall += wall
            print(row, flush=True)
        except Exception as exc:  # noqa: BLE001 — skipped row, rc 0
            print(json.dumps({
                "metric": f"twin_{name}",
                "value": 0.0,
                "unit": f"skipped: {type(exc).__name__}: {exc}"[:200],
                "skipped": True,
            }), flush=True)
    print(json.dumps({
        "metric": "twin_catalog_wall_s",
        "value": round(total_wall, 3),
        "unit": "s",
        "scenarios": len(names),
    }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
