"""Perf sweep on the real chip: remat x batch x attention_impl x seq.

Round-2 verdict weak #2: the benched config was never tuned.  This script
measures tokens/sec/chip (and MFU) for a grid of candidate configs so
``__graft_entry__._bench_model`` / ``bench.py`` can be set to the winner,
with numbers recorded in PERF.md.

Usage:  python scripts/perf_sweep.py [--quick]
Prints one JSON line per config; safe to ^C between configs.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import jax

sys.path.insert(0, ".")

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.parallel import sharding as shardlib
from kubeflow_tpu.train import data as datalib
from kubeflow_tpu.train import trainer as trainlib

WARMUP = 3
MEASURED = 8


def measure(model_cfg: llamalib.LlamaConfig, batch: int, seq: int) -> dict:
    devices = jax.devices()
    cfg = trainlib.TrainConfig(
        model=model_cfg,
        mesh_axes={"data": len(devices)} if len(devices) > 1 else {},
        global_batch=batch,
        seq_len=seq,
        steps=WARMUP + MEASURED,
        warmup_steps=2,
        log_every=10_000,
    )
    t = trainlib.Trainer(cfg, devices=devices)
    source = datalib.SyntheticLm(
        batch, seq, model_cfg.vocab_size, process_index=0, process_count=1)
    state = t.init_state()
    step_fn = t.compiled_step()
    times = []
    with shardlib.shard_context(t.mesh):
        for step in range(WARMUP + MEASURED):
            arrays = {
                k: jax.device_put(v, t.batch_sharding)
                for k, v in source.local_batch(step).items()
            }
            t0 = time.perf_counter()
            state, out = step_fn(state, arrays)
            float(jax.device_get(out["loss"]))
            dt = time.perf_counter() - t0
            if step >= WARMUP:
                times.append(dt)
    times.sort()
    median = times[len(times) // 2]
    n = len(devices)
    tps_chip = batch * seq / median / n
    flops_tok = llamalib.flops_per_token(model_cfg, seq)
    kind = getattr(devices[0], "device_kind", "cpu").lower()
    peak = trainlib.PEAK_TFLOPS.get(kind, 0.0)
    mfu = tps_chip * flops_tok / (peak * 1e12) if peak else 0.0
    return {
        "tok_s_chip": round(tps_chip, 1),
        "mfu": round(mfu, 4),
        "median_step_s": round(median, 4),
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true", help="first 4 configs only")
    p.add_argument("--only", type=str, default=None,
                   help="comma-separated config names to run")
    args = p.parse_args()

    base = dict(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=16, num_heads=8, num_kv_heads=8, head_dim=128,
        max_seq_len=4096, scan_layers=True,
    )

    grid: list[tuple[str, dict, int, int]] = [
        # name, cfg overrides, batch, seq
        ("r1_baseline_remat_dense_b16", dict(remat=True, attention_impl="dense"), 16, 1024),
        ("noremat_dense_b16", dict(remat=False, attention_impl="dense"), 16, 1024),
        ("noremat_dense_b32", dict(remat=False, attention_impl="dense"), 32, 1024),
        ("noremat_dense_b64", dict(remat=False, attention_impl="dense"), 64, 1024),
        ("noremat_flash_b32", dict(remat=False, attention_impl="flash"), 32, 1024),
        ("noremat_dense_b16_s2048", dict(remat=False, attention_impl="dense"), 16, 2048),
        ("noremat_flash_b16_s2048", dict(remat=False, attention_impl="flash"), 16, 2048),
        ("remat_dense_b8_s4096", dict(remat=True, attention_impl="dense"), 8, 4096),
        ("remat_flash_b8_s4096", dict(remat=True, attention_impl="flash"), 8, 4096),
    ]
    if args.quick:
        grid = grid[:4]
    if args.only:
        names = set(args.only.split(","))
        grid = [g for g in grid if g[0] in names]

    for name, overrides, batch, seq in grid:
        cfg = llamalib.LlamaConfig(**{**base, **overrides})
        try:
            result = measure(cfg, batch, seq)
        except Exception as e:  # noqa: BLE001 — OOM etc.: the failure
            # is recorded in the result row and the sweep continues
            result = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(json.dumps({"config": name, "batch": batch, "seq": seq, **result}),
              flush=True)


if __name__ == "__main__":
    main()
