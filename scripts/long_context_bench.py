"""Long-context attention benchmark: Pallas flash vs XLA dense, fwd+bwd.

Substantiates the long-context claim (SURVEY §5 long-context row) with
measured numbers: per-step attention grad time over sequence lengths at a
fixed token budget (batch shrinks as seq grows, so each row does the same
non-attention work).  Prints one JSON line per (impl, seq).
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from kubeflow_tpu.ops.flash_attention import flash_attention  # noqa: E402

TOKEN_BUDGET = 16384  # batch * seq held constant
HEADS, HEAD_DIM = 8, 128
REPS = 10


def dense_ref(q, k, v):
    _, s, _, _ = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask[None, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def bench(fn, q, k, v) -> float:
    f = jax.jit(jax.grad(
        lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
        argnums=(0, 1, 2)))
    out = f(q, k, v)
    jax.device_get(out[0][0, 0, 0, 0])  # sync (axon-safe)
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = f(q, k, v)
    jax.device_get(out[0][0, 0, 0, 0])
    return (time.perf_counter() - t0) / REPS


def main() -> None:
    for seq in (1024, 2048, 4096, 8192):
        b = max(1, TOKEN_BUDGET // seq)
        ks = [jax.random.normal(jax.random.PRNGKey(i), (b, seq, HEADS, HEAD_DIM),
                                jnp.bfloat16) for i in range(3)]
        rows = {}
        for name, fn in (("dense", dense_ref), ("flash", flash_attention)):
            try:
                rows[name] = bench(fn, *ks)
            except Exception as e:  # noqa: BLE001 — e.g. dense OOM at long seq
                rows[name] = None
                rows[f"{name}_error"] = f"{type(e).__name__}"
        speedup = (rows["dense"] / rows["flash"]
                   if rows.get("dense") and rows.get("flash") else None)
        print(json.dumps({
            "seq": seq, "batch": b,
            "dense_ms": round(rows["dense"] * 1e3, 2) if rows.get("dense") else None,
            "flash_ms": round(rows["flash"] * 1e3, 2) if rows.get("flash") else None,
            "flash_speedup": round(speedup, 2) if speedup else None,
            **{k: v for k, v in rows.items() if k.endswith("_error")},
        }), flush=True)


if __name__ == "__main__":
    main()
