"""MoE dispatch benchmark on the local chip: dense capacity dispatch vs
dropless ragged dispatch (masked-scan vs Pallas grouped-GEMM compute).

Single-chip (no expert axis -> no transport): isolates the expert-compute
cost, which is where the grouped kernel's block-sparsity pays.  Forward +
backward of one MoE layer; one JSON line per row.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from kubeflow_tpu.models import llama as llamalib
from kubeflow_tpu.models.moe import MoeMlp

B, S, H, M, E, K = 8, 1024, 1024, 2816, 8, 2


def bench(name: str, **cfg_kw) -> dict:
    cfg = llamalib.LlamaConfig(
        hidden_size=H, intermediate_size=M, num_heads=8, num_kv_heads=8,
        head_dim=128, moe_experts=E, moe_top_k=K, remat=False,
        **cfg_kw)
    moe = MoeMlp(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, H), jnp.bfloat16)
    params = nn.meta.unbox(moe.init(jax.random.PRNGKey(1), x)["params"])

    def loss(p, x):
        return (moe.apply({"params": p}, x).astype(jnp.float32) ** 2).mean()

    inner = 10  # steps per dispatch: the tunnel's ~10ms/dispatch floor
                # would otherwise swamp the layer's device time

    @jax.jit
    def window(p, x):
        def body(carry, _):
            l, g = jax.value_and_grad(loss)(p, x + carry)
            # consume the grads (sum of squares) so the backward survives DCE
            gsum = sum(jnp.sum(leaf.astype(jnp.float32) ** 2)
                       for leaf in jax.tree.leaves(g))
            return carry + jnp.bfloat16(l * 0), l + gsum
        _, losses = jax.lax.scan(body, jnp.bfloat16(0), None, length=inner)
        return losses.sum()

    out = window(params, x)
    float(jax.device_get(out))  # real host fetch: block_until_ready is
    reps = 3                    # unreliable on the remote-dispatch tunnel
    t0 = time.perf_counter()
    for _ in range(reps):
        out = window(params, x)
    float(jax.device_get(out))
    dt = (time.perf_counter() - t0) / (reps * inner)
    tokens = B * S
    return {
        "metric": "moe_layer_fwd_bwd",
        "impl": name,
        "tokens": tokens, "experts": E, "top_k": K,
        "hidden": H, "ffn": M,
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(tokens / dt, 1),
    }


def sweep() -> None:
    """Grouped-GEMM tuning sweep (r3 verdict item 9): tiling x accumulator
    dtype on the real chip.  Each candidate re-jits the ragged+grouped
    layer with the override installed."""
    from kubeflow_tpu.ops import grouped_matmul as gmmlib

    candidates = [
        (128, 128, 128, jnp.float32),   # r3 default
        (512, 512, 512, jnp.float32),
        (512, 1024, 1024, jnp.float32),
        (1024, 512, 1408, jnp.float32),
        (256, 1024, 704, jnp.float32),
        (512, 1024, 1024, jnp.bfloat16),
        (128, 128, 128, jnp.bfloat16),
    ]
    best = None
    for tm, tk, tn, acc in candidates:
        gmmlib.set_gmm_tiling(tm, tk, tn, acc_dtype=acc)
        name = f"grouped_t{tm}x{tk}x{tn}_{jnp.dtype(acc).name}"
        try:
            r = bench(name, moe_dispatch="ragged",
                      moe_ragged_compute="grouped")
        except Exception as e:  # noqa: BLE001 — VMEM OOM etc.: record, go on
            print(json.dumps({
                "metric": "moe_layer_fwd_bwd", "impl": name,
                "tiling": [tm, tk, tn], "acc_dtype": jnp.dtype(acc).name,
                "error": f"{type(e).__name__}: {str(e)[:200]}"}), flush=True)
            continue
        r["tiling"] = [tm, tk, tn]
        r["acc_dtype"] = jnp.dtype(acc).name
        print(json.dumps(r), flush=True)
        if best is None or r["ms_per_step"] < best["ms_per_step"]:
            best = r
    print(json.dumps({"metric": "moe_gmm_sweep_best", **{
        k: best[k] for k in ("impl", "ms_per_step", "tiling", "acc_dtype")}}),
        flush=True)


def train_step_bench() -> None:
    """End-to-end MoE LLM train step on one chip (not just the layer):
    an 8-layer MoE Llama (every-layer MoE, E=8 top-2; 16 layers crashes
    the tunnel's compile helper) through the real Trainer, dense capacity
    vs dropless ragged+grouped dispatch — the number that tells whether
    dropless is deployable as the default."""
    from kubeflow_tpu.train import trainer as trainlib

    import time as _time

    for name, kw in (
        ("dense_capacity", dict(moe_dispatch="dense")),
        ("ragged_grouped", dict(moe_dispatch="ragged",
                                moe_ragged_compute="grouped")),
    ):
        cfg = llamalib.LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_layers=8, num_heads=8, num_kv_heads=8, head_dim=128,
            max_seq_len=1024, attention_impl="flash", remat=True,
            moe_experts=8, moe_top_k=2, **kw)
        tcfg = trainlib.TrainConfig(
            model=cfg, global_batch=8, seq_len=1024, steps=16,
            log_every=8, aux_loss_coef=0.01)
        t = trainlib.Trainer(tcfg)
        out = []
        t.train(on_metrics=lambda m: out.append(m))
        m = out[-1]  # second window: warm steps only
        print(json.dumps({
            "metric": "moe_llama_train_tokens_per_sec_per_chip",
            "impl": name, "layers": 8, "experts": 8, "top_k": 2,
            "value": round(m.tokens_per_sec_per_chip, 1),
            "step_ms": round(m.step_time_s * 1e3, 1),
            "loss": round(m.loss, 3),
        }), flush=True)
        del t
        _time.sleep(1)


def main() -> None:
    if "--sweep" in sys.argv:
        sweep()
        return
    if "--train" in sys.argv:
        train_step_bench()
        return
    rows = [
        bench("dense_capacity_1.25", moe_dispatch="dense",
              moe_capacity_factor=1.25),
        bench("dense_capacity_2.0", moe_dispatch="dense",
              moe_capacity_factor=2.0),
        bench("ragged_masked", moe_dispatch="ragged",
              moe_ragged_compute="masked"),
        bench("ragged_grouped", moe_dispatch="ragged",
              moe_ragged_compute="grouped"),
    ]
    for r in rows:
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
