#!/usr/bin/env python
"""Platform lint entry point — the findings ratchet, as a script.

Thin wrapper over ``python -m kubeflow_tpu.analysis`` (one code path;
this file exists so CI configs and operators have a stable script name
next to the other scripts/):

    python scripts/platform_lint.py                  # ratchet check
    python scripts/platform_lint.py --update-baseline
    python scripts/platform_lint.py --json           # machine-readable + timing
    python scripts/platform_lint.py --changed        # findings in your diff only
    python scripts/platform_lint.py --all            # list frozen debt too
    python scripts/platform_lint.py --rule threads   # one concern only
    python scripts/platform_lint.py --rule persist   # torn-write commit protocol
    python scripts/platform_lint.py --self-test      # rule fixtures, no pytest

Exit 0: no findings above kubeflow_tpu/analysis/baseline.json (or
self-test green).
Exit 1: NEW findings — fix, pragma (``# analysis: ok <rule> — why``),
or re-freeze reviewed debt with --update-baseline; for --self-test, a
rule stopped firing on its true positive or fired on its near miss.
Exit 2: usage error.

``--rule`` takes rule names or group aliases (dispatch, hygiene,
locks, threads, protocol, persist).  ``--changed`` still parses the
WHOLE platform — the cross-module call graph needs every file to
resolve effects — but reports only findings in files your working tree
changed vs HEAD (plus untracked), which is the pre-commit loop.
``--self-test`` runs the built-in true-positive/near-miss fixture pair
per rule (analysis/selftest.py) — the lint binary validating itself in
tier-1 with no test framework.

The same check runs as tier-1 (tests/test_analysis.py::TestRatchet), so
every PR inherits it; tier-1 also asserts the whole-platform
parse+lint wall time stays under its budget, so the call-graph engine
can't quietly make every PR slower.  This script is the fast
pre-commit form.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
