"""Pipeline schedule probe: GPipe vs 1F1B — bubble fraction and
compiler-estimated memory at M in {4, 8, 16} microbatches.

Runs on the 8-device virtual CPU mesh (the multi-chip stand-in, SURVEY
§4c): measures per-tick useful-work fraction analytically from the
schedule tables, wall-clock per step on the mesh, and XLA's
memory_analysis() for both schedules — the observable the 1F1B memory
bound (in-flight ~P microbatches vs GPipe's M) shows up in.

Prints one JSON line per (schedule, M) row.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.parallel import mesh as meshlib  # noqa: E402
from kubeflow_tpu.parallel import pipeline as pipelib  # noqa: E402
from kubeflow_tpu.parallel import sharding as shardlib  # noqa: E402

P_STAGES = 4
LAYERS = 16  # divisible by P_STAGES x the largest interleave (4)
WIDTH = 256
BATCH = 32
STEPS = 10


def problem():
    k = jax.random.PRNGKey(0)
    kw, kh, kx, kt = jax.random.split(k, 4)
    ws = jax.random.normal(kw, (LAYERS, WIDTH, WIDTH)) * 0.1
    head = jax.random.normal(kh, (WIDTH, 8)) * 0.1
    x = jax.random.normal(kx, (BATCH, WIDTH))
    tgt = jax.random.normal(kt, (BATCH, 8))

    def block_apply(w, h):
        return jnp.tanh(h @ w)

    def loss_fn(hp, y, t):
        return ((y @ hp - t) ** 2).mean()

    return block_apply, loss_fn, ws, head, x, tgt


def bench(schedule: str, m: int, v: int = 1,
          persistent: bool = False) -> dict:
    """``persistent``: weights live PRE-PERMUTED in the interleaved
    layout across steps (the in-step permute and its ~2x temp bytes
    vanish; grads come back in the same layout, so a trainer adopting it
    must canonicalize at checkpoint/publish boundaries)."""
    block_apply, loss_fn, ws, head, x, tgt = problem()
    mesh = meshlib.build_mesh({"pipeline": P_STAGES, "data": 8 // P_STAGES})

    if schedule == "gpipe":
        def step(ws, hp, x, tgt):
            def loss(ws, hp):
                y = pipelib.gpipe(
                    block_apply, ws, x, mesh=mesh, num_microbatches=m)
                return loss_fn(hp, y, tgt)
            return jax.value_and_grad(loss, argnums=(0, 1))(ws, hp)
    else:
        perm = pipelib.interleave_permutation(LAYERS, P_STAGES, v)
        if persistent and v > 1:
            ws = jnp.take(ws, jnp.asarray(perm), axis=0)

        def step(ws, hp, x, tgt):
            # in-step permute (as in the trainer) unless persistent —
            # both variants measured so the layout cost is visible
            w_used = (ws if (v == 1 or persistent)
                      else jnp.take(ws, jnp.asarray(perm), axis=0))
            loss, (dws, dhead, dx) = pipelib.one_f_one_b(
                block_apply, loss_fn, w_used, hp, x, tgt,
                mesh=mesh, num_microbatches=m, interleave=v)
            return loss, dws

    with shardlib.shard_context(mesh):
        lowered = jax.jit(step).lower(ws, head, x, tgt)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        out = compiled(ws, head, x, tgt)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = compiled(ws, head, x, tgt)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / STEPS

    row = {
        "metric": "pipeline_schedule_probe",
        "schedule": (schedule if v == 1 else
                     f"{schedule}-v{v}" + ("-persist" if persistent else "")),
        "stages": P_STAGES,
        "interleave": v,
        "microbatches": m,
        "step_ms": round(dt * 1e3, 2),
        "temp_bytes_per_device": getattr(mem, "temp_size_in_bytes", None),
        "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
    }
    # Unified slot accounting (r3 ADVICE: the two schedules' fractions
    # must use the same units).  A "slot" is one microbatch-direction of
    # work at one stage; per stage a full step fills exactly 2M slots
    # (M fwd + M bwd) in EVERY schedule.  Capacity differs: GPipe runs a
    # fwd sweep then a bwd sweep — 2(M+P-1) one-slot ticks; 1F1B runs
    # M+2(P-1) two-slot ticks (each tick holds one fwd AND one bwd slot).
    # useful = filled / capacity = M/(M+P-1) vs M/(M+2(P-1)) — derived
    # from the same accounting, so the columns compare directly.
    if schedule == "1f1b":
        s = pipelib.schedule_1f1b(P_STAGES, m, v)
        ticks, slots_per_tick = s.ticks, 2
        filled = int((s.fwd >= 0).sum() + (s.bwd >= 0).sum())
        row["act_stash_microbatches"] = s.act_slots
        # wall ticks in STAGE units (a v-chunk tick is 1/v of a stage)
        row["stage_ticks"] = round(ticks / v, 2)
    else:
        ticks, slots_per_tick = 2 * (m + P_STAGES - 1), 1
        filled = 2 * m * P_STAGES
        row["act_stash_microbatches"] = m
        row["stage_ticks"] = ticks / 2  # fwd+bwd pairs
    row["ticks"] = ticks
    row["useful_fraction"] = round(
        filled / (slots_per_tick * ticks * P_STAGES), 3)
    return row


def main() -> None:
    for m in (4, 8, 16):
        for schedule, v, persist in (
                ("gpipe", 1, False), ("1f1b", 1, False),
                ("1f1b", 2, False), ("1f1b", 4, False),
                ("1f1b", 2, True), ("1f1b", 4, True)):
            print(json.dumps(bench(schedule, m, v, persist)), flush=True)


if __name__ == "__main__":
    main()
